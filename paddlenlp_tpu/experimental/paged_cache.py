"""Paged (block) KV cache + host-side block manager.

Counterpart of the reference's block-attention machinery: the CUDA block pool in
``csrc/gpu/append_attn/*`` (write_cache_with_rope, c16 cache) and the in-kernel
allocator ``csrc/gpu/step.cu`` (op ``step_paddle`` :316 — free/dispatch blocks,
preempt + recover). TPU-native split:

- device side: ONE pool tensor ``[L, 2, num_blocks, n_kv, block_size, H]``
  (kv-head-major so a Pallas BlockSpec can DMA one head's ``[block_size, H]``
  tile — the last two dims must be TPU-tileable);
  prefill/decode scatter new K/V into table-addressed slots
  (``lax`` scatter via ``.at[]``) and attention gathers whole block rows — static
  shapes, jit-compiled once;
- host side: ``BlockManager`` does the step.cu bookkeeping (free list, per-seq
  tables, allocate/extend/free, preemption candidates) in plain Python — the
  allocator runs between device steps, so there is no launch-latency reason to
  put it in-kernel as CUDA must.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKVPool", "BlockManager", "init_paged_pool", "write_kv_block", "gather_kv",
           "copy_blocks"]


@dataclasses.dataclass
class PagedKVPool:
    """Device-side pool: kv [L, 2, num_blocks, n_kv, block_size, head_dim].

    Quantized caches (the reference's c8/fp8 cache, ``csrc/gpu/append_attn/``
    c8 impls + ``predictor.py:775-791`` cachekv_int8) store ``kv`` as int8 /
    float8_e4m3 plus per-token-per-head ``scale`` [L, 2, nb, n_kv, bs, 1] —
    dequant happens at the attention read (in-kernel for the Pallas path)."""

    kv: jnp.ndarray
    scale: Optional[jnp.ndarray] = None

    @property
    def num_blocks(self) -> int:
        return self.kv.shape[2]

    @property
    def block_size(self) -> int:
        return self.kv.shape[4]

    @property
    def quantized(self) -> bool:
        return self.scale is not None


jax.tree_util.register_dataclass(PagedKVPool, data_fields=["kv", "scale"], meta_fields=[])

_QMAX = {"int8": 127.0, "fp8": 448.0}  # float8_e4m3 max normal


def init_paged_pool(config, num_blocks: int, block_size: int = 16, dtype=jnp.bfloat16,
                    quant: Optional[str] = None) -> PagedKVPool:
    n_kv = getattr(config, "num_key_value_heads", config.num_attention_heads)
    head_dim = getattr(config, "head_dim", config.hidden_size // config.num_attention_heads)
    shape = (config.num_hidden_layers, 2, num_blocks, n_kv, block_size, head_dim)
    if quant is None:
        return PagedKVPool(kv=jnp.zeros(shape, dtype=dtype))
    if quant not in _QMAX:
        raise ValueError(f"kv cache quant must be int8/fp8, got {quant!r}")
    qdtype = jnp.int8 if quant == "int8" else jnp.float8_e4m3fn
    return PagedKVPool(
        kv=jnp.zeros(shape, dtype=qdtype),
        scale=jnp.zeros(shape[:-1] + (1,), dtype=jnp.float32),
    )


def quantize_kv(x: jnp.ndarray, qdtype) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-token-per-head symmetric quant over the head dim.

    x [..., H] -> (q [..., H] in qdtype, scale [..., 1] fp32)."""
    qmax = _QMAX["int8" if qdtype == jnp.int8 else "fp8"]
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-6) / qmax
    q = x.astype(jnp.float32) / scale
    if qdtype == jnp.int8:
        q = jnp.clip(jnp.round(q), -127, 127)
    return q.astype(qdtype), scale


def write_kv_block(pool_layer: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   block_table: jnp.ndarray, start_pos,
                   scale_layer: Optional[jnp.ndarray] = None):
    """Scatter new tokens' K/V into the pool (one layer).

    pool_layer [2, num_blocks, K, bs, H]; k/v [T, K, H] for ONE sequence;
    block_table [max_blocks]; start_pos scalar — token i lands at logical position
    start_pos+i -> (block_table[(start_pos+i)//bs], (start_pos+i)%bs).
    With ``scale_layer`` [2, num_blocks, K, bs, 1] the pool is quantized: K/V are
    range-compressed per token+head on write. Returns pool_layer or
    (pool_layer, scale_layer)."""
    T = k.shape[0]
    bs = pool_layer.shape[3]
    pos = start_pos + jnp.arange(T)
    blocks = block_table[pos // bs]
    offs = pos % bs
    if scale_layer is not None:
        k, ks = quantize_kv(k, pool_layer.dtype)
        v, vs = quantize_kv(v, pool_layer.dtype)
        scale_layer = scale_layer.at[0, blocks, :, offs].set(ks)
        scale_layer = scale_layer.at[1, blocks, :, offs].set(vs)
    # advanced indices (blocks, offs) split by the kv-head slice: result rows
    # are [T, K, H], matching k/v
    pool_layer = pool_layer.at[0, blocks, :, offs].set(k.astype(pool_layer.dtype))
    pool_layer = pool_layer.at[1, blocks, :, offs].set(v.astype(pool_layer.dtype))
    if scale_layer is not None:
        return pool_layer, scale_layer
    return pool_layer


def gather_kv(pool_layer: jnp.ndarray, block_tables: jnp.ndarray,
              scale_layer: Optional[jnp.ndarray] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Gather per-sequence K/V views (one layer).

    pool_layer [2, num_blocks, K, bs, H]; block_tables [B, max_blocks] ->
    (k, v) each [B, max_blocks*bs, K, H]. Out-of-range table entries must point at
    a zeroed sentinel block; masking by context length happens in attention.
    Quantized pools dequantize on the gathered (per-sequence) view."""
    k = pool_layer[0][block_tables]  # [B, max_blocks, K, bs, H]
    v = pool_layer[1][block_tables]
    B, M, K, bs, H = k.shape
    if scale_layer is not None:
        ks = scale_layer[0][block_tables]  # [B, M, K, bs, 1]
        vs = scale_layer[1][block_tables]
        # dequantize to bf16: the quantized cache must not carry a LARGER
        # working set than the bf16 pool it replaces
        k = (k.astype(jnp.float32) * ks).astype(jnp.bfloat16)
        v = (v.astype(jnp.float32) * vs).astype(jnp.bfloat16)
    k = k.transpose(0, 1, 3, 2, 4).reshape(B, M * bs, K, H)
    v = v.transpose(0, 1, 3, 2, 4).reshape(B, M * bs, K, H)
    return k, v


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_blocks_plane(plane: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray) -> jnp.ndarray:
    return plane.at[:, :, dst].set(plane[:, :, src])


def copy_blocks(pool: PagedKVPool, pairs: Sequence[Tuple[int, int]]) -> PagedKVPool:
    """Copy whole KV blocks src -> dst across every layer (K and V planes).

    The copy-on-write primitive behind prefix caching: when a request's prompt
    is fully covered by cached blocks, the tail block must still absorb the
    re-prefilled last token — so it is duplicated into a private block first.
    Jitted with the pool donated so XLA scatters in place — an eager ``.at[]``
    would materialize a second full pool (transient 2x HBM) to copy one block.
    Functional semantics still order the copy before any later prefill/decode
    write that might recycle ``src``.

    The pair list is padded to the next power of two with ``(0, 0)`` identity
    copies of the zero sentinel block (real dsts are never block 0), so the
    full-pool scatter compiles for at most log2(max pairs) shapes instead of
    once per distinct COW count seen in the admission hot path."""
    if not pairs:
        return pool
    padded = 1
    while padded < len(pairs):
        padded *= 2
    pairs = list(pairs) + [(0, 0)] * (padded - len(pairs))
    src = jnp.asarray([s for s, _ in pairs], jnp.int32)
    dst = jnp.asarray([d for _, d in pairs], jnp.int32)
    kv = _copy_blocks_plane(pool.kv, src, dst)
    scale = None if pool.scale is None else _copy_blocks_plane(pool.scale, src, dst)
    return PagedKVPool(kv=kv, scale=scale)


class BlockManager:
    """Host-side allocator (the step.cu bookkeeping in Python).

    Block 0 is reserved as the zero sentinel for unused table slots.

    **Prefix caching** (``enable_prefix_cache=True``): every owned block carries
    a refcount, and full blocks of finished prompts are registered in a
    chained-hash index (``h_i = sha256(h_{i-1} || block_i tokens)`` — block-
    granular, content-addressed). ``allocate(..., token_ids=...)`` walks the
    chain and reuses the longest cached prefix of FULL blocks; the caller skips
    prefill for those tokens. Zero-ref cached blocks sit on an LRU list and are
    evicted only under allocation pressure, so the cache can never cause an
    admission failure the uncached allocator wouldn't have had: ``num_free``
    counts them as available.

    **Concurrency model**: lock-free by thread confinement — the manager is
    owned by the engine, which the serving stack drives from ONE loop thread
    (engine_loop.py); ``generate()`` callers are single-threaded by contract.
    Metrics/stats readers on HTTP threads only touch scalar counters
    (``cache_hits``/``num_free``/...), where a stale read is harmless. Do not
    add cross-thread mutation here; route it through the engine loop's
    command queue instead.
    """

    def __init__(self, num_blocks: int, block_size: int, max_blocks_per_seq: int,
                 enable_prefix_cache: bool = False):
        self.block_size = block_size
        self.max_blocks_per_seq = max_blocks_per_seq
        self.total_usable_blocks = num_blocks - 1
        self.free: List[int] = list(range(1, num_blocks))  # block 0 = sentinel
        self.tables: Dict[int, List[int]] = {}
        self.lengths: Dict[int, int] = {}
        self.enable_prefix_cache = enable_prefix_cache
        self.ref: Dict[int, int] = {}  # block -> #sequences referencing it
        self._index: Dict[int, int] = {}  # chained prefix hash -> block
        self._block_hash: Dict[int, int] = {}  # registered block -> its hash
        self._lru: "OrderedDict[int, None]" = OrderedDict()  # zero-ref cached blocks
        self._cow_pairs: List[Tuple[int, int]] = []  # (src, dst) device copies owed
        self._cache_epoch = 0  # bumped by clear_prefix_cache()
        self._seq_epoch: Dict[int, int] = {}  # seq -> epoch it was allocated in
        self.cache_hits = 0  # allocations that reused >=1 cached block
        self.cached_tokens_total = 0  # prompt tokens whose prefill was skipped
        self.evictions = 0  # cached blocks recycled under pressure
        # hierarchical cache (kv_host_tier.py): with a tier attached, LRU
        # evictions queue (hash, block) pairs here instead of dropping the
        # content; the engine drains them into one batched D2H spill BEFORE
        # any device launch can overwrite the recycled blocks
        self.host_tier = None
        self._pending_spills: List[Tuple[bytes, int]] = []

    @property
    def num_free(self) -> int:
        """Blocks available to an allocation: the free list plus zero-ref
        cached blocks (evictable on demand, so they ARE capacity)."""
        return len(self.free) + len(self._lru)

    @property
    def num_cached_blocks(self) -> int:
        """Blocks currently registered in the prefix index (shared or idle)."""
        return len(self._block_hash)

    def blocks_needed(self, n_tokens: int) -> int:
        return (n_tokens + self.block_size - 1) // self.block_size

    def can_allocate(self, n_tokens: int) -> bool:
        return self.blocks_needed(n_tokens) <= self.num_free

    def can_admit(self, n_tokens: int, token_ids=None, match=None,
                  salt: Optional[str] = None) -> bool:
        """Like :meth:`can_allocate`, but cached prefix blocks don't need fresh
        capacity — the scheduler admits a warm request a cold one must wait for.

        Pass a precomputed ``match`` (from :meth:`match_prefix`) to skip
        re-hashing the prompt; matched blocks that are idle on the LRU are
        subtracted from available capacity — they can't be both "no fresh
        block needed" AND "evictable free capacity" at once."""
        if match is None and token_ids is not None:
            match = self.match_prefix(token_ids, min(len(token_ids), n_tokens),
                                      salt=salt)
        matched = match[0] if match is not None else []
        need = self.blocks_needed(n_tokens) - len(matched)
        return need <= self.num_free - self._idle_count(matched)

    # ------------------------------------------------------------- prefix cache
    def _idle_count(self, blocks) -> int:
        """How many of ``blocks`` currently sit on the (counted-as-free) LRU."""
        return sum(1 for b in blocks if b in self._lru)

    def _chain_hashes(self, token_ids, nb_full: int, salt: Optional[str] = None):
        """Chained sha256 content digests of the first ``nb_full`` full blocks.

        Cryptographic on purpose: the index serves another prompt's KV on a
        key collision with no further check, so a non-collision-resistant
        hash would be a silent-wrong-output (and cross-request leak) channel.

        ``salt`` seeds the chain (multi-LoRA: the adapter_id) so two tenants
        with identical prompts but different adapters never share KV — a LoRA
        delta changes every hidden state, so cross-adapter cache hits would be
        silently wrong. ``salt=None`` keeps the historical hash values: the
        no-adapter cache population is untouched."""
        h = hashlib.sha256(salt.encode()).digest() if salt else b""
        bs = self.block_size
        arr = np.ascontiguousarray(
            np.asarray(token_ids[: nb_full * bs], dtype=np.int64))
        out = []
        for i in range(nb_full):
            h = hashlib.sha256(h + arr[i * bs: (i + 1) * bs].tobytes()).digest()
            out.append(h)
        return out

    def match_prefix(self, token_ids, n_tokens: int, salt: Optional[str] = None):
        """Longest cached full-block prefix of ``token_ids``.

        Returns ``(shared_blocks, n_cached_tokens, cow_src)``: blocks to attach
        by reference, tokens covered, and — when the match would cover the whole
        prompt (leaving nothing to prefill) — the tail block to copy-on-write
        instead of sharing, so the re-prefilled last token never mutates a
        shared block. Pure lookup: acquires nothing. ``salt`` must match the
        salt the blocks were registered under (see :meth:`_chain_hashes`)."""
        if not self.enable_prefix_cache:
            return [], 0, None
        bs = self.block_size
        nb_full = min(len(token_ids), n_tokens) // bs
        matched: List[int] = []
        for h in self._chain_hashes(token_ids, nb_full, salt=salt):
            b = self._index.get(h)
            if b is None:
                break
            matched.append(b)
        if not matched:
            return [], 0, None
        if len(matched) * bs == n_tokens:
            # full cover: keep >=1 token to prefill (the sampler needs logits
            # at the last prompt position) — COW the tail block
            return matched[:-1], n_tokens - 1, matched[-1]
        return matched, len(matched) * bs, None

    def _acquire(self, block: int):
        """Take a reference on a cached block (removing it from the LRU if idle)."""
        self.ref[block] = self.ref.get(block, 0) + 1
        self._lru.pop(block, None)

    def _release_block(self, block: int):
        r = self.ref.get(block, 0) - 1
        if r > 0:
            self.ref[block] = r
            return
        self.ref.pop(block, None)
        if block in self._block_hash:
            # zero-ref but cached: evictable, not free — most-recently-used last
            self._lru[block] = None
            self._lru.move_to_end(block)
        else:
            self.free.append(block)

    def _pop_block(self) -> int:
        """A fresh private block: free list first, else evict the LRU cached
        block (allocation pressure is the ONLY thing that shrinks the cache).
        With a host tier attached the evicted block's hash demotes instead of
        dying: it is queued for the engine's batched D2H spill and the tier
        keeps serving it to future prefix matches (:meth:`host_match`)."""
        if self.free:
            b = self.free.pop()
        else:
            b, _ = self._lru.popitem(last=False)
            h = self._block_hash.pop(b)
            self._index.pop(h, None)
            self.evictions += 1
            if self.host_tier is not None and self.host_tier.accepting:
                self._pending_spills.append((h, b))
        self.ref[b] = 1
        return b

    # ------------------------------------------------------------- host tier
    def attach_host_tier(self, tier):
        """Hang a :class:`~.kv_host_tier.HostKVTier` under the LRU: evictions
        demote to it, :meth:`host_match` extends prefix matches into it."""
        self.host_tier = tier

    def drain_pending_spills(self) -> List[Tuple[bytes, int]]:
        """(hash, block) pairs evicted since the last drain; cleared on read.
        The engine MUST consume these before dispatching any device work that
        writes the recycled blocks — the spill gather reads them in dispatch
        order (exactly the COW-pairs contract one method up)."""
        out, self._pending_spills = self._pending_spills, []
        return out

    def host_match(self, token_ids, n_tokens: int, salt: Optional[str] = None,
                   skip: int = 0) -> List[bytes]:
        """Chain hashes of the full-block prefix run that continues past the
        device match (``skip`` = blocks the device index already covered)
        and is resident in the host tier. Pure lookup: pops nothing — the
        engine calls :meth:`HostKVTier.take` only once it has device blocks
        allocated to promote into."""
        if (not self.enable_prefix_cache or self.host_tier is None
                or not self.host_tier.accepting):
            return []
        bs = self.block_size
        nb_full = min(len(token_ids), n_tokens) // bs
        if nb_full <= skip:
            return []
        out: List[bytes] = []
        for h in self._chain_hashes(token_ids, nb_full, salt=salt)[skip:]:
            if not self.host_tier.contains(h):
                break
            out.append(h)
        return out

    def register_promoted(self, blocks: Sequence[int], hashes: Sequence[bytes]):
        """Re-register just-promoted blocks in the device index (the other
        half of the resident-XOR move that :meth:`HostKVTier.take` started).
        Content-addressed exactly like :meth:`finish_seq_cached`: a hash or
        block already claimed is simply skipped."""
        for b, h in zip(blocks, hashes):
            if h not in self._index and b not in self._block_hash:
                self._index[h] = b
                self._block_hash[b] = h

    def drain_cow_pairs(self) -> List[Tuple[int, int]]:
        """(src, dst) block copies the caller owes the device pool (see
        :func:`copy_blocks`); cleared on read."""
        pairs, self._cow_pairs = self._cow_pairs, []
        return pairs

    # ------------------------------------------------------------- allocation
    def allocate(self, seq_id: int, n_tokens: int, token_ids=None, match=None,
                 salt: Optional[str] = None):
        """Allocate a sequence's blocks.

        Plain call (``token_ids=None``): the uncached path — returns the block
        list, exactly the historical contract.

        With ``token_ids`` and prefix caching enabled: matches the longest
        cached full-block prefix and returns ``(cached_blocks,
        n_cached_tokens, new_blocks)``; the sequence's table is
        ``cached_blocks [+ cow dst] + new_blocks`` and the caller only
        prefills tokens ``[n_cached_tokens:]``. Pass the ``match`` a prior
        :meth:`match_prefix`/:meth:`can_admit` computed (no mutation may
        happen in between) to avoid re-hashing the prompt."""
        need = self.blocks_needed(n_tokens)
        if need > self.max_blocks_per_seq:
            raise ValueError(f"sequence needs {need} blocks > max_blocks_per_seq {self.max_blocks_per_seq}")
        if match is None and token_ids is not None:
            match = self.match_prefix(token_ids, n_tokens, salt=salt)
        shared, n_cached, cow_src = match if match is not None else ([], 0, None)
        n_fresh = need - len(shared)
        # matched idle blocks are about to leave the LRU: they can't double as
        # evictable capacity for this same allocation's fresh blocks
        available = self.num_free - self._idle_count(shared)
        if n_fresh > available:
            raise RuntimeError(f"out of KV blocks: need {n_fresh}, free {available}")
        # acquire shared refs BEFORE popping fresh blocks: a matched idle block
        # must leave the LRU first or the eviction path could recycle it
        for b in shared:
            self._acquire(b)
        if cow_src is not None and cow_src in self._lru:
            self._lru.move_to_end(cow_src)  # just used: keep it warm
        new_blocks = [self._pop_block() for _ in range(n_fresh)]
        if cow_src is not None:
            # new_blocks[0] becomes the private copy of the shared tail block
            self._cow_pairs.append((cow_src, new_blocks[0]))
        self.tables[seq_id] = shared + new_blocks
        self.lengths[seq_id] = n_tokens
        self._seq_epoch[seq_id] = self._cache_epoch
        if n_cached > 0:
            self.cache_hits += 1
            self.cached_tokens_total += n_cached
        if token_ids is not None:
            return shared, n_cached, new_blocks
        return self.tables[seq_id]

    def extend(self, seq_id: int, n_new_tokens: int = 1) -> Optional[List[int]]:
        """Grow a sequence; returns newly-allocated blocks (None if OOM -> preempt)."""
        new_len = self.lengths[seq_id] + n_new_tokens
        need = self.blocks_needed(new_len) - len(self.tables[seq_id])
        if need > 0:
            if need > self.num_free:
                return None
            if self.blocks_needed(new_len) > self.max_blocks_per_seq:
                return None
            new_blocks = [self._pop_block() for _ in range(need)]
            self.tables[seq_id].extend(new_blocks)
        else:
            new_blocks = []
        self.lengths[seq_id] = new_len
        return new_blocks

    def shrink(self, seq_id: int, new_len: int):
        """Release blocks beyond ``new_len`` tokens (undo speculative multi-step
        extension after a sequence finished early). Refcount-aware: a shared
        block dropped from this table survives for its other holders."""
        if seq_id not in self.tables:
            return
        keep = max(self.blocks_needed(new_len), 1)
        blocks = self.tables[seq_id]
        if keep < len(blocks):
            for b in blocks[keep:]:
                self._release_block(b)
            del blocks[keep:]
        self.lengths[seq_id] = new_len

    def free_seq(self, seq_id: int):
        """Release a sequence WITHOUT registering its blocks (abort/preempt)."""
        blocks = self.tables.pop(seq_id, [])
        self.lengths.pop(seq_id, None)
        self._seq_epoch.pop(seq_id, None)
        for b in blocks:
            self._release_block(b)

    def finish_seq_cached(self, seq_id: int, token_ids, salt: Optional[str] = None):
        """Release a finished sequence, registering its full prompt blocks in
        the prefix index so later requests skip their prefill.

        Chain registration is content-addressed: a block whose hash is already
        claimed by another block is simply not registered (deeper blocks still
        are — a future match mixes providers freely, content is identical).

        A sequence allocated before the last :meth:`clear_prefix_cache` holds
        KV computed under superseded params — it releases without registering
        (the epoch check), otherwise it would re-poison the cleared index."""
        blocks = self.tables.pop(seq_id, None)
        self.lengths.pop(seq_id, None)
        epoch = self._seq_epoch.pop(seq_id, None)
        if blocks is None:
            return
        if self.enable_prefix_cache and token_ids is not None and epoch == self._cache_epoch:
            bs = self.block_size
            nb_full = min(len(token_ids) // bs, len(blocks))
            for i, h in enumerate(self._chain_hashes(token_ids, nb_full, salt=salt)):
                b = blocks[i]
                if h not in self._index and b not in self._block_hash:
                    self._index[h] = b
                    self._block_hash[b] = h
                    # resident-XOR: a cold re-prefill of a spilled span just
                    # re-registered device-side — the (identical-content) host
                    # copy is displaced, and any still-queued spill of it dies
                    # before the drain would double-register it
                    if self.host_tier is not None:
                        self.host_tier.discard(h)
                        if self._pending_spills:
                            self._pending_spills = [
                                p for p in self._pending_spills if p[0] != h]
        for b in blocks:
            self._release_block(b)

    def clear_prefix_cache(self):
        """Drop every idle cached block back to the free list (index reset)."""
        for b in list(self._lru):
            self._index.pop(self._block_hash.pop(b), None)
            self.free.append(b)
        self._lru.clear()
        # blocks still referenced by running sequences stay out of the index
        # from now on: unregister them so they free normally on release
        for b in list(self._block_hash):
            self._index.pop(self._block_hash.pop(b), None)
        # in-flight sequences hold KV from before the clear: the epoch bump
        # stops finish_seq_cached from re-registering it into the fresh index
        self._cache_epoch += 1
        # the host tier is the same cache one level down: a promoted pre-swap
        # block serving post-swap traffic would splice KV across weight
        # generations, so queued spills die and resident entries invalidate
        self._pending_spills.clear()
        if self.host_tier is not None:
            self.host_tier.clear()

    def table_array(self, seq_id: int) -> np.ndarray:
        """Padded table row (sentinel block 0 for unused slots)."""
        out = np.zeros(self.max_blocks_per_seq, dtype=np.int32)
        blocks = self.tables.get(seq_id, [])
        out[: len(blocks)] = blocks
        return out

    def longest_seq(self) -> Optional[int]:
        """Preemption candidate (reference step.cu preempts the longest)."""
        if not self.lengths:
            return None
        return max(self.lengths, key=lambda s: self.lengths[s])
