"""Pluggable model backend: the seam between the scheduler and the device.

``InferenceEngine`` (engine.py) owns *scheduling* — the waiting queue, slot
binding, the ``BlockManager``, preemption, chunk budgets, prefix-cache
bookkeeping, speculative acceptance. Everything that touches the device —
model params, the paged KV pool, the per-slot penalty-count tensor, and the
jitted step programs — lives behind a :class:`ModelBackend`. The engine talks
to it in host numpy and plain Python; the backend decides placement, layout
and compilation.

Contract (one backend == one way to run the forward + lay out KV):

- ``prefill(...)``       batched monolithic prompt prefill, samples token 0;
- ``decode(...)``        multi-token decode for every running slot;
- ``mixed_step(...)``    one ragged step of prefill chunks + decode tokens;
- ``verify(...)``        speculative-decoding verify forward;
- ``seed_counts``/``reset_counts``  per-slot penalty-count maintenance;
- ``apply_cow(pairs)``   prefix-cache copy-on-write block copies in the pool;
- ``describe()``         placement metadata for ``stats()``/the metrics plane.

Every step entry point additionally stamps ``self.step_accounting`` —
``{"fed": <token positions the launch processed>, "shape": <launch-geometry
key>}`` — immediately before dispatch. The engine reads it right after the
call to feed the goodput ledger (observability/goodput.py): ``fed`` is the
*padded* geometry (``n_rows * bucket_width``), which is what the device
actually burnt cycles on, and ``shape`` keys the live shape-bucket
cardinality gauge. Backends never decompose fed into useful/padding/rework —
that split needs scheduler knowledge (prefix hits, preemption history,
speculative acceptance) the backend deliberately does not have.

External weight updates (serving epochs, PPO rollouts) flow through the
``params`` property: callers rebind ``model.params`` and the backend picks it
up on the next step (the sharded backend re-places the tree on its mesh via
an id check).

Implementations:

- :class:`SingleDeviceBackend` — the historical engine layout: everything on
  the default device, ``PagedInferenceModel`` jits with no sharding
  annotations.
- ``ShardedBackend`` (sharded_backend.py) — weights + KV pool laid out with
  ``jax.sharding.NamedSharding`` over a ``parallel.mesh`` Mesh; the same
  scheduler runs unchanged on top.

**MPMD stage-split seam.** A two-stage disaggregated prefill/decode backend
(per *Scaling Deep Learning Training with MPMD Pipeline Parallelism*) is a
THIRD implementation of this interface, not an engine rewrite: ``prefill`` /
the chunk rows of ``mixed_step`` run on the prefill stage's mesh, ``decode`` /
the decode rows on the decode stage's mesh, and the backend migrates a
sequence's KV blocks between the two pools when its last chunk lands (the
block-table indirection means the engine's tables stay valid — only the pool
tensor behind them moves). Nothing in the engine assumes the four entry
points share a device, a pool tensor, or even a process; the only cross-call
state the engine relies on is that KV written by one call is readable by the
next call *for the same sequence*. ``DisaggBackend`` (disagg_backend.py)
implements it: backends that set ``staged = True`` additionally expose
``kv_migrate(seq_id, blocks, slot, token_hist)`` → ticket and
``migration_ready(ticket)``, and the engine gates a sequence's
decode-eligibility on the landed migration (the scheduler still never
touches the device — it only polls tickets).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .inference_model import PagedInferenceModel
from .kv_host_tier import HostPromoteTicket, gather_blocks, scatter_blocks
from .paged_cache import PagedKVPool, copy_blocks, init_paged_pool

__all__ = ["ModelBackend", "SingleDeviceBackend", "MixedRow", "samp_arrays"]


def samp_arrays(sampling: Sequence, n: Optional[int] = None):
    """Per-row sampling-parameter arrays for the device kernels.

    ``sampling`` holds SamplingParams-shaped objects (duck-typed) or None for
    padding rows; ``n`` pads/truncates to a fixed row count."""
    rows = list(sampling)
    if n is not None:
        rows = (rows + [None] * n)[:n]
    get = lambda f, d: np.asarray([getattr(s, f) if s is not None else d for s in rows])
    return dict(
        seeds=jnp.asarray(get("seed", 0), jnp.int32),
        temperature=jnp.asarray(get("temperature", 1.0), jnp.float32),
        top_k=jnp.asarray(get("top_k", 0), jnp.int32),
        top_p=jnp.asarray(get("top_p", 1.0), jnp.float32),
        do_sample=jnp.asarray(get("do_sample", False), bool),
        repetition_penalty=jnp.asarray(get("repetition_penalty", 1.0), jnp.float32),
        presence_penalty=jnp.asarray(get("presence_penalty", 0.0), jnp.float32),
        frequency_penalty=jnp.asarray(get("frequency_penalty", 0.0), jnp.float32),
    )


@dataclasses.dataclass
class MixedRow:
    """One row of a ragged mixed step, as the scheduler sees it.

    A prefill-chunk row feeds ``tokens`` (the next chunk of the prompt)
    starting at absolute position ``start``; a decode row feeds exactly one
    token (the slot's last sampled id). ``emit=True`` means the sampler's
    token at position ``start + len(tokens)`` is kept (final chunks and
    decode rows); non-final chunks discard it."""

    slot: int
    tokens: np.ndarray
    start: int
    table: np.ndarray
    emit: bool
    sampling: object
    is_chunk: bool
    #: adapter-pool slot this row's LoRA delta gathers from (0 = identity —
    #: the no-adapter row); the engine fills it from Request.adapter_slot
    adapter: int = 0


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class ModelBackend:
    """Interface base (see module docstring). Subclasses own params, the KV
    pool, the penalty-count tensor and the compiled step functions."""

    #: the PagedInferenceModel (or subclass) holding the jitted programs —
    #: exposed because tests and tools flip ``infer.use_paged_kernel``
    infer: PagedInferenceModel

    #: True for stage-split (disaggregated) backends: the engine then routes
    #: finished prefills through kv_migrate/migration_ready before treating
    #: the sequence as decode-eligible
    staged = False

    #: the last launch's padded token geometry for the goodput ledger (see
    #: module docstring) — stamped (REASSIGNED, never mutated in place: the
    #: engine may hold a reference across its accounting read) by every step
    #: entry point before dispatch. Instance state — initialized per backend
    #: in __init__ so fleets of in-process engines never share one dict.
    step_accounting: dict

    def prefill(self, input_ids, block_tables, suffix_lens, cached_entries,
                sampling, slot_idx, adapter_table=None) -> np.ndarray:
        raise NotImplementedError

    def decode(self, last_tokens, block_tables, context_lens, done0, remaining,
               sampling, adapter_table=None) -> Tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def mixed_step(self, chunk_rows: List[MixedRow], decode_rows: List[MixedRow]) -> np.ndarray:
        raise NotImplementedError

    def verify(self, tokens, block_tables, start_pos, need_logits: bool,
               adapter_table=None):
        raise NotImplementedError

    def seed_counts(self, slot_idx, cached_entries):
        raise NotImplementedError

    def reset_counts(self):
        raise NotImplementedError

    def apply_cow(self, pairs):
        raise NotImplementedError

    def kv_spill(self, block_ids):
        """Gather ``block_ids`` out of the pool and start their D2H copy
        (hierarchical prefix cache, kv_host_tier.py). Returns ``(kv, scale)``
        gathered [L, 2, n_padded, K, bs, H] planes with
        ``copy_to_host_async`` dispatched — the engine hands them straight to
        :meth:`HostKVTier.put`. Must be called BEFORE any launch that writes
        the (just-recycled) blocks; dispatch order then guarantees the gather
        reads the pre-overwrite bytes."""
        raise NotImplementedError

    def kv_promote(self, seq_id, block_ids, host_kv, host_scale=None):
        """Scatter host-tier KV back into freshly-allocated pool blocks (the
        async H2D dispatched ahead of prefill). Returns a
        :class:`HostPromoteTicket` whose markers feed
        :meth:`migration_ready` — the engine keeps the sequence in
        ``kv_stage == "promoting"`` until the copy lands."""
        raise NotImplementedError

    def kv_writeback(self, block_ids):
        """Make ``block_ids``' KV readable by future *prefill* work. A no-op
        everywhere except staged backends: generated-token KV is written in
        the decode pool, so registering generated blocks in the prefix index
        needs their bytes copied back into the prefill pool first."""
        return None

    def migration_ready(self, ticket) -> bool:
        """Non-blocking landed check for any marker-carrying copy ticket
        (stage migrations and host-tier promotions share it). Purely a
        scheduling signal — the pool's functional threading already orders
        every read after the copy — so a runtime without ``is_ready``
        introspection just reports landed."""
        for m in ticket.markers:
            probe = getattr(m, "is_ready", None)
            if probe is not None and not probe():
                return False
        return True

    def sync_params(self, new_params):
        """Install a new base-weight tree as THE params for every subsequent
        step. The explicit sibling of the lazy params-property rebind: callers
        that need the placement to happen NOW (a serving weight swap that
        wants device OOM / layout failures to surface inside its rollback
        window, not on the next request's step) go through here. Backends
        must keep their existing device layout — same NamedShardings, same
        mesh — and must not touch the KV pool or penalty counts."""
        raise NotImplementedError

    def describe(self) -> dict:
        raise NotImplementedError


class SingleDeviceBackend(ModelBackend):
    """The historical engine layout: params/pool/counts on the default device,
    no sharding annotations on the jitted steps."""

    def __init__(self, model, *, max_batch_size: int, block_size: int, num_blocks: int,
                 max_blocks_per_seq: int, dtype, decode_steps: int, eos_ids,
                 kv_cache_quant: Optional[str] = None,
                 token_flatten: Optional[bool] = None,
                 adapter_registry=None):
        self.model = model
        self.max_batch_size = max_batch_size
        self.step_accounting = {"fed": 0, "shape": ()}
        # multi-LoRA: with a registry attached, EVERY step passes the device
        # adapter pool + a per-row slot index (identity rows gather slot 0's
        # zeros) — one program serves mixed adapter/no-adapter batches. No
        # registry -> lora=None everywhere: the historical programs, untouched.
        # Set BEFORE _build_infer: the sharded infer reads it to decide the
        # lora leg of its in_shardings at jit-build time.
        self.adapter_registry = adapter_registry
        self._lora_dev = None
        self._lora_version = None
        self.infer = self._build_infer(model, block_size, num_blocks, max_blocks_per_seq,
                                       dtype, decode_steps, eos_ids)
        self.pool = self._init_pool(model.config, num_blocks, block_size, dtype, kv_cache_quant)
        self.counts = self._init_counts()
        # None = auto: flatten on the XLA fallback (where decode rows padded to
        # the chunk bucket dominate the mixed-step cost), keep the single
        # padded launch when the Pallas ragged kernel is active
        self.token_flatten = token_flatten

    # ---------------------------------------------------------------- setup
    def _build_infer(self, model, block_size, num_blocks, max_blocks_per_seq,
                     dtype, decode_steps, eos_ids) -> PagedInferenceModel:
        return PagedInferenceModel(
            model, block_size, num_blocks, max_blocks_per_seq, dtype=dtype,
            decode_steps=decode_steps, eos_ids=eos_ids,
        )

    def _init_pool(self, config, num_blocks, block_size, dtype, quant):
        return init_paged_pool(config, num_blocks, block_size,
                               dtype=jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32,
                               quant=quant)

    def _init_counts(self):
        return jnp.zeros((self.max_batch_size, self.model.config.vocab_size), jnp.int32)

    @property
    def params(self):
        return self.model.params

    def sync_params(self, new_params):
        # single device: the params property reads model.params directly, so
        # the rebind IS the install (jit retraces nothing — same avals)
        self.model.params = new_params

    # ---------------------------------------------------------------- lora
    def _place_lora(self, host_pool):
        """Place the host adapter pool on device (the sharded backend overrides
        this with NamedSharding placement)."""
        return jax.tree_util.tree_map(jnp.asarray, host_pool)

    def _lora_tree(self):
        """Device copy of the registry's adapter pool, re-placed ONLY when the
        registry's content version moved (adapter load/evict) — the sharded
        params-rebind id-check pattern applied to the adapter pool."""
        reg = self.adapter_registry
        if reg is None:
            return None
        host, version = reg.pool_arrays()
        if version != self._lora_version:
            self._lora_dev = self._place_lora(host)
            self._lora_version = version
        return self._lora_dev

    def _adapter_idx(self, adapter_table, n: int):
        """Per-row pool-slot indices for an n-row launch (None -> identity).
        Raises when adapters are requested without a registry attached — a
        scheduler bug that must not silently serve base-model tokens."""
        if adapter_table is None:
            idx = np.zeros(n, np.int32)
        else:
            idx = np.zeros(n, np.int32)
            idx[: len(adapter_table)] = np.asarray(adapter_table, np.int32)
        if self.adapter_registry is None:
            if idx.any():
                raise ValueError("adapter_table has non-identity rows but the "
                                 "backend has no adapter_registry")
            return None
        return jnp.asarray(idx)

    # ---------------------------------------------------------------- counts
    def _cached_counts(self, cached_entries, n_rows: int) -> jnp.ndarray:
        """Penalty counts for prefix-cache-hit prompt spans: the fed suffix is
        counted on device, the cached span here via host bincount. Clipped: an
        out-of-vocab id from a direct caller must degrade to a garbage count
        (the old one_hot behavior), not crash the step. All-miss (or
        cache-off) batches materialize the zeros on device instead of shipping
        an n*vocab host buffer. ``cached_entries`` = [(row, prompt_ids,
        n_cached)]; returns [n_rows, vocab] int32."""
        vocab = self.model.config.vocab_size
        counts_in = None
        for row, prompt_ids, n_cached in cached_entries:
            if n_cached > 0:
                if counts_in is None:
                    counts_in = np.zeros((n_rows, vocab), np.int32)
                counts_in[row] = np.bincount(  # sync-ok: bincount of HOST prompt ids over the cached span only (documented in the docstring)
                    np.clip(prompt_ids[:n_cached], 0, vocab - 1),
                    minlength=vocab)[:vocab]
        if counts_in is None:
            return jnp.zeros((n_rows, vocab), jnp.int32)
        return jnp.asarray(counts_in)

    def seed_counts(self, slot_idx, cached_entries):
        rows = self._cached_counts(cached_entries, len(slot_idx))
        self.counts = self.counts.at[jnp.asarray(np.asarray(slot_idx))].set(rows)  # sync-ok: slot_idx is a host int list

    def reset_counts(self):
        self.counts = jnp.zeros_like(self.counts)

    # ---------------------------------------------------------------- steps
    def prefill(self, input_ids, block_tables, suffix_lens, cached_entries,
                sampling, slot_idx, adapter_table=None) -> np.ndarray:
        n = input_ids.shape[0]
        self.step_accounting = {"fed": n * input_ids.shape[1],
                                "shape": ("prefill", n, input_ids.shape[1])}
        cached_lens = np.zeros(n, np.int32)
        for row, _ids, n_cached in cached_entries:
            cached_lens[row] = n_cached
        counts_dev = self._cached_counts(cached_entries, n)
        tokens, counts_rows, self.pool = self.infer.prefill(
            self.params, self.pool, jnp.asarray(input_ids), jnp.asarray(block_tables),
            jnp.asarray(suffix_lens), jnp.asarray(cached_lens), counts_dev,
            samp_arrays(sampling, n),
            lora=self._lora_tree(), adapter_idx=self._adapter_idx(adapter_table, n),
        )
        self.counts = self.counts.at[jnp.asarray(np.asarray(slot_idx))].set(  # sync-ok: slot_idx is a host int list
            counts_rows[: len(slot_idx)])
        return np.asarray(tokens)  # sync-ok: THE prefill sync point — sampled int32 ids only

    def decode(self, last_tokens, block_tables, context_lens, done0, remaining,
               sampling, adapter_table=None) -> Tuple[np.ndarray, np.ndarray]:
        B, steps = last_tokens.shape[0], self.infer.decode_steps
        self.step_accounting = {"fed": B * steps, "shape": ("decode", B, steps)}
        toks, valid, _, _, self.counts, self.pool = self.infer.decode(
            self.params, self.pool, jnp.asarray(last_tokens), jnp.asarray(block_tables),
            jnp.asarray(context_lens), jnp.asarray(done0), jnp.asarray(remaining),
            self.counts, samp_arrays(sampling, len(sampling)),
            lora=self._lora_tree(), adapter_idx=self._adapter_idx(adapter_table, B),
        )
        return np.asarray(toks), np.asarray(valid)  # sync-ok: THE decode sync point — int32 ids + validity flags only

    def verify(self, tokens, block_tables, start_pos, need_logits: bool,
               adapter_table=None):
        self.step_accounting = {
            "fed": tokens.shape[0] * tokens.shape[1],
            "shape": ("verify", tokens.shape[0], tokens.shape[1])}
        argmax, logits, self.pool = self.infer.verify(
            self.params, self.pool, jnp.asarray(tokens), jnp.asarray(block_tables),
            jnp.asarray(start_pos),
            lora=self._lora_tree(),
            adapter_idx=self._adapter_idx(adapter_table, tokens.shape[0]),
            need_logits=need_logits,
        )
        return np.asarray(argmax), (np.asarray(logits) if need_logits else None)  # sync-ok: THE verify sync point (logits only when rejection sampling asks)

    def apply_cow(self, pairs):
        self.pool = copy_blocks(self.pool, pairs)

    # ---------------------------------------------------------------- host tier
    def _build_host_tier_jits(self):
        """(gather, scatter) programs for spill/promote. The sharded backend
        overrides this to compile them with explicit shardings; the jits are
        dtype-polymorphic so one pair serves the kv and scale planes."""
        return (jax.jit(gather_blocks, donate_argnums=()),
                jax.jit(scatter_blocks, donate_argnums=(0,)))

    def _host_tier_jits(self):
        jits = getattr(self, "_host_jits", None)
        if jits is None:
            jits = self._build_host_tier_jits()
            self._host_jits = jits
        return jits

    def _place_host_blocks(self, data):
        """Start the H2D transfer of a promoted block slice (the sharded
        backend lands it with the pool's NamedSharding)."""
        return jnp.asarray(data)

    @staticmethod
    def _pad_block_ids(block_ids):
        """pow2-pad with sentinel self-references (block 0 is never a live
        dst), bounding gather/scatter to log2(max_blocks_per_seq) compiles —
        the migration padding rule."""
        ids = [int(b) for b in block_ids]
        padded = 1
        while padded < max(len(ids), 1):
            padded *= 2
        return ids, jnp.asarray(ids + [0] * (padded - len(ids)), jnp.int32), padded

    def kv_spill(self, block_ids):
        ids, ids_arr, _ = self._pad_block_ids(block_ids)
        gather, _ = self._host_tier_jits()
        kv = gather(self.pool.kv, ids_arr)
        kv.copy_to_host_async()
        scale = None
        if self.pool.scale is not None:
            scale = gather(self.pool.scale, ids_arr)
            scale.copy_to_host_async()
        return kv, scale

    def kv_promote(self, seq_id, block_ids, host_kv, host_scale=None):
        ids, ids_arr, padded = self._pad_block_ids(block_ids)
        n = len(ids)
        if padded != n:
            # pad with ZERO rows, not gathered bytes: the sentinel ids point
            # the extra scatter rows at block 0, which must stay all-zeros
            pad = np.zeros(host_kv.shape[:2] + (padded - n,) + host_kv.shape[3:],
                           host_kv.dtype)
            host_kv = np.concatenate([host_kv, pad], axis=2)
            if host_scale is not None:
                spad = np.zeros(host_scale.shape[:2] + (padded - n,) + host_scale.shape[3:],
                                host_scale.dtype)
                host_scale = np.concatenate([host_scale, spad], axis=2)
        _, scatter = self._host_tier_jits()
        new_kv, marker = scatter(self.pool.kv, self._place_host_blocks(host_kv), ids_arr)
        markers = [marker]
        scale = self.pool.scale
        if scale is not None:
            if host_scale is None:
                raise ValueError("quantized pool promote needs the spilled scale plane")
            scale, s_marker = scatter(scale, self._place_host_blocks(host_scale), ids_arr)
            markers.append(s_marker)
        self.pool = PagedKVPool(kv=new_kv, scale=scale)
        return HostPromoteTicket(seq_id=seq_id, n_blocks=n, markers=tuple(markers))

    # ---------------------------------------------------------------- mixed
    def mixed_step(self, chunk_rows: List[MixedRow], decode_rows: List[MixedRow]) -> np.ndarray:
        """One ragged mixed step. Returns sampled tokens in row order
        ``[*chunk_rows, *decode_rows]`` (the scheduler keeps them only where
        ``emit``)."""
        return self.mixed_step_begin(chunk_rows, decode_rows)()

    def mixed_step_begin(self, chunk_rows: List[MixedRow],
                         decode_rows: List[MixedRow]) -> Callable[[], np.ndarray]:
        """Dispatch the ragged step WITHOUT syncing; returns a zero-arg
        collector yielding the sampled ids in ``[*chunk_rows, *decode_rows]``
        order. The split exists for staged (MPMD) backends: they dispatch the
        prefill-stage and decode-stage programs back to back and only then
        collect, so the two device groups compute concurrently instead of the
        host serializing them at the first sync."""
        flat = self.token_flatten
        if flat is None:
            flat = not self.infer.use_paged_kernel
        launch = self._mixed_flat_launch if flat else self._mixed_padded_launch
        tokens_dev, mapper = launch(chunk_rows, decode_rows)

        def collect() -> np.ndarray:
            return mapper(np.asarray(tokens_dev))  # sync-ok: THE mixed-step sync point — sampled int32 ids only

        return collect

    def _mixed_padded_launch(self, chunk_rows, decode_rows):
        """Legacy layout: one [B, T] launch, every row padded to the chunk
        bucket — what the Pallas ragged kernel wants (a single grid covers
        chunks, decodes and dead rows). Returns (device tokens, host-order
        mapper)."""
        B = self.max_batch_size
        T = _bucket(max([len(r.tokens) for r in chunk_rows], default=1), minimum=1)
        self.step_accounting = {"fed": B * T, "shape": ("mixed_padded", B, T)}
        ids = np.zeros((B, T), np.int32)
        tables = np.zeros((B, chunk_rows[0].table.shape[0] if chunk_rows
                           else decode_rows[0].table.shape[0]), np.int32)
        q_lens = np.zeros(B, np.int32)
        q_start = np.zeros(B, np.int32)
        count_fed = np.zeros(B, bool)
        emit = np.zeros(B, bool)
        adapter = np.zeros(B, np.int32)
        sampling: List = [None] * B
        for r in chunk_rows + decode_rows:
            n = len(r.tokens)
            ids[r.slot, :n] = r.tokens
            tables[r.slot] = r.table
            q_lens[r.slot] = n
            q_start[r.slot] = r.start
            count_fed[r.slot] = r.is_chunk  # chunk tokens accumulate into counts
            emit[r.slot] = r.emit
            adapter[r.slot] = r.adapter
            sampling[r.slot] = r.sampling
        tokens, self.counts, self.pool = self.infer.mixed_step(
            self.params, self.pool, jnp.asarray(ids), jnp.asarray(tables),
            jnp.asarray(q_lens), jnp.asarray(q_start), self.counts,
            jnp.asarray(count_fed), jnp.asarray(emit), samp_arrays(sampling, B),
            lora=self._lora_tree(), adapter_idx=self._adapter_idx(adapter, B),
        )
        rows = chunk_rows + decode_rows
        return tokens, lambda host: np.asarray([host[r.slot] for r in rows])  # sync-ok: host reshuffle of already-synced ids

    def _mixed_flat_launch(self, chunk_rows, decode_rows):
        """Token-flattened layout: chunk rows keep their [C, T] matrix, decode
        rows collapse to a [D, 1] segment — per-step cost scales with the
        tokens actually fed (bucketed per segment), not B x chunk. Both
        segments run in ONE jit; token-identical to the padded layout (each
        live row's math is a row-slice of the padded program's). Returns
        (device tokens, host-order mapper)."""
        C = _bucket(len(chunk_rows), minimum=1)
        T = _bucket(max([len(r.tokens) for r in chunk_rows], default=1), minimum=1)
        D = _bucket(len(decode_rows), minimum=1)
        self.step_accounting = {"fed": C * T + D, "shape": ("mixed_flat", C, T, D)}
        M = (chunk_rows[0].table.shape[0] if chunk_rows else decode_rows[0].table.shape[0])
        c_ids = np.zeros((C, T), np.int32)
        c_tables = np.zeros((C, M), np.int32)
        c_qlens = np.zeros(C, np.int32)
        c_start = np.zeros(C, np.int32)
        c_slots = np.zeros(C, np.int32)
        c_emit = np.zeros(C, bool)
        c_adapter = np.zeros(C, np.int32)
        d_tokens = np.zeros(D, np.int32)
        d_tables = np.zeros((D, M), np.int32)
        d_start = np.zeros(D, np.int32)
        d_slots = np.zeros(D, np.int32)
        d_live = np.zeros(D, bool)
        d_adapter = np.zeros(D, np.int32)
        for j, r in enumerate(chunk_rows):
            n = len(r.tokens)
            c_ids[j, :n] = r.tokens
            c_tables[j] = r.table
            c_qlens[j] = n
            c_start[j] = r.start
            c_slots[j] = r.slot
            c_emit[j] = r.emit
            c_adapter[j] = r.adapter
        for j, r in enumerate(decode_rows):
            d_tokens[j] = r.tokens[0]
            d_tables[j] = r.table
            d_start[j] = r.start
            d_slots[j] = r.slot
            d_live[j] = True
            d_adapter[j] = r.adapter
        sampling = ([r.sampling for r in chunk_rows] + [None] * (C - len(chunk_rows))
                    + [r.sampling for r in decode_rows] + [None] * (D - len(decode_rows)))
        tokens, self.counts, self.pool = self.infer.mixed_step_flat(
            self.params, self.pool,
            jnp.asarray(c_ids), jnp.asarray(c_tables), jnp.asarray(c_qlens),
            jnp.asarray(c_start), jnp.asarray(c_slots), jnp.asarray(c_emit),
            jnp.asarray(d_tokens), jnp.asarray(d_tables), jnp.asarray(d_start),
            jnp.asarray(d_slots), jnp.asarray(d_live),
            self.counts, samp_arrays(sampling, C + D),
            lora=self._lora_tree(), chunk_adapter=self._adapter_idx(c_adapter, C),
            dec_adapter=self._adapter_idx(d_adapter, D),
        )
        n_c, n_d = len(chunk_rows), len(decode_rows)
        return tokens, lambda host: np.concatenate([host[:n_c], host[C : C + n_d]])

    # ---------------------------------------------------------------- misc
    def describe(self) -> dict:
        return {"kind": "single_device", "devices": 1, "tp_degree": 1, "mesh": None}
