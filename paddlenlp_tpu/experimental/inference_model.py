"""Paged-attention inference forward for llama-family models.

Counterpart of ``paddlenlp/experimental/transformers/fused_transformer_layers.py``
(``FusedBlockMultiTransformer`` :2192) + per-model ``*BlockInferenceModel`` classes:
a decode-optimized forward that REUSES the training params (scanned [L] layout)
but runs its own fused loop — mirroring the reference's split between training
models and the experimental inference runtime.

TPU-native: one ``lax.scan`` over the stacked layer params + the [L]-leading paged
pool; block-table gathers/scatters instead of CUDA append-attention kernels; the
whole prefill/decode step is a single jit.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.rope import apply_rotary_pos_emb, rope_frequencies, rope_tables
from .paged_cache import PagedKVPool, gather_kv, write_kv_block

__all__ = ["PagedInferenceModel", "sample_tokens"]


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] fp32
    *,
    positions: jnp.ndarray,  # [B] absolute position of the token being sampled
    seeds: jnp.ndarray,  # [B] int32 per-slot seeds
    temperature: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (<=0: off)
    top_p: jnp.ndarray,  # [B]
    do_sample: jnp.ndarray,  # [B] bool
    counts: Optional[jnp.ndarray] = None,  # [B, V] token counts (prompt+generated)
    repetition_penalty: Optional[jnp.ndarray] = None,  # [B]
    presence_penalty: Optional[jnp.ndarray] = None,  # [B]
    frequency_penalty: Optional[jnp.ndarray] = None,  # [B]
) -> jnp.ndarray:
    """Fully on-device sampling: penalties + temperature + top-k/top-p + draw.

    Counterpart of the reference's in-kernel sampling path
    (``csrc/gpu/sample_kernels/top_p_sampling_reject.cu``,
    ``csrc/gpu/token_penalty_multi_scores.cu``): one [B,V] sort serves both
    top-k and top-p, the draw is a per-row categorical, and randomness is keyed
    on (seed, absolute position) so a preempted-and-recomputed sequence
    resamples identical tokens. Host never sees logits — only ids.
    """
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    if counts is not None:
        seen = counts > 0
        rp = repetition_penalty[:, None]
        logits = jnp.where(seen, jnp.where(logits > 0, logits / rp, logits * rp), logits)
        logits = logits - seen.astype(jnp.float32) * presence_penalty[:, None]
        logits = logits - counts.astype(jnp.float32) * frequency_penalty[:, None]
    greedy = jnp.argmax(logits, axis=-1)

    warped = logits / jnp.maximum(temperature, 1e-6)[:, None]
    order = jnp.argsort(-warped, axis=-1)
    sorted_logits = jnp.take_along_axis(warped, order, axis=-1)
    ranks = jnp.arange(V)[None, :]
    # top-k first, RENORMALIZE, then the nucleus cutoff over the renormalized
    # distribution — the composition the host sampler / warper chain defines
    keep_k = jnp.where(top_k[:, None] > 0, ranks < top_k[:, None], True)
    k_masked = jnp.where(keep_k, sorted_logits, -jnp.inf)
    probs = jax.nn.softmax(k_masked, axis=-1)
    csum = jnp.cumsum(probs, axis=-1)
    keep = keep_k & ((csum - probs) < top_p[:, None])
    keep |= ranks == 0  # top-1 always kept
    masked = jnp.where(keep, sorted_logits, -jnp.inf)

    def draw(seed, pos, row):
        key = jax.random.fold_in(jax.random.key(seed), pos)
        return jax.random.categorical(key, row)

    picked = jax.vmap(draw)(seeds, positions, masked)
    sampled = jnp.take_along_axis(order, picked[:, None], axis=-1)[:, 0]
    return jnp.where(do_sample, sampled, greedy).astype(jnp.int32)


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(x.dtype)


class PagedInferenceModel:
    """Holds jitted prefill/decode over (params, pool). Llama-family only
    (llama/qwen2/mistral: config-driven biases + GQA + rope)."""

    def __init__(self, model, block_size: int = 16, num_blocks: int = 512, max_blocks_per_seq: int = 64,
                 dtype=jnp.bfloat16, decode_steps: int = 8, eos_ids=(), use_paged_kernel=None):
        self.model = model
        self.config = model.config
        if "layers" not in model.params.get("model", {}):
            raise ValueError("PagedInferenceModel requires the scanned-layer param layout (use_scan_layers)")
        self.dtype = dtype
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks_per_seq
        self.decode_steps = decode_steps
        # Pallas paged decode kernel: default-on for TPU when the tile shapes
        # are Mosaic-safe (compile errors would surface at the enclosing jit's
        # compile, uncatchable here); the XLA gather path stays the fallback.
        if use_paged_kernel is None:
            use_paged_kernel = (
                jax.default_backend() == "tpu"
                and self.config.head_dim % 64 == 0
                and block_size % 8 == 0
            )
        self.use_paged_kernel = use_paged_kernel
        # [-1] sentinel when no eos: never matches a sampled id
        self.eos_arr = jnp.asarray(sorted(eos_ids) or [-1], jnp.int32)
        cfg = self.config
        self.eps = cfg.rms_norm_eps
        self.n_heads = cfg.num_attention_heads
        self.n_kv = cfg.num_key_value_heads
        self.head_dim = cfg.head_dim
        self.inv_freq = jnp.asarray(rope_frequencies(self.head_dim, cfg.rope_theta, cfg.rope_scaling))
        # serving a QuantizedModel: its params carry qweight/scales leaves
        # (stacked [L, ...] — lax.scan slices per layer); _mm dispatches per
        # projection (reference int8_gemm_with_cutlass serving path)
        self.quant_cfg = getattr(model, "quantization_config", None)
        self._build_jits()

    def _build_jits(self):
        """Compile the step entry points. The sharded subclass overrides this
        to attach explicit ``in_shardings``/``out_shardings``; the base keeps
        the historical un-annotated jits."""
        self._prefill = jax.jit(self._prefill_impl, donate_argnums=(1,))
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        self._verify = jax.jit(self._verify_impl, donate_argnums=(1,),
                               static_argnames=("need_logits",))
        self._mixed = jax.jit(self._mixed_impl, donate_argnums=(1,))
        self._mixed_flat = jax.jit(self._mixed_flat_impl, donate_argnums=(1,))

    def _hint(self, x, kind: str):
        """Activation-layout hook: identity here; the sharded subclass turns
        ``kind`` ("heads" / "kv_heads" / "mlp" / "full") into
        ``with_sharding_constraint`` anchors so GSPMD keeps per-head compute
        local and gathers before every cross-shard contraction (the all-gather
        layout keeps the sharded forward bitwise-identical to this one)."""
        return x

    def _mm(self, p, x):
        """x @ kernel with quantized-leaf dispatch: a8w8 -> int8 x int8 MXU dot;
        weight-only -> dequant fused into the matmul operand read."""
        if "qweight" not in p:
            y = x @ p["kernel"].astype(self.dtype)
        elif self.quant_cfg is not None and self.quant_cfg.is_activation_quantize:
            from ..quantization.a8w8 import int8_linear

            return int8_linear(x, p["qweight"], p["scales"], bias=p.get("bias"),
                               act_scale=p.get("act_scale"), out_dtype=self.dtype)
        else:
            from ..quantization.quantization_utils import dequantize_leaf

            bits = self.quant_cfg.bits if self.quant_cfg is not None else 8
            y = x @ dequantize_leaf(p["qweight"], p["scales"], bits, self.dtype)
        if "bias" in p:
            y = y + p["bias"].astype(self.dtype)
        return y

    def _lora_mm(self, p, x, lora_layer, adapter_idx, name: str):
        """Base matmul + per-row LoRA delta gathered from the adapter pool.

        ``lora_layer`` is one layer's slice of the pool: ``{proj: {"A":
        [P, d_in, r], "B": [P, r, d_out]}}`` (P = slots, slot 0 = identity
        zeros, scaling pre-folded into B); ``adapter_idx`` [B] maps each batch
        row to its slot. The delta is per-row — ``base(x) + B[idx] @ (A[idx]
        @ x)`` computed row-independently — so a row's tokens are bitwise
        identical whether its adapter shares the batch with others or runs
        solo, the same independence the sampler's (seed, position) keying
        provides. fp32 accumulation matches the merged-LoRA training math."""
        y = self._mm(p, x)
        if lora_layer is None or name not in lora_layer:
            return y
        a = lora_layer[name]["A"][adapter_idx].astype(jnp.float32)  # [B, d_in, r]
        b = lora_layer[name]["B"][adapter_idx].astype(jnp.float32)  # [B, r, d_out]
        xr = jnp.einsum("btd,bdr->btr", x.astype(jnp.float32), a)
        delta = jnp.einsum("btr,bro->bto", xr, b)
        return y + delta.astype(y.dtype)

    # ------------------------------------------------------------------ forward core
    def _attend(self, q, k, v, q_positions, kv_len_mask):
        """q [B,T,N,H]; k/v [B,S,K,H]; causal by absolute position + length mask."""
        B, T, N, H = q.shape
        S = k.shape[1]
        if self.n_kv != N:
            k = jnp.repeat(k, N // self.n_kv, axis=2)
            v = jnp.repeat(v, N // self.n_kv, axis=2)
        logits = jnp.einsum("btnh,bsnh->bnts", q.astype(jnp.float32), k.astype(jnp.float32)) * (H**-0.5)
        kv_pos = jnp.arange(S)[None, :]
        mask = (kv_pos[:, None, :] <= q_positions[:, :, None]) & kv_len_mask[:, None, :]
        logits = jnp.where(mask[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnts,bsnh->btnh", probs, v.astype(jnp.float32))
        return out.astype(q.dtype)

    def _layer(self, carry, scanned, block_tables, q_positions, kv_len_mask, write_pos,
               q_lens, adapter_idx):
        """One decoder layer inside lax.scan: scanned = (layer_params, pool_layer,
        scale_layer-or-None for quantized caches, lora_layer-or-None for
        multi-LoRA batches)."""
        h = carry
        lp, pool_layer, scale_layer, lora_layer = scanned
        cfg = self.config
        B, T, D = h.shape

        x = _rms(h, lp["input_layernorm"]["scale"], self.eps)
        attn = lp["self_attn"]

        def proj(p, x, heads, name):
            return self._lora_mm(p, x, lora_layer, adapter_idx, name) \
                .reshape(B, T, heads, self.head_dim)

        q = self._hint(proj(attn["q_proj"], x, self.n_heads, "q_proj"), "heads")
        k = self._hint(proj(attn["k_proj"], x, self.n_kv, "k_proj"), "kv_heads")
        v = self._hint(proj(attn["v_proj"], x, self.n_kv, "v_proj"), "kv_heads")
        cos, sin = rope_tables(q_positions, self.inv_freq)
        q, k = apply_rotary_pos_emb(q, k, cos, sin)

        # scatter new K/V into the pool (per sequence)
        for i in range(B):
            written = write_kv_block(pool_layer, k[i], v[i], block_tables[i],
                                     write_pos[i], scale_layer)
            if scale_layer is not None:
                pool_layer, scale_layer = written
            else:
                pool_layer = written
        if self.use_paged_kernel:
            # fused block-table walk + attend: the Pallas ragged kernel streams
            # addressed KV blocks instead of materializing the gathered cache
            # (dequant rides in-kernel for int8/fp8 pools). One launch covers
            # the whole ragged batch — decode rows (q_lens=1), prefill chunks
            # (q_lens up to T), and inactive padding (q_lens=0) together.
            from ..ops.pallas.paged_attention import ragged_paged_attention

            attn_out = ragged_paged_attention(
                q, pool_layer[0], pool_layer[1], block_tables,
                q_start=q_positions[:, 0], q_lens=q_lens,
                k_scale=None if scale_layer is None else scale_layer[0],
                v_scale=None if scale_layer is None else scale_layer[1],
            )
        else:
            k_all, v_all = gather_kv(pool_layer, block_tables, scale_layer)
            attn_out = self._attend(q, k_all, v_all, q_positions, kv_len_mask)
        attn_out = attn_out.reshape(B, T, self.n_heads * self.head_dim)
        # gather before the contraction (o_proj stays column-parallel: full
        # dot per output column, no cross-shard partial sums), gather after
        # so the residual/norms see a replicated stream
        attn_out = self._hint(attn_out, "full")
        h = h + self._hint(
            self._lora_mm(attn["o_proj"], attn_out, lora_layer, adapter_idx, "o_proj"),
            "full")

        x = _rms(h, lp["post_attention_layernorm"]["scale"], self.eps)
        mlp = lp["mlp"]
        gate = self._hint(
            self._lora_mm(mlp["gate_proj"], x, lora_layer, adapter_idx, "gate_proj"), "mlp")
        up = self._hint(
            self._lora_mm(mlp["up_proj"], x, lora_layer, adapter_idx, "up_proj"), "mlp")
        act = self._hint(jax.nn.silu(gate) * up, "full")
        h = h + self._hint(
            self._lora_mm(mlp["down_proj"], act, lora_layer, adapter_idx, "down_proj"),
            "full")
        if scale_layer is not None:
            return h, (pool_layer, scale_layer)
        return h, pool_layer

    def _forward(self, params, pool: PagedKVPool, input_ids, block_tables, q_positions,
                 kv_len_mask, write_pos, last_pos, q_lens=None, lora=None,
                 adapter_idx=None):
        """input_ids [B,T]; returns (logits at last_pos [B,V], new PagedKVPool).

        ``last_pos=None`` returns full-sequence logits [B,T,V] (the speculative
        verify step needs the model's prediction after EVERY draft position).
        ``q_lens`` [B] = valid new tokens per row (defaults to T everywhere);
        only the Pallas ragged kernel consumes it — the XLA path masks padded
        rows implicitly (their outputs are never read).

        ``lora`` is the adapter pool tree ``{proj: {"A": [L, P, d_in, r],
        "B": [L, P, r, d_out]}}`` (or None for an adapter-free program);
        ``adapter_idx`` [B] maps each row to a pool slot (0 = identity). Both
        ride the layer scan: the pool's [L] axis slices per layer alongside
        the params, and None is a valid empty pytree — the adapter-free
        program carries no extra operands at all."""
        if q_lens is None:
            q_lens = jnp.full((input_ids.shape[0],), input_ids.shape[1], jnp.int32)
        if lora is not None and adapter_idx is None:
            adapter_idx = jnp.zeros((input_ids.shape[0],), jnp.int32)
        m = params["model"]
        embed = m["embed_tokens"]["embedding"]
        h = self._hint(embed[input_ids].astype(self.dtype), "full")
        if getattr(self.config, "scale_embeddings", False):
            h = h * jnp.asarray(self.config.hidden_size**0.5, h.dtype)

        def body(carry, scanned):
            return self._layer(carry, scanned, block_tables, q_positions, kv_len_mask,
                               write_pos, q_lens, adapter_idx)

        # uniform 4-tuple xs: None entries are empty pytrees lax.scan slices
        # to None per layer — the quant-off / lora-off programs are unchanged
        scanned = (m["layers"], pool.kv, pool.scale, lora)
        h, new_pool = jax.lax.scan(body, h, scanned)
        if pool.scale is None:
            new_pool = PagedKVPool(kv=new_pool)
        else:
            new_pool = PagedKVPool(kv=new_pool[0], scale=new_pool[1])
        h = _rms(h, m["norm"]["scale"], self.eps)
        last = h if last_pos is None else h[jnp.arange(h.shape[0]), last_pos]
        if "lm_head" in params:
            logits = last @ params["lm_head"]["kernel"].astype(self.dtype)
        else:
            logits = last @ embed.T.astype(self.dtype)
        # logits stay in compute dtype: every consumer either casts to fp32
        # itself (sample_tokens) or explicitly opts out of the cast (greedy
        # verify reads only the argmax, sparing the [B, T, V] fp32 buffer).
        # Sharded layouts leave them vocab-sharded here; the gather to the
        # replicated sampler happens once at this anchor.
        return self._hint(logits, "full"), new_pool

    # ------------------------------------------------------------------ entry points
    def _prefill_impl(self, params, pool, input_ids, block_tables, suffix_lens,
                      cached_lens, cached_counts, samp, lora=None, adapter_idx=None):
        """Batched prefill: [n, T_pad] SUFFIX sequences; samples the first token
        on device.

        Prefix caching feeds only the uncached tail of each prompt:
        ``input_ids`` row j holds prompt tokens ``[cached_lens[j]:]`` (padded to
        T), attention reads the cached span straight from the shared blocks in
        ``block_tables``, and new KV is written starting at ``cached_lens[j]``.
        ``cached_lens = 0`` everywhere reproduces the uncached full prefill.
        ``cached_counts`` [n, V] int32 are the token counts of the CACHED span
        only (host-side — suffix-only input can't see the cached tokens the
        penalty kernels must still count); the fed suffix is counted on device
        as before, so the cache-off / cache-miss path ships only zeros.

        Returns (tokens [n], counts [n, V] incl. prompt + sampled token, new pool).
        """
        n, T = input_ids.shape
        positions = cached_lens[:, None] + jnp.arange(T)[None, :]
        total_lens = cached_lens + suffix_lens
        S = block_tables.shape[1] * self.block_size
        kv_len_mask = jnp.arange(S)[None, :] < total_lens[:, None]
        logits, new_pool = self._forward(
            params, pool, input_ids, block_tables, positions,
            kv_len_mask, cached_lens,
            jnp.maximum(suffix_lens - 1, 0),  # last VALID token (input may be padded)
            q_lens=suffix_lens, lora=lora, adapter_idx=adapter_idx,
        )
        V = cached_counts.shape[-1]
        valid = (jnp.arange(T)[None, :] < suffix_lens[:, None]).astype(jnp.int32)
        # out-of-vocab ids one_hot to zero rows — same degrade as the old
        # full-prompt device count
        counts = cached_counts + (jax.nn.one_hot(input_ids, V, dtype=jnp.int32)
                                  * valid[..., None]).sum(axis=1)
        tokens = sample_tokens(logits, positions=total_lens, counts=counts, **samp)
        counts = counts + jax.nn.one_hot(tokens, V, dtype=jnp.int32)
        return tokens, counts, new_pool

    def _mixed_impl(self, params, pool, input_ids, block_tables, q_lens, q_start,
                    counts, count_fed, emit, samp, lora=None, adapter_idx=None):
        """One ragged mixed prefill/decode step: every row feeds ``q_lens[j]``
        new tokens starting at absolute position ``q_start[j]`` — a prefill
        CHUNK (``q_start`` = tokens already prefilled, ``q_lens`` up to the
        chunk size), a decode step (``q_lens = 1``, ``q_start`` = position of
        the last sampled token), or nothing (``q_lens = 0``, padded slot). KV
        for every fed token is written into the paged pool at its absolute
        position; attention covers ``[0, q_start + t]`` per fed token t —
        causal across chunk boundaries because earlier chunks' KV is already
        in the pool.

        Sampling fires for EVERY row at position ``q_start + q_lens`` (the
        next position) from the logits after the last valid fed token; the
        caller keeps the token only where ``emit`` is set (final prefill
        chunks and decode rows) — non-final chunks discard it, exactly the
        "sampler fires only when the last chunk lands" contract.

        Penalty-count accumulation across chunks: ``counts`` [B, V] is the
        running per-row token count. Rows with ``count_fed`` add their fed
        tokens on device (prefill chunks — the count survives to the next
        chunk through the returned array); decode rows don't (their fed token
        was counted when it was sampled). Rows with ``emit`` add the sampled
        token. Penalties see counts INCLUDING the fed tokens, matching the
        monolithic prefill exactly.

        Returns (tokens [B], counts' [B, V], new pool).
        """
        n, T = input_ids.shape
        positions = q_start[:, None] + jnp.arange(T)[None, :]
        S = block_tables.shape[1] * self.block_size
        kv_len_mask = jnp.arange(S)[None, :] < (q_start + q_lens)[:, None]
        logits, new_pool = self._forward(
            params, pool, input_ids, block_tables, positions, kv_len_mask,
            q_start, jnp.maximum(q_lens - 1, 0), q_lens=q_lens,
            lora=lora, adapter_idx=adapter_idx,
        )
        V = counts.shape[-1]
        valid = (jnp.arange(T)[None, :] < q_lens[:, None]).astype(jnp.int32)
        fed = (jax.nn.one_hot(input_ids, V, dtype=jnp.int32) * valid[..., None]).sum(axis=1)
        counts = counts + fed * count_fed.astype(jnp.int32)[:, None]
        tokens = sample_tokens(logits, positions=q_start + q_lens, counts=counts, **samp)
        counts = counts + jax.nn.one_hot(tokens, V, dtype=jnp.int32) \
            * emit.astype(jnp.int32)[:, None]
        return tokens, counts, new_pool

    def _mixed_flat_impl(self, params, pool, chunk_ids, chunk_tables, chunk_qlens,
                         chunk_start, chunk_slots, chunk_emit, dec_tokens, dec_tables,
                         dec_start, dec_slots, dec_live, counts, samp, lora=None,
                         chunk_adapter=None, dec_adapter=None):
        """Token-flattened ragged mixed step (the XLA-fallback layout).

        :meth:`_mixed_impl` pads EVERY row — decode rows included — to the
        chunk bucket, so a mixed step costs B x chunk query positions on the
        XLA path however few tokens are actually fed. Here the step is two
        packed segments inside one jit: prefill chunks keep their [C, T]
        matrix (C = rows actually mid-prefill, bucketed) and decode rows
        collapse to [D, 1]; cost scales with the tokens fed. Rows map to
        engine slots through ``chunk_slots``/``dec_slots`` — the penalty-count
        tensor stays slot-indexed, updated by scatter instead of dense adds.

        Token-identical to the padded layout: each live row's computation is
        a row-slice of the padded program (same contraction lengths, same
        sampling keys ``(seed, position)``), and the count updates are the
        same integers. Dead padding rows (``chunk_qlens = 0`` /
        ``~dec_live``) write only into the sentinel block and add zeros.

        Returns (tokens [C + D], counts', new pool) — tokens in segment
        order, the caller slices live rows back out.
        """
        C, T = chunk_ids.shape
        S = chunk_tables.shape[1] * self.block_size
        positions_c = chunk_start[:, None] + jnp.arange(T)[None, :]
        kv_mask_c = jnp.arange(S)[None, :] < (chunk_start + chunk_qlens)[:, None]
        logits_c, pool = self._forward(
            params, pool, chunk_ids, chunk_tables, positions_c, kv_mask_c,
            chunk_start, jnp.maximum(chunk_qlens - 1, 0), q_lens=chunk_qlens,
            lora=lora, adapter_idx=chunk_adapter,
        )
        D = dec_tokens.shape[0]
        positions_d = dec_start[:, None]
        kv_mask_d = jnp.arange(S)[None, :] <= dec_start[:, None]
        logits_d, pool = self._forward(
            params, pool, dec_tokens[:, None], dec_tables, positions_d, kv_mask_d,
            dec_start, jnp.zeros((D,), jnp.int32), q_lens=dec_live.astype(jnp.int32),
            lora=lora, adapter_idx=dec_adapter,
        )
        V = counts.shape[-1]
        valid = (jnp.arange(T)[None, :] < chunk_qlens[:, None]).astype(jnp.int32)
        fed = (jax.nn.one_hot(chunk_ids, V, dtype=jnp.int32) * valid[..., None]).sum(axis=1)
        counts = counts.at[chunk_slots].add(fed)
        rows = jnp.concatenate([chunk_slots, dec_slots])
        logits_all = jnp.concatenate([logits_c, logits_d], axis=0)
        pos_all = jnp.concatenate([chunk_start + chunk_qlens, dec_start + 1])
        tokens = sample_tokens(logits_all, positions=pos_all, counts=counts[rows], **samp)
        emit_all = jnp.concatenate([chunk_emit, dec_live]).astype(jnp.int32)
        counts = counts.at[rows].add(
            jax.nn.one_hot(tokens, V, dtype=jnp.int32) * emit_all[:, None])
        return tokens, counts, pool

    def _decode_impl(self, params, pool, tokens, block_tables, context_lens, done0,
                     remaining, counts, samp, lora=None, adapter_idx=None):
        """Multi-step decode: advance every slot up to ``decode_steps`` tokens in ONE
        jit — the host round-trip carries ids and flags only (the reference's whole
        per-token op chain ``update_inputs.cu``/``stop_generation_multi_ends.cu``/
        sampling runs in here). Finished rows freeze: ctx stops advancing and their
        KV slot is rewritten in place, never read again.

        Returns (tokens [steps, B], valid [steps, B], done, ctx, counts, pool).
        """
        B = tokens.shape[0]
        S = block_tables.shape[1] * self.block_size
        eos = self.eos_arr

        def one(carry, _):
            pool_c, tok, ctx, done, counts, n_out = carry
            kv_mask = jnp.arange(S)[None, :] <= ctx[:, None]
            logits, pool_c = self._forward(
                params, pool_c, tok[:, None], block_tables, ctx[:, None],
                kv_mask, ctx, jnp.zeros((B,), jnp.int32),
                lora=lora, adapter_idx=adapter_idx,
            )
            nxt = sample_tokens(logits, positions=ctx + 1, counts=counts, **samp)
            emit = ~done
            hit_eos = (nxt[:, None] == eos[None, :]).any(axis=-1)
            newly_done = emit & (hit_eos | (n_out + 1 >= remaining))
            nxt = jnp.where(done, tok, nxt)
            counts = counts + jax.nn.one_hot(nxt, counts.shape[-1], dtype=counts.dtype) * emit[:, None]
            ctx = jnp.where(done, ctx, ctx + 1)
            n_out = n_out + emit
            done = done | newly_done
            return (pool_c, nxt, ctx, done, counts, n_out), (nxt, emit)

        init = (pool, tokens, context_lens, done0, counts,
                jnp.zeros((B,), jnp.int32))
        (pool, _, ctx, done, counts, _), (toks, valid) = jax.lax.scan(
            one, init, None, length=self.decode_steps
        )
        return toks, valid, done, ctx, counts, pool

    def _verify_impl(self, params, pool, tokens, block_tables, start_pos,
                     lora=None, adapter_idx=None, need_logits: bool = True):
        """Speculative-decoding verify: one forward over ``[last_token, d_1..d_K]``.

        Counterpart of the reference's speculative write path
        (``csrc/gpu/append_attn/`` speculative decoding ops): the draft tokens
        are scored in a single [B, K+1] forward over the paged cache and the
        host accepts the longest matching prefix. KV for every fed position is
        written optimistically; rejected positions need no rollback — they are
        masked by absolute position until the next step overwrites them
        in place (the same property the reference's block cache relies on).

        tokens [B, K+1] (row = last accepted token then drafts, 0-padded);
        start_pos [B] absolute position of tokens[:, 0]. Returns
        (argmax [B, K+1] int32, logits [B, K+1, V] fp32 or None, new pool) —
        position i scores the token AFTER consuming tokens[:, i]. Greedy
        acceptance reads only the argmax, and ``need_logits=False`` skips the
        [B, K+1, V] fp32 materialization entirely (it doubled the verify
        buffer per speculative step for a tensor greedy mode never read);
        rejection sampling passes ``need_logits=True`` for the full logits.
        """
        B, T = tokens.shape
        positions = start_pos[:, None] + jnp.arange(T)[None, :]
        S = block_tables.shape[1] * self.block_size
        kv_len_mask = jnp.arange(S)[None, :] <= (start_pos[:, None] + T - 1)
        logits, new_pool = self._forward(
            params, pool, tokens, block_tables, positions, kv_len_mask,
            start_pos, last_pos=None, lora=lora, adapter_idx=adapter_idx,
        )
        argmax = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if not need_logits:
            return argmax, None, new_pool
        return argmax, logits.astype(jnp.float32), new_pool

    def verify(self, params, pool: PagedKVPool, tokens, block_tables, start_pos,
               lora=None, adapter_idx=None, need_logits: bool = True):
        return self._verify(params, pool, tokens, block_tables, start_pos,
                            lora, adapter_idx, need_logits=need_logits)

    def prefill(self, params, pool: PagedKVPool, input_ids, block_tables, suffix_lens,
                cached_lens, cached_counts, samp, lora=None, adapter_idx=None):
        return self._prefill(params, pool, input_ids, block_tables, suffix_lens,
                             cached_lens, cached_counts, samp, lora, adapter_idx)

    def decode(self, params, pool: PagedKVPool, tokens, block_tables, context_lens, done0,
               remaining, counts, samp, lora=None, adapter_idx=None):
        return self._decode(
            params, pool, tokens, block_tables, context_lens, done0, remaining, counts,
            samp, lora, adapter_idx
        )

    def mixed_step(self, params, pool: PagedKVPool, input_ids, block_tables, q_lens,
                   q_start, counts, count_fed, emit, samp, lora=None, adapter_idx=None):
        return self._mixed(params, pool, input_ids, block_tables, q_lens, q_start,
                           counts, count_fed, emit, samp, lora, adapter_idx)

    def mixed_step_flat(self, params, pool: PagedKVPool, chunk_ids, chunk_tables,
                        chunk_qlens, chunk_start, chunk_slots, chunk_emit, dec_tokens,
                        dec_tables, dec_start, dec_slots, dec_live, counts, samp,
                        lora=None, chunk_adapter=None, dec_adapter=None):
        return self._mixed_flat(params, pool, chunk_ids, chunk_tables, chunk_qlens,
                                chunk_start, chunk_slots, chunk_emit, dec_tokens,
                                dec_tables, dec_start, dec_slots, dec_live, counts, samp,
                                lora, chunk_adapter, dec_adapter)
