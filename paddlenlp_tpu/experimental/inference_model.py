"""Paged-attention inference forward for llama-family models.

Counterpart of ``paddlenlp/experimental/transformers/fused_transformer_layers.py``
(``FusedBlockMultiTransformer`` :2192) + per-model ``*BlockInferenceModel`` classes:
a decode-optimized forward that REUSES the training params (scanned [L] layout)
but runs its own fused loop — mirroring the reference's split between training
models and the experimental inference runtime.

TPU-native: one ``lax.scan`` over the stacked layer params + the [L]-leading paged
pool; block-table gathers/scatters instead of CUDA append-attention kernels; the
whole prefill/decode step is a single jit.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.rope import apply_rotary_pos_emb, rope_frequencies, rope_tables
from .paged_cache import PagedKVPool, gather_kv, write_kv_block

__all__ = ["PagedInferenceModel"]


def _rms(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)).astype(x.dtype)


class PagedInferenceModel:
    """Holds jitted prefill/decode over (params, pool). Llama-family only
    (llama/qwen2/mistral: config-driven biases + GQA + rope)."""

    def __init__(self, model, block_size: int = 16, num_blocks: int = 512, max_blocks_per_seq: int = 64,
                 dtype=jnp.bfloat16):
        self.model = model
        self.config = model.config
        if "layers" not in model.params.get("model", {}):
            raise ValueError("PagedInferenceModel requires the scanned-layer param layout (use_scan_layers)")
        self.dtype = dtype
        self.block_size = block_size
        self.num_blocks = num_blocks
        self.max_blocks_per_seq = max_blocks_per_seq
        cfg = self.config
        self.eps = cfg.rms_norm_eps
        self.n_heads = cfg.num_attention_heads
        self.n_kv = cfg.num_key_value_heads
        self.head_dim = cfg.head_dim
        self.inv_freq = jnp.asarray(rope_frequencies(self.head_dim, cfg.rope_theta, cfg.rope_scaling))
        self._prefill = jax.jit(self._prefill_impl)
        self._decode = jax.jit(self._decode_impl)

    # ------------------------------------------------------------------ forward core
    def _attend(self, q, k, v, q_positions, kv_len_mask):
        """q [B,T,N,H]; k/v [B,S,K,H]; causal by absolute position + length mask."""
        B, T, N, H = q.shape
        S = k.shape[1]
        if self.n_kv != N:
            k = jnp.repeat(k, N // self.n_kv, axis=2)
            v = jnp.repeat(v, N // self.n_kv, axis=2)
        logits = jnp.einsum("btnh,bsnh->bnts", q.astype(jnp.float32), k.astype(jnp.float32)) * (H**-0.5)
        kv_pos = jnp.arange(S)[None, :]
        mask = (kv_pos[:, None, :] <= q_positions[:, :, None]) & kv_len_mask[:, None, :]
        logits = jnp.where(mask[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnts,bsnh->btnh", probs, v.astype(jnp.float32))
        return out.astype(q.dtype)

    def _layer(self, carry, scanned, block_tables, q_positions, kv_len_mask, write_pos):
        """One decoder layer inside lax.scan: scanned = (layer_params, pool_layer)."""
        h = carry
        lp, pool_layer = scanned
        cfg = self.config
        B, T, D = h.shape

        x = _rms(h, lp["input_layernorm"]["scale"], self.eps)
        attn = lp["self_attn"]

        def proj(p, x, heads):
            y = x @ p["kernel"].astype(self.dtype)
            if "bias" in p:
                y = y + p["bias"].astype(self.dtype)
            return y.reshape(B, T, heads, self.head_dim)

        q = proj(attn["q_proj"], x, self.n_heads)
        k = proj(attn["k_proj"], x, self.n_kv)
        v = proj(attn["v_proj"], x, self.n_kv)
        cos, sin = rope_tables(q_positions, self.inv_freq)
        q, k = apply_rotary_pos_emb(q, k, cos, sin)

        # scatter new K/V into the pool (vmapped over the batch)
        def write_one(pool_l, k_i, v_i, table_i, start_i):
            return write_kv_block(pool_l, k_i, v_i, table_i, start_i)

        pool_layer = functools.reduce(
            lambda pl, i: write_one(pl, k[i], v[i], block_tables[i], write_pos[i]),
            range(B),
            pool_layer,
        )
        k_all, v_all = gather_kv(pool_layer, block_tables)
        attn_out = self._attend(q, k_all, v_all, q_positions, kv_len_mask)
        attn_out = attn_out.reshape(B, T, self.n_heads * self.head_dim)
        o = attn_out @ attn["o_proj"]["kernel"].astype(self.dtype)
        if "bias" in attn["o_proj"]:
            o = o + attn["o_proj"]["bias"].astype(self.dtype)
        h = h + o

        x = _rms(h, lp["post_attention_layernorm"]["scale"], self.eps)
        mlp = lp["mlp"]
        gate = x @ mlp["gate_proj"]["kernel"].astype(self.dtype)
        up = x @ mlp["up_proj"]["kernel"].astype(self.dtype)
        h = h + (jax.nn.silu(gate) * up) @ mlp["down_proj"]["kernel"].astype(self.dtype)
        return h, pool_layer

    def _forward(self, params, pool_kv, input_ids, block_tables, q_positions, kv_len_mask, write_pos, last_pos):
        """input_ids [B,T]; returns (logits at last_pos [B,V], new pool kv [L,...])."""
        m = params["model"]
        embed = m["embed_tokens"]["embedding"]
        h = embed[input_ids].astype(self.dtype)
        if getattr(self.config, "scale_embeddings", False):
            h = h * jnp.asarray(self.config.hidden_size**0.5, h.dtype)

        def body(carry, scanned):
            return self._layer(carry, scanned, block_tables, q_positions, kv_len_mask, write_pos)

        h, new_pool = jax.lax.scan(body, h, (m["layers"], pool_kv))
        h = _rms(h, m["norm"]["scale"], self.eps)
        last = h[jnp.arange(h.shape[0]), last_pos]
        if "lm_head" in params:
            logits = last @ params["lm_head"]["kernel"].astype(self.dtype)
        else:
            logits = last @ embed.T.astype(self.dtype)
        return logits.astype(jnp.float32), new_pool

    # ------------------------------------------------------------------ entry points
    def _prefill_impl(self, params, pool_kv, input_ids, block_table, prompt_len):
        """One sequence [1, T_pad]; valid prefix length = prompt_len."""
        T = input_ids.shape[1]
        positions = jnp.arange(T)[None, :]
        S = block_table.shape[0] * self.block_size
        kv_len_mask = jnp.arange(S)[None, :] < prompt_len
        logits, new_pool = self._forward(
            params, pool_kv, input_ids, block_table[None], positions,
            kv_len_mask, jnp.zeros((1,), jnp.int32),
            jnp.asarray([prompt_len - 1]),  # last VALID token (input may be padded)
        )
        return logits, new_pool

    def _decode_impl(self, params, pool_kv, tokens, block_tables, context_lens):
        """tokens [B] (the next input token per seq, at position context_lens)."""
        B = tokens.shape[0]
        positions = context_lens[:, None]
        S = block_tables.shape[1] * self.block_size
        kv_len_mask = jnp.arange(S)[None, :] <= context_lens[:, None]
        logits, new_pool = self._forward(
            params, pool_kv, tokens[:, None], block_tables, positions,
            kv_len_mask, context_lens,
            jnp.zeros((B,), jnp.int32),
        )
        return logits, new_pool

    def prefill(self, params, pool: PagedKVPool, input_ids, block_table, prompt_len) -> Tuple[jnp.ndarray, PagedKVPool]:
        logits, kv = self._prefill(params, pool.kv, input_ids, block_table, prompt_len)
        return logits, PagedKVPool(kv=kv)

    def decode(self, params, pool: PagedKVPool, tokens, block_tables, context_lens) -> Tuple[jnp.ndarray, PagedKVPool]:
        logits, kv = self._decode(params, pool.kv, tokens, block_tables, context_lens)
        return logits, PagedKVPool(kv=kv)
