"""Host-RAM KV spill tier: the second level of the hierarchical prefix cache.

HBM bounds the prefix cache today — when allocation pressure pops a zero-ref
cached block off the :class:`~.paged_cache.BlockManager` LRU, its KV bytes
are simply recycled and a later identical prompt re-prefills from scratch.
This module keeps those bytes alive one level down: the engine gathers the
evicted blocks out of the device pool (one batched async D2H per step, the
:mod:`~.disagg_backend` migration gather pointed at the host) and registers
them here under the SAME chained content hashes the device index used. A
later prefix match that runs past the device index and lands on host-tier
entries promotes them back with an async H2D scatter dispatched ahead of
prefill — the PR 12 migration machinery verbatim: a data-dependent marker
scalar gates *scheduling* (``kv_stage == "promoting"`` until it lands,
overlapped with other slots' decode steps) while the pool's functional
threading already guarantees *correctness* ordering.

Invariants the tests pin:

- a chain hash is resident in the device index XOR the host tier — spill
  moves it down (``_pop_block`` unregisters, the engine ``put``s here),
  promote moves it back up (``take`` pops here, ``register_promoted``
  re-registers there). Leaks in either direction show up as double-resident
  or vanished hashes under churn.
- promoted bytes are bitwise-identical to the bytes spilled: the tier never
  touches content, so an evict-to-host-then-promote run streams the exact
  tokens a never-evicted run does.
- weight swaps invalidate the tier with the device cache
  (``clear_prefix_cache`` → :meth:`HostKVTier.clear`): a pre-swap block must
  never splice old-weights KV into post-swap traffic.

Spill batches hold the gathered device array until the *next* spill (or
their own ``take``) settles them to numpy — ``copy_to_host_async`` is
dispatched at gather time, so the eventual ``np.asarray`` finds the copy
already landed instead of blocking a hot path on D2H.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax  # noqa: F401  (jnp is the real dependency; kept for parity with siblings)
import jax.numpy as jnp
import numpy as np

__all__ = ["HostKVTier", "HostPromoteTicket", "gather_blocks", "scatter_blocks",
           "pool_block_bytes"]


def gather_blocks(src, ids):
    """Pull whole blocks (all layers, K and V planes) out of a stage pool —
    the migration gather (disagg_backend) reused for the D2H spill read."""
    return src[:, :, ids]


def scatter_blocks(dst, data, ids):
    """Land promoted blocks in the device pool. The second output is a tiny
    marker scalar data-dependent on the scatter result: it completes exactly
    when the copy has landed and — unlike the (donated-away-next-step) pool
    tensor itself — stays safe to poll with ``is_ready()``."""
    out = dst.at[:, :, ids].set(data)
    marker = (out[0, 0, 0, 0, 0, 0] * 0).astype(jnp.int32) + ids.shape[0]
    return out, marker


def pool_block_bytes(pool) -> int:
    """Bytes one block carries across the host boundary: [L, 2, K, bs, H]
    (+ the scale plane for quantized pools)."""
    kv = pool.kv
    n = int(kv.dtype.itemsize * kv.shape[0] * 2 * kv.shape[3] * kv.shape[4] * kv.shape[5])
    if pool.scale is not None:
        s = pool.scale
        n += int(s.dtype.itemsize * s.shape[0] * 2 * s.shape[3] * s.shape[4] * s.shape[5])
    return n


@dataclasses.dataclass
class HostPromoteTicket:
    """One in-flight host→device block promotion (engine-held). Shape-
    compatible with :class:`~.disagg_backend.MigrationTicket` so the engine's
    marker-poll scheduling gate (``migration_ready``) serves both."""

    seq_id: int
    n_blocks: int
    markers: tuple  # device scalars completing when each plane's copy lands
    polls: int = 0  # force-land fallback counter (engine-side scheduling)


@dataclasses.dataclass
class _SpillBatch:
    """One batched spill's payload: gathered [L, 2, n, K, bs, H] planes,
    device-resident until settled (D2H already in flight), then numpy."""

    kv: object
    scale: object  # None for unquantized pools
    live: int  # resident tier entries still pointing into this batch
    settled: bool = False

    def settle(self):
        if not self.settled:
            # the async D2H was dispatched at gather time; this materializes
            # the landed copy and drops the device buffers
            self.kv = np.asarray(self.kv)  # sync-ok: copy_to_host_async dispatched at spill time — this reads the landed host copy
            if self.scale is not None:
                self.scale = np.asarray(self.scale)  # sync-ok: same landed D2H copy, scale plane
            self.settled = True


class HostKVTier:
    """Host-side LRU of spilled prefix-cache blocks, keyed by chain hash.

    Owned by the engine loop thread exactly like the :class:`BlockManager`
    it sits under (same lock-free-by-confinement concurrency model); the
    metrics plane only reads the scalar ``stats`` counters, where a stale
    read is harmless. ``max_blocks == 0`` disables the tier (``accepting``
    False) so the manager's spill hook stays dormant.
    """

    def __init__(self, max_blocks: int, block_bytes: int = 0):
        self.max_blocks = int(max_blocks)
        self.block_bytes = int(block_bytes)
        # hash -> (batch, row index along the gathered blocks axis)
        self._entries: "OrderedDict[bytes, Tuple[_SpillBatch, int]]" = OrderedDict()
        #: monotone counters (the metrics plane deltas these) + the live size
        self.stats: Dict[str, int] = {
            "spills": 0,          # spilled blocks, total
            "spill_batches": 0,   # batched D2H dispatches, total
            "promotes": 0,        # promote (take) calls, total
            "promoted_blocks": 0,
            "promote_bytes": 0,
            "evictions": 0,       # host-LRU evictions under tier pressure
        }

    # ------------------------------------------------------------- queries
    @property
    def accepting(self) -> bool:
        return self.max_blocks > 0

    @property
    def num_blocks(self) -> int:
        """Blocks currently resident in the tier."""
        return len(self._entries)

    def contains(self, h: bytes) -> bool:
        return h in self._entries

    def snapshot(self) -> Dict[str, int]:
        out = dict(self.stats)
        out["blocks"] = len(self._entries)
        out["capacity"] = self.max_blocks
        return out

    # ------------------------------------------------------------- mutation
    def _drop_entry(self, h: bytes):
        batch, _row = self._entries.pop(h)
        batch.live -= 1

    def put(self, hashes: List[bytes], kv, scale=None):
        """Register one spill batch: ``kv``/``scale`` are the gathered
        [L, 2, n, K, bs, H] planes (rows beyond ``len(hashes)`` are pow2
        padding and never referenced) with their D2H copies already in
        flight. Earlier batches settle to numpy here — one batch of deferral
        means the async copy has had a full engine step to land."""
        if not self.accepting or not hashes:
            return
        for _h, (batch, _row) in list(self._entries.items()):
            batch.settle()
        new = _SpillBatch(kv=kv, scale=scale, live=0)
        for row, h in enumerate(hashes):
            if h in self._entries:
                # re-spill of a hash already resident: newest content wins
                # (identical bytes by content-addressing, but the old batch
                # must drop its reference either way)
                self._drop_entry(h)
            self._entries[h] = (new, row)
            self._entries.move_to_end(h)
            new.live += 1
        self.stats["spills"] += len(hashes)
        self.stats["spill_batches"] += 1
        while len(self._entries) > self.max_blocks:
            oldest = next(iter(self._entries))
            self._drop_entry(oldest)
            self.stats["evictions"] += 1

    def take(self, hashes: List[bytes]):
        """Pop ``hashes`` (resident-XOR invariant: a promoted hash leaves the
        tier — the engine re-registers it in the device index) and return
        their stacked planes ``(kv [L, 2, m, K, bs, H], scale | None,
        nbytes)`` ready for the H2D scatter."""
        kv_rows, scale_rows = [], []
        for h in hashes:
            batch, row = self._entries[h]
            batch.settle()
            kv_rows.append(batch.kv[:, :, row])
            if batch.scale is not None:
                scale_rows.append(batch.scale[:, :, row])
            self._drop_entry(h)
        kv = np.stack(kv_rows, axis=2)
        scale = np.stack(scale_rows, axis=2) if scale_rows else None
        nbytes = len(hashes) * self.block_bytes
        self.stats["promotes"] += 1
        self.stats["promoted_blocks"] += len(hashes)
        self.stats["promote_bytes"] += nbytes
        return kv, scale, nbytes

    def discard(self, h: bytes):
        """Drop one hash if resident — the device index just (re-)claimed it
        (cold re-prefill of a spilled span), and resident-XOR says the tier
        copy yields. Content-addressing makes the two copies identical, so
        this is bookkeeping, not invalidation."""
        if h in self._entries:
            self._drop_entry(h)

    def clear(self):
        """Invalidate every resident block (weight swap / cache-epoch bump:
        pre-swap KV must never serve post-swap traffic)."""
        for h in list(self._entries):
            self._drop_entry(h)
