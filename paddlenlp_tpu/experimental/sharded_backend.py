"""Tensor-parallel serving backend: one engine replica spans a device mesh.

The router (serving/router/) scales the fleet *out* over identical
single-chip replicas; this backend scales a replica *up* — weights and the
paged KV pool are laid out with ``jax.sharding.NamedSharding`` over a
``parallel.mesh`` Mesh, and every jitted step program is compiled with
explicit ``in_shardings``/``out_shardings`` so XLA inserts the collectives
(the serving twin of *Scalable Training of Language Models using JAX pjit
and TPUv4*). The engine's scheduler, BlockManager, prefix cache, chunked
prefill and supervisor all run unchanged on top: they only ever see host
numpy and the backend interface.

Layout — all-gather tensor parallelism on the ``tp`` axis:

=========================  =================================================
tensor                     sharding (when the dim divides tp; else replicated)
=========================  =================================================
embed_tokens.embedding     vocab rows sharded
q/k/v_proj kernels+bias    output (heads) sharded — column parallel
o_proj / down_proj kernel  output (hidden) sharded — column parallel
gate/up_proj kernels+bias  output (ffn) sharded
lm_head kernel             output (vocab) sharded
KV pool [L,2,nb,K,bs,H]    kv-heads axis sharded; blocks/batch replicated
activations                heads/ffn dims sharded between anchors; the
                           residual stream, logits, penalty counts replicated
=========================  =================================================

Every contraction reads *replicated* operands on its contraction dim (the
``_hint(..., "full")`` anchors in inference_model.py force an all-gather
first), so each output element is the SAME floating-point reduction as the
single-device program — the sharded engine is bitwise token-identical to
:class:`~.backend.SingleDeviceBackend`, which is what the parity suite
asserts. The classic row-parallel alternative (partial dots + psum) moves
less data but reorders the o_proj/down_proj reductions; flipping those two
rules to ``P("tp", None)`` buys it back where bit-exactness doesn't matter.

``dp`` (the leading axis of ``mesh_shape=(dp, tp)``) currently replicates —
it is the seam for data-parallel batch sharding and for the two-stage MPMD
prefill/decode split (stage = dp slice, KV migrating between stage pools;
see backend.py's seam note) without another engine refactor.

Testable anywhere: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
gives an 8-way CPU mesh.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import MeshConfig, create_mesh
from ..parallel.partition import spec_tree_from_rules
from ..utils.faults import FaultPoint
from ..utils.log import logger
from .backend import SingleDeviceBackend
from .kv_host_tier import gather_blocks, scatter_blocks
from .inference_model import PagedInferenceModel
from .paged_cache import PagedKVPool

__all__ = ["ShardedBackend", "ShardedPagedInferenceModel", "serving_partition_rules"]

_F_SHARD_INIT = FaultPoint("engine.shard_init")

#: identity logical->physical mapping: the serving rules below name mesh axes
#: directly ("tp"); `layers` is the auto-prepended leading axis of scanned
#: param stacks and stays unsharded here (pp is a training concern).
_IDENTITY_RULES = {"tp": "tp", "layers": None}


def serving_partition_rules(config, tp: int):
    """[(param-path regex, physical PartitionSpec)] for the serving layout.

    Head-bearing dims are gated on head-count divisibility (an aligned split
    keeps the per-head attention compute local to a shard); vocab/ffn/hidden
    dims rely on `resolve_spec`'s shape check to fall back to replication.
    ``(kernel|qweight)`` covers weight-only-quantized serving params — their
    per-channel scales replicate via the catch-all."""
    n_heads = config.num_attention_heads
    n_kv = getattr(config, "num_key_value_heads", n_heads)
    rules = []
    if n_heads % tp == 0:
        rules += [
            (r"self_attn/q_proj/(kernel|qweight)$", P(None, "tp")),
            (r"self_attn/q_proj/bias$", P("tp")),
        ]
    if n_kv % tp == 0:
        rules += [
            (r"self_attn/[kv]_proj/(kernel|qweight)$", P(None, "tp")),
            (r"self_attn/[kv]_proj/bias$", P("tp")),
        ]
    rules += [
        (r"embed_tokens/embedding$", P("tp", None)),
        (r"(lm_head|score)/kernel$", P(None, "tp")),
        (r"mlp/(gate_proj|up_proj)/(kernel|qweight)$", P(None, "tp")),
        (r"mlp/(gate_proj|up_proj)/bias$", P("tp")),
        (r"self_attn/o_proj/(kernel|qweight)$", P(None, "tp")),
        (r"self_attn/o_proj/bias$", P("tp")),
        (r"mlp/down_proj/(kernel|qweight)$", P(None, "tp")),
        (r"mlp/down_proj/bias$", P("tp")),
        (r".*", P()),
    ]
    return rules


def _normalize_mesh_shape(mesh_shape) -> MeshConfig:
    """int tp | (dp, tp) | MeshConfig -> MeshConfig."""
    if isinstance(mesh_shape, MeshConfig):
        return mesh_shape
    if isinstance(mesh_shape, int):
        return MeshConfig(dp=1, tp=mesh_shape)
    if isinstance(mesh_shape, (tuple, list)) and len(mesh_shape) == 2:
        return MeshConfig(dp=int(mesh_shape[0]), tp=int(mesh_shape[1]))
    raise ValueError(
        f"mesh_shape must be an int tp degree, a (dp, tp) pair or a MeshConfig; "
        f"got {mesh_shape!r}")


class ShardedPagedInferenceModel(PagedInferenceModel):
    """PagedInferenceModel whose jitted steps carry explicit shardings.

    Construction needs the model params (to build the param sharding tree)
    and whether the pool is quantized (its structure). The activation
    ``_hint`` anchors implement the all-gather layout described in the
    module docstring."""

    def __init__(self, model, *args, mesh, kv_quantized: bool = False,
                 lora_enabled: bool = False, **kw):
        self.mesh = mesh
        self.tp = int(mesh.shape["tp"])
        self._repl = NamedSharding(mesh, P())
        rules = serving_partition_rules(model.config, self.tp)
        self.param_specs = spec_tree_from_rules(model.params, rules, mesh, _IDENTITY_RULES)
        self.param_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), self.param_specs)
        n_kv = getattr(model.config, "num_key_value_heads", model.config.num_attention_heads)
        self.pool_spec = (P(None, None, None, "tp", None, None)
                          if n_kv % self.tp == 0 else P())
        pool_ns = NamedSharding(mesh, self.pool_spec)
        self.pool_shardings = PagedKVPool(kv=pool_ns, scale=pool_ns if kv_quantized else None)
        self.lora_specs, self.lora_shardings = self._lora_layout(model.config, lora_enabled)
        super().__init__(model, *args, **kw)

    def _lora_layout(self, config, lora_enabled: bool):
        """(spec tree, sharding tree) for the adapter pool argument.

        Adapter weights follow the column-parallel rules of the projections
        they patch: ``B`` [L, P, r, d_out] shards its output dim on ``tp``
        exactly when the base kernel's output dim does (else replication —
        the same fallback `serving_partition_rules` uses), and ``A`` is
        always replicated (its output dim is the tiny rank r). ``x @ A``
        then reads a replicated operand, and ``(xA) @ B`` produces a
        tp-sharded delta that lands on ``base(x)``'s identical layout before
        the `_hint` anchors re-gather — the reduction ORDER matches the
        single-device program, preserving bitwise token identity.

        LoRA off -> the lora argument is always None (an empty pytree), and
        a single replicated leaf serves as its universal tree prefix."""
        if not lora_enabled:
            return None, self._repl
        from ..serving.tenancy.adapters import adapter_dims_from_config
        specs = {}
        for proj, (_d_in, d_out) in adapter_dims_from_config(config).items():
            b_spec = P(None, None, None, "tp") if d_out % self.tp == 0 else P()
            specs[proj] = {"A": P(), "B": b_spec}
        shardings = jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs,
                                 is_leaf=lambda x: isinstance(x, P))
        return specs, shardings

    def _hint(self, x, kind: str):
        if self.tp == 1:
            return x
        if kind == "full":
            spec = P()
        elif kind in ("heads", "kv_heads"):
            if x.shape[2] % self.tp != 0:
                return x
            spec = P(None, None, "tp", None)
        elif kind == "mlp":
            if x.shape[-1] % self.tp != 0:
                return x
            spec = P(None, None, "tp")
        else:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(self.mesh, spec))

    def _build_jits(self):
        # every step's trailing args are the multi-LoRA pair(s): the adapter
        # pool (column-parallel / replicated per _lora_layout; a replicated
        # prefix when LoRA is off and the arg is always None) and the
        # replicated per-row slot indices.
        ps, pool_s, r = self.param_shardings, self.pool_shardings, self._repl
        lora_s = self.lora_shardings
        self._prefill = jax.jit(
            self._prefill_impl, donate_argnums=(1,),
            in_shardings=(ps, pool_s) + (r,) * 6 + (lora_s, r),
            out_shardings=(r, r, pool_s))
        self._decode = jax.jit(
            self._decode_impl, donate_argnums=(1,),
            in_shardings=(ps, pool_s) + (r,) * 7 + (lora_s, r),
            out_shardings=(r, r, r, r, r, pool_s))
        self._verify = jax.jit(
            self._verify_impl, donate_argnums=(1,), static_argnames=("need_logits",),
            in_shardings=(ps, pool_s) + (r,) * 3 + (lora_s, r),
            out_shardings=(r, r, pool_s))
        self._mixed = jax.jit(
            self._mixed_impl, donate_argnums=(1,),
            in_shardings=(ps, pool_s) + (r,) * 8 + (lora_s, r),
            out_shardings=(r, r, pool_s))
        self._mixed_flat = jax.jit(
            self._mixed_flat_impl, donate_argnums=(1,),
            in_shardings=(ps, pool_s) + (r,) * 13 + (lora_s, r, r),
            out_shardings=(r, r, pool_s))


class ShardedBackend(SingleDeviceBackend):
    """Engine backend running the forward + KV pool over a device mesh.

    ``InferenceEngine(mesh_shape=...)`` selects it. Params are device_put
    once with their NamedShardings and re-put only when ``model.params`` is
    rebound (a serving weight update); the pool and counts live sharded /
    replicated on the mesh for their whole life."""

    def __init__(self, model, *, mesh_shape, devices=None, stage=None, **kw):
        # surfaced as a named fault point: mesh/layout init is the first
        # thing a supervisor rebuild of a sharded engine replays, and chaos
        # coverage needs it to fail deterministically. Staged (disagg)
        # backends construct one ShardedBackend per stage, so the fault fires
        # once per stage rebuild — `stage` labels which one.
        _F_SHARD_INIT.fire(stage=stage or "all")
        self.stage = stage  # None = whole-replica backend; "prefill"/"decode" = disagg stage
        config = _normalize_mesh_shape(mesh_shape)
        if devices is None:
            devices = jax.devices()
        else:
            devices = list(devices)
        if config.dp == -1:  # MeshConfig callers may leave dp to absorb
            config = config.resolve(len(devices))
        n_dev = config.dp * config.fsdp * config.pp * config.sep * config.cp * config.tp
        if n_dev > len(devices):
            raise ValueError(
                f"mesh_shape {mesh_shape!r} needs {n_dev} devices, "
                f"{len(devices)} available (CPU runs: set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n_dev})")
        self.mesh = create_mesh(config, devices=devices[:n_dev])
        self.mesh_config = config
        self._kv_quantized = kw.get("kv_cache_quant") is not None
        super().__init__(model, **kw)
        self._params_src = model.params
        self._params = jax.device_put(model.params, self.infer.param_shardings)
        n_kv = getattr(model.config, "num_key_value_heads", model.config.num_attention_heads)
        if n_kv % config.tp != 0:
            logger.warning(
                f"sharded backend: num_key_value_heads={n_kv} not divisible by "
                f"tp={config.tp}; KV pool and attention run replicated")

    # ---------------------------------------------------------------- setup
    def _build_infer(self, model, block_size, num_blocks, max_blocks_per_seq,
                     dtype, decode_steps, eos_ids):
        return ShardedPagedInferenceModel(
            model, block_size, num_blocks, max_blocks_per_seq, dtype=dtype,
            decode_steps=decode_steps, eos_ids=eos_ids,
            mesh=self.mesh, kv_quantized=self._kv_quantized,
            lora_enabled=self.adapter_registry is not None,
        )

    def _init_pool(self, config, num_blocks, block_size, dtype, quant):
        pool = super()._init_pool(config, num_blocks, block_size, dtype, quant)
        return jax.device_put(pool, self.infer.pool_shardings)

    def _place_lora(self, host_pool):
        # adapter pool lands with its column-parallel/replicated layout so
        # dispatch never re-shards it against the jits' in_shardings
        return jax.device_put(host_pool, self.infer.lora_shardings)

    def _init_counts(self):
        return jax.device_put(super()._init_counts(), self.infer._repl)

    def _build_host_tier_jits(self):
        # host-tier spill/promote with the step programs' explicit-placement
        # contract: gather/scatter on the pool's sharding (the block-slice
        # layout equals the pool layout — the kv-heads axis shards, blocks
        # replicate), ids and the marker replicated, scatter pool donated.
        # The kv sharding serves the scale plane too: same NamedSharding,
        # same axis-3 split.
        kv_s = self.infer.pool_shardings.kv
        r = self.infer._repl
        gather = jax.jit(gather_blocks, donate_argnums=(),
                         in_shardings=(kv_s, r), out_shardings=kv_s)
        scatter = jax.jit(scatter_blocks, donate_argnums=(0,),
                          in_shardings=(kv_s, kv_s, r), out_shardings=(kv_s, r))
        return gather, scatter

    def _place_host_blocks(self, data):
        # promoted rows land pre-placed on the pool layout so the scatter jit
        # never reshards its data operand at dispatch
        return jax.device_put(data, self.infer.pool_shardings.kv)

    @property
    def params(self):
        # a weight update rebinds model.params: re-place it on the mesh once,
        # not per step (id check is one pointer compare on the hot path)
        if self.model.params is not self._params_src:
            self._params_src = self.model.params
            self._params = jax.device_put(self.model.params, self.infer.param_shardings)
        return self._params

    def sync_params(self, new_params):
        # eager re-place with the EXISTING NamedSharding layout: placement
        # failures surface here (inside a swap's rollback window), and the
        # id-check in the params property then sees a settled rebind
        placed = jax.device_put(new_params, self.infer.param_shardings)
        self.model.params = new_params
        self._params_src = new_params
        self._params = placed

    def describe(self) -> dict:
        axes = {k: int(v) for k, v in self.mesh.shape.items()}
        out = {
            "kind": "sharded",
            "devices": int(self.mesh.size),
            "tp_degree": axes.get("tp", 1),
            "mesh": axes,
            "mesh_shape": [self.mesh_config.dp, self.mesh_config.tp],
            "kv_pool_sharded": self.infer.pool_spec != P(),
        }
        if self.stage is not None:
            out["stage"] = self.stage
        return out

