"""Disaggregated prefill/decode serving backend: MPMD two-stage execution.

The third :class:`~.backend.ModelBackend` implementation — the stage-split
PR 8 reserved the seam for (backend.py's "MPMD stage-split seam" note, per
*Scaling Deep Learning Training with MPMD Pipeline Parallelism*). Chunked
prefill time-slices the TTFT-vs-inter-token contention on one device group;
this backend *removes* it: prompt processing (monolithic prefill and the
chunk rows of mixed steps) executes on a **prefill stage** and decode rows on
a **decode stage**, each its own device group with its own tp layout, sized
independently (``stages=(P, D)`` device counts).

Layout — two disjoint sub-meshes of the ``(dp, tp)`` mesh:

- each stage is a :class:`~.sharded_backend.ShardedBackend` pinned to an
  explicit device slice (``devices[:P]`` / ``devices[P:P+D]``), so each stage
  keeps the all-gather column-parallel layout that is bitwise token-identical
  to :class:`~.backend.SingleDeviceBackend` — the disagg engine inherits the
  token-identity contract stage by stage;
- both stages allocate a **full-size paged pool** over ONE shared block-id
  space (the engine's single ``BlockManager``): a block id addresses the same
  logical block in either pool, so the engine's block tables stay valid on
  both stages and migration never rewrites a table — only the pool tensor
  behind it moves.

**KV-block migration.** A sequence's prompt KV is written on the prefill
stage; decode reads it on the decode stage. When the last prefill chunk lands
(first token sampled), the engine calls :meth:`DisaggBackend.kv_migrate`: the
sequence's table blocks are gathered on the prefill mesh, ``device_put``
across meshes, and scattered into the decode pool — all async dispatches the
host never blocks on, so the copy stream overlaps subsequent decode steps.
Correctness needs no gate at all (the decode pool tensor is threaded
functionally, so XLA orders the scatter before any later decode read); the
``migration_ready`` poll is the *scheduling* gate — a sequence becomes
decode-eligible only once its blocks have landed, so a decode step never
stalls on an in-flight copy. Per-sequence penalty counts migrate as a
host-truth re-seed (bincount of ``prompt + emitted``, exactly the engine's
quarantine ``resync_counts`` rule) — the same integers the prefill stage
accumulated, so penalty sampling stays token-exact across the handoff.

Shared prefix-cache blocks live in BOTH pools: their content is written once
on the prefill stage (chunk attention reads them there) and copied to the
decode pool by every migration that references them — identical bytes, so
concurrent re-copies are idempotent. COW copies run on the prefill pool only
(the re-prefilled tail is prefill-stage work); migration carries the result
across.

Testable anywhere: ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
backs both stages with virtual CPU devices, and the parity suite
(tests/experimental/test_disagg_backend.py) asserts bitwise token identity
against the single-device engine.
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import logger
from .backend import MixedRow, ModelBackend
from .paged_cache import PagedKVPool
from .sharded_backend import ShardedBackend

__all__ = ["DisaggBackend", "MigrationTicket"]


def _normalize_stages(stages) -> Tuple[int, int]:
    """``(P, D)`` device counts for the prefill / decode stages."""
    if isinstance(stages, (tuple, list)) and len(stages) == 2:
        p, d = int(stages[0]), int(stages[1])
        if p >= 1 and d >= 1:
            return p, d
    raise ValueError(
        f"disagg stages must be a (prefill_devices, decode_devices) pair of "
        f"positive ints; got {stages!r}")


def _gather_blocks(src, ids):
    """Pull whole blocks (all layers, K and V planes) out of one stage's pool."""
    return src[:, :, ids]


def _scatter_blocks(dst, data, ids):
    """Land migrated blocks in the destination pool. The second output is a
    tiny marker scalar data-dependent on the scatter result: it completes
    exactly when the copy has landed and — unlike the (donated-away-next-step)
    pool tensor itself — stays safe to poll with ``is_ready()``."""
    out = dst.at[:, :, ids].set(data)
    marker = (out[0, 0, 0, 0, 0, 0] * 0).astype(jnp.int32) + ids.shape[0]
    return out, marker


@dataclasses.dataclass
class MigrationTicket:
    """One in-flight prefill→decode block migration (engine-held)."""

    seq_id: int
    n_blocks: int
    markers: tuple  # device scalars completing when each plane's copy lands
    polls: int = 0  # force-land fallback counter (engine-side scheduling)


class DisaggBackend(ModelBackend):
    """Two-stage MPMD backend: prefill rows on one device group, decode rows
    on another, paged KV blocks migrating between the stage pools.

    ``InferenceEngine(disagg_stages=(P, D))`` selects it. The engine's
    scheduler stays device-free: it sees the ordinary backend interface plus
    the three migration hooks (:meth:`kv_migrate`, :meth:`migration_ready`,
    ``migration_stats``) and owns all migration *scheduling* (stage-aware
    admission, the decode-pressure gate, the in-flight bound)."""

    #: engines check this to enable migration scheduling
    staged = True

    def __init__(self, model, *, stages, **kw):
        p_devs, d_devs = _normalize_stages(stages)
        devices = jax.devices()
        if p_devs + d_devs > len(devices):
            raise ValueError(
                f"disagg stages {stages!r} need {p_devs + d_devs} devices, "
                f"{len(devices)} available (CPU runs: set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={p_devs + d_devs})")
        self.model = model
        self.max_batch_size = kw["max_batch_size"]
        self.step_accounting = {"fed": 0, "shape": ()}
        # two disjoint sub-meshes: each stage is a full ShardedBackend over its
        # own device slice (engine.shard_init fires once per stage, so a
        # supervisor rebuild of either stage is chaos-coverable)
        self.prefill_stage = ShardedBackend(
            model, mesh_shape=(1, p_devs), devices=devices[:p_devs],
            stage="prefill", **kw)
        self.decode_stage = ShardedBackend(
            model, mesh_shape=(1, d_devs), devices=devices[p_devs:p_devs + d_devs],
            stage="decode", **kw)
        self._build_migration_jits()
        kv = self.decode_stage.pool.kv
        # bytes one block carries across the wire: [L, 2, K, bs, H] (+ scale)
        self._block_bytes = int(
            kv.dtype.itemsize * kv.shape[0] * 2 * kv.shape[3] * kv.shape[4] * kv.shape[5])
        if self.decode_stage.pool.scale is not None:
            s = self.decode_stage.pool.scale
            self._block_bytes += int(
                s.dtype.itemsize * s.shape[0] * 2 * s.shape[3] * s.shape[4] * s.shape[5])
        # monotone migration accounting + a bounded (seq, blocks, bytes) event
        # ring the metrics plane drains by sequence number (same contract as
        # the engine's chunk rings: stats() reads never consume events)
        self.migration_stats = {"migrations": 0, "blocks": 0, "bytes": 0}
        self.recent_migrations: deque = deque(maxlen=256)
        self._mig_seq = itertools.count(1)
        if p_devs != d_devs:
            logger.info(
                f"disagg backend: asymmetric stages prefill={p_devs} decode={d_devs} "
                "(independent tp layouts; migration reshards in flight)")

    def _build_migration_jits(self):
        """Migration copy programs, compiled with the same explicit-placement
        contract as every other step program (sharding-contract checker):
        gather on the prefill mesh, scatter (pool donated) on the decode
        mesh. The cross-mesh hop itself is a ``device_put`` at call time."""
        p_inf, d_inf = self.prefill_stage.infer, self.decode_stage.infer
        p_kv_s = p_inf.pool_shardings.kv
        d_kv_s = d_inf.pool_shardings.kv
        self._kv_data_sharding = d_kv_s  # block-slice layout == pool layout
        self._gather_kv = jax.jit(
            _gather_blocks, donate_argnums=(),
            in_shardings=(p_kv_s, p_inf._repl), out_shardings=p_kv_s)
        self._scatter_kv = jax.jit(
            _scatter_blocks, donate_argnums=(0,),
            in_shardings=(d_kv_s, d_kv_s, d_inf._repl),
            out_shardings=(d_kv_s, d_inf._repl))
        # the reverse direction (decode→prefill) serves kv_writeback:
        # generated-token KV exists only in the decode pool, but prefix-cache
        # reads (chunk attention, host-tier spills) happen on the prefill
        # stage — registering generated blocks requires carrying them back
        self._gather_kv_back = jax.jit(
            _gather_blocks, donate_argnums=(),
            in_shardings=(d_kv_s, d_inf._repl), out_shardings=d_kv_s)
        self._scatter_kv_back = jax.jit(
            _scatter_blocks, donate_argnums=(0,),
            in_shardings=(p_kv_s, p_kv_s, p_inf._repl),
            out_shardings=(p_kv_s, p_inf._repl))
        self._kv_back_sharding = p_kv_s
        if self.decode_stage.pool.scale is not None:
            p_s = p_inf.pool_shardings.scale
            d_s = d_inf.pool_shardings.scale
            self._scale_data_sharding = d_s
            self._gather_scale = jax.jit(
                _gather_blocks, donate_argnums=(),
                in_shardings=(p_s, p_inf._repl), out_shardings=p_s)
            self._scatter_scale = jax.jit(
                _scatter_blocks, donate_argnums=(0,),
                in_shardings=(d_s, d_s, d_inf._repl),
                out_shardings=(d_s, d_inf._repl))
            self._gather_scale_back = jax.jit(
                _gather_blocks, donate_argnums=(),
                in_shardings=(d_s, d_inf._repl), out_shardings=d_s)
            self._scatter_scale_back = jax.jit(
                _scatter_blocks, donate_argnums=(0,),
                in_shardings=(p_s, p_s, p_inf._repl),
                out_shardings=(p_s, p_inf._repl))
            self._scale_back_sharding = p_s

    # ------------------------------------------------------------- device state
    # the decode stage is "the" pool/counts/infer for read paths that predate
    # the stage split (tests, tools, the metrics plane): decode is where
    # sequences live for most of their lifetime
    @property
    def infer(self):
        return self.decode_stage.infer

    @property
    def pool(self):
        return self.decode_stage.pool

    @property
    def counts(self):
        return self.decode_stage.counts

    @property
    def params(self):
        return self.decode_stage.params

    def sync_params(self, new_params):
        """Atomic two-stage resync: both stage placements are staged BEFORE
        either stage's binding moves, so no step can ever launch prefill rows
        on one weight version and decode rows on the other — if the second
        ``device_put`` raises, neither stage changed. Each stage keeps its own
        mesh/NamedSharding layout; pools, counts and in-flight migrations are
        untouched (KV is invalidated one level up via the prefix-cache
        epoch)."""
        p_placed = jax.device_put(new_params, self.prefill_stage.infer.param_shardings)
        d_placed = jax.device_put(new_params, self.decode_stage.infer.param_shardings)
        self.model.params = new_params
        self.prefill_stage._params_src = new_params
        self.prefill_stage._params = p_placed
        self.decode_stage._params_src = new_params
        self.decode_stage._params = d_placed

    # ------------------------------------------------------------- steps
    def prefill(self, input_ids, block_tables, suffix_lens, cached_entries,
                sampling, slot_idx, adapter_table=None):
        out = self.prefill_stage.prefill(
            input_ids, block_tables, suffix_lens, cached_entries, sampling, slot_idx,
            adapter_table=adapter_table)
        self.step_accounting = self.prefill_stage.step_accounting
        return out

    def decode(self, last_tokens, block_tables, context_lens, done0, remaining,
               sampling, adapter_table=None):
        out = self.decode_stage.decode(
            last_tokens, block_tables, context_lens, done0, remaining, sampling,
            adapter_table=adapter_table)
        self.step_accounting = self.decode_stage.step_accounting
        return out

    def verify(self, tokens, block_tables, start_pos, need_logits: bool,
               adapter_table=None):
        out = self.decode_stage.verify(tokens, block_tables, start_pos, need_logits,
                                       adapter_table=adapter_table)
        self.step_accounting = self.decode_stage.step_accounting
        return out

    def mixed_step(self, chunk_rows: List[MixedRow], decode_rows: List[MixedRow]):
        """One engine mixed step = up to TWO stage programs: chunk rows on the
        prefill stage, decode rows on the decode stage — distinct programs on
        distinct device groups (the MPMD split). BOTH programs are dispatched
        before either is collected, so the stages compute concurrently: a
        decode row never waits on the host serializing it behind a chunk
        forward (the whole point of disaggregation, preserved off-TPU too).
        Returns tokens in ``[*chunk_rows, *decode_rows]`` order, the
        single-backend contract."""
        collectors = []
        fed = 0
        shapes = []
        if chunk_rows:
            collectors.append(self.prefill_stage.mixed_step_begin(chunk_rows, []))
            fed += self.prefill_stage.step_accounting["fed"]
            shapes.append(("stage_prefill",) + self.prefill_stage.step_accounting["shape"])
        if decode_rows:
            collectors.append(self.decode_stage.mixed_step_begin([], decode_rows))
            fed += self.decode_stage.step_accounting["fed"]
            shapes.append(("stage_decode",) + self.decode_stage.step_accounting["shape"])
        # one engine mixed step = the SUM of both stage launches: the goodput
        # ledger accounts device positions burnt fleet-of-stages-wide
        self.step_accounting = {"fed": fed, "shape": tuple(shapes)}
        if not collectors:
            return np.zeros(0, np.int32)
        return np.concatenate([collect() for collect in collectors])

    def apply_cow(self, pairs):
        # COW serves the re-prefill of the tail token — prefill-stage work;
        # migration carries the private copy into the decode pool later
        self.prefill_stage.apply_cow(pairs)

    def seed_counts(self, slot_idx, cached_entries):
        # chunk rows accumulate onto the prefill counts; the decode row is
        # re-seeded at migration. Seeding BOTH keeps either stage's row exact
        # for whichever program touches the slot next (quarantine resyncs
        # land here too, where live slots may sit on either stage).
        self.prefill_stage.seed_counts(slot_idx, cached_entries)
        self.decode_stage.seed_counts(slot_idx, cached_entries)

    def reset_counts(self):
        self.prefill_stage.reset_counts()
        self.decode_stage.reset_counts()

    # ------------------------------------------------------------- migration
    def kv_migrate(self, seq_id: int, blocks: Sequence[int], slot: int,
                   token_hist) -> MigrationTicket:
        """Start moving one sequence's KV blocks prefill→decode.

        Everything here is an async dispatch: gather on the prefill mesh,
        cross-mesh ``device_put``, scatter into the (donated) decode pool.
        The new decode pool is bound immediately — later decode steps are
        functionally ordered after the copy — and the returned ticket's
        markers tell the engine when the blocks have physically landed.
        ``token_hist`` (host ids: prefilled prompt + emitted tokens) re-seeds
        the slot's decode-stage penalty counts exactly."""
        ids = [int(b) for b in blocks]
        n = len(ids)
        # pad to pow2 with sentinel self-copies (block 0 is never a live dst),
        # bounding the gather/scatter to log2(max_blocks_per_seq) compiles
        padded = 1
        while padded < max(n, 1):
            padded *= 2
        ids_arr = jnp.asarray(ids + [0] * (padded - n), jnp.int32)
        src = self._gather_kv(self.prefill_stage.pool.kv, ids_arr)
        moved = jax.device_put(src, self._kv_data_sharding)
        new_kv, marker = self._scatter_kv(self.decode_stage.pool.kv, moved, ids_arr)
        markers = [marker]
        scale = self.decode_stage.pool.scale
        if scale is not None:
            s_src = self._gather_scale(self.prefill_stage.pool.scale, ids_arr)
            s_moved = jax.device_put(s_src, self._scale_data_sharding)
            scale, s_marker = self._scatter_scale(scale, s_moved, ids_arr)
            markers.append(s_marker)
        self.decode_stage.pool = PagedKVPool(kv=new_kv, scale=scale)
        self.decode_stage.seed_counts([slot], [(0, token_hist, len(token_hist))])
        moved_bytes = n * self._block_bytes
        self.migration_stats["migrations"] += 1
        self.migration_stats["blocks"] += n
        self.migration_stats["bytes"] += moved_bytes
        self.recent_migrations.append((next(self._mig_seq), n, moved_bytes))
        return MigrationTicket(seq_id=seq_id, n_blocks=n, markers=tuple(markers))

    # migration_ready: inherited from ModelBackend — the marker poll is the
    # same non-blocking scheduling signal for stage migrations and host-tier
    # promotions (correctness never needs it; functional threading orders
    # every pool read after the copy).

    # ------------------------------------------------------------- host tier
    # Registered prefix blocks live canonically in the PREFILL pool (written
    # there by chunk/prefill work, carried to decode by migrations), so the
    # hierarchical tier spills from and promotes into the prefill stage; a
    # promoted sequence's ordinary prefill→decode migration then carries the
    # promoted blocks across like any other prefix hit.
    def kv_spill(self, block_ids):
        return self.prefill_stage.kv_spill(block_ids)

    def kv_promote(self, seq_id, block_ids, host_kv, host_scale=None):
        return self.prefill_stage.kv_promote(seq_id, block_ids, host_kv,
                                             host_scale=host_scale)

    def kv_writeback(self, block_ids):
        """Carry generated-token KV decode→prefill so the blocks can join the
        prefix index: async gather on the decode mesh, cross-mesh
        ``device_put``, scatter into the (donated) prefill pool — kv_migrate
        run in reverse, with the same sentinel padding. No ticket: nothing
        gates on the landing (future prefill reads are functionally ordered
        after the scatter)."""
        ids = [int(b) for b in block_ids]
        n = len(ids)
        padded = 1
        while padded < max(n, 1):
            padded *= 2
        ids_arr = jnp.asarray(ids + [0] * (padded - n), jnp.int32)
        src = self._gather_kv_back(self.decode_stage.pool.kv, ids_arr)
        moved = jax.device_put(src, self._kv_back_sharding)
        new_kv, _ = self._scatter_kv_back(self.prefill_stage.pool.kv, moved, ids_arr)
        scale = self.prefill_stage.pool.scale
        if scale is not None:
            s_src = self._gather_scale_back(self.decode_stage.pool.scale, ids_arr)
            s_moved = jax.device_put(s_src, self._scale_back_sharding)
            scale, _ = self._scatter_scale_back(scale, s_moved, ids_arr)
        self.prefill_stage.pool = PagedKVPool(kv=new_kv, scale=scale)
        return None

    # ------------------------------------------------------------- misc
    def describe(self) -> dict:
        p, d = self.prefill_stage.describe(), self.decode_stage.describe()
        return {
            "kind": "disagg",
            "devices": p["devices"] + d["devices"],
            "tp_degree": d["tp_degree"],  # decode is the steady-state stage
            "mesh": {"prefill_tp": p["tp_degree"], "decode_tp": d["tp_degree"]},
            "stages": {"prefill": p, "decode": d},
            "kv_pool_sharded": d["kv_pool_sharded"],
        }
