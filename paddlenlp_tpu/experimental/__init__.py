from .backend import MixedRow, ModelBackend, SingleDeviceBackend  # noqa: F401
from .disagg_backend import DisaggBackend  # noqa: F401
from .engine import InferenceEngine, Request, SamplingParams  # noqa: F401
from .inference_model import PagedInferenceModel  # noqa: F401
from .kv_host_tier import HostKVTier, HostPromoteTicket  # noqa: F401
from .paged_cache import BlockManager, PagedKVPool, init_paged_pool  # noqa: F401
