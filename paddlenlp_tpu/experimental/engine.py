"""Continuous-batching inference engine.

Counterpart of the reference's dynamic-insertion serving loop:
``GenerationBlockInferenceModel.sample`` per-token loop
(experimental/transformers/generation_utils.py:403) + the ``step_paddle`` block
scheduler (csrc/gpu/step.cu:316 — dispatch/free/preempt/recover). Host-side
scheduler + two jitted device programs (bucketed prefill, fixed-shape decode):

- admission: waiting requests prefill one-at-a-time into freshly allocated block
  tables (prompt lengths bucketed to powers of two to bound retraces);
- decode: ALL running sequences advance one token per step in a single fixed
  [max_batch_size] jit — empty slots point at the sentinel block and are masked;
- preemption: on block exhaustion the youngest sequence is evicted and requeued
  with prompt+generated as its new prompt (recompute-style recovery, the
  ``is_block_step``/recover list of step.cu);
- streaming: per-request callbacks fire as tokens land (the reference pushes
  tokens over a SysV message queue to the serving process; in-process callbacks
  replace the IPC hop).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import logger
from .inference_model import PagedInferenceModel
from .paged_cache import BlockManager, init_paged_pool

__all__ = ["InferenceEngine", "Request", "SamplingParams"]


@dataclasses.dataclass
class SamplingParams:
    max_new_tokens: int = 64
    do_sample: bool = False
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int = 0


@dataclasses.dataclass
class Request:
    req_id: int
    prompt_ids: np.ndarray
    sampling: SamplingParams
    output_ids: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    stream_cb: Optional[Callable[[int, bool], None]] = None
    _rng: Optional[np.random.Generator] = None
    arrival_t: float = 0.0
    first_token_t: Optional[float] = None

    @property
    def total_len(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)


def _bucket(n: int, minimum: int = 16) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class InferenceEngine:
    def __init__(
        self,
        model,
        tokenizer=None,
        max_batch_size: int = 8,
        block_size: int = 16,
        num_blocks: int = 512,
        max_blocks_per_seq: int = 64,
        eos_token_id: Optional[int] = None,
        dtype=jnp.float32,
    ):
        self.model = model
        self.tokenizer = tokenizer
        self.infer = PagedInferenceModel(model, block_size, num_blocks, max_blocks_per_seq, dtype=dtype)
        self.pool = init_paged_pool(model.config, num_blocks, block_size,
                                    dtype=jnp.bfloat16 if dtype == jnp.bfloat16 else jnp.float32)
        self.mgr = BlockManager(num_blocks, block_size, max_blocks_per_seq)
        self.max_batch_size = max_batch_size
        eos = eos_token_id if eos_token_id is not None else getattr(model.config, "eos_token_id", None)
        self.eos_ids = set(eos) if isinstance(eos, (list, tuple)) else ({eos} if eos is not None else set())
        self.waiting: deque[Request] = deque()
        self.running: Dict[int, Request] = {}  # seq_id == req_id
        self._next_id = itertools.count()
        self._last_token: Dict[int, int] = {}

    # ------------------------------------------------------------------ api
    def add_request(self, prompt_ids, sampling: Optional[SamplingParams] = None,
                    stream_cb: Optional[Callable] = None) -> int:
        sampling = sampling or SamplingParams()
        req = Request(
            req_id=next(self._next_id),
            prompt_ids=np.asarray(prompt_ids, dtype=np.int32).reshape(-1),
            sampling=sampling,
            stream_cb=stream_cb,
            _rng=np.random.default_rng(sampling.seed),
            arrival_t=time.time(),
        )
        self.waiting.append(req)
        return req.req_id

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def generate(self, prompts: List, sampling: Optional[SamplingParams] = None) -> List[List[int]]:
        """Submit a batch and run to completion (convenience API)."""
        ids = [self.add_request(p, sampling) for p in prompts]
        results: Dict[int, Request] = {}
        while self.has_work():
            for req in self.step():
                results[req.req_id] = req
        return [results[i].output_ids for i in ids]

    # ------------------------------------------------------------------ scheduling
    def step(self) -> List[Request]:
        """One engine iteration: admit + decode. Returns requests finished this step."""
        finished: List[Request] = []
        self._admit(finished)
        self._decode_running(finished)
        return finished

    def _admit(self, finished: List[Request]):
        while self.waiting and len(self.running) < self.max_batch_size:
            req = self.waiting[0]
            prompt_len = len(req.prompt_ids)
            # a request that can NEVER fit must fail fast, not spin has_work() forever
            need = self.mgr.blocks_needed(prompt_len + req.sampling.max_new_tokens)
            if need > self.mgr.max_blocks_per_seq or need > self.mgr.total_usable_blocks:
                self.waiting.popleft()
                req.done = True
                logger.warning(f"req {req.req_id}: needs {need} KV blocks (> capacity); rejected")
                finished.append(req)
                continue
            # reserve prompt + 1 so the first decode never immediately preempts
            if not self.mgr.can_allocate(prompt_len + 1):
                break
            self.waiting.popleft()
            self.mgr.allocate(req.req_id, prompt_len)
            table = jnp.asarray(self.mgr.table_array(req.req_id))
            padded = _bucket(prompt_len)
            ids = np.zeros((1, padded), np.int32)
            ids[0, :prompt_len] = req.prompt_ids
            logits, self.pool = self.infer.prefill(
                self.model.params, self.pool, jnp.asarray(ids), table, jnp.asarray(prompt_len)
            )
            tok = self._sample(req, np.asarray(logits[0]))
            self._emit(req, tok)
            if req.done:
                self.mgr.free_seq(req.req_id)
                finished.append(req)
            else:
                self.running[req.req_id] = req
                self._last_token[req.req_id] = tok

    def _decode_running(self, finished: List[Request]):
        if not self.running:
            return
        # grow tables; preempt (recompute-requeue) youngest on exhaustion
        for req_id in sorted(self.running, reverse=True):
            req = self.running[req_id]
            if self.mgr.extend(req_id, 1) is None:
                logger.warning(f"req {req_id}: KV blocks exhausted; preempting (recompute)")
                self.mgr.free_seq(req_id)
                del self.running[req_id]
                req.prompt_ids = np.concatenate([req.prompt_ids, np.asarray(req.output_ids, np.int32)])
                req.output_ids = []
                self.waiting.appendleft(req)

        if not self.running:
            return
        B = self.max_batch_size
        tokens = np.zeros(B, np.int32)
        tables = np.zeros((B, self.mgr.max_blocks_per_seq), np.int32)
        ctx = np.zeros(B, np.int32)
        slots = list(self.running.values())
        for i, req in enumerate(slots):
            tokens[i] = self._last_token[req.req_id]
            tables[i] = self.mgr.table_array(req.req_id)
            ctx[i] = req.total_len - 1  # position of the token being fed
        logits, self.pool = self.infer.decode(
            self.model.params, self.pool, jnp.asarray(tokens), jnp.asarray(tables), jnp.asarray(ctx)
        )
        logits_np = np.asarray(logits)
        for i, req in enumerate(slots):
            tok = self._sample(req, logits_np[i])
            self._emit(req, tok)
            if req.done:
                self.mgr.free_seq(req.req_id)
                del self.running[req.req_id]
                self._last_token.pop(req.req_id, None)
                finished.append(req)
            else:
                self._last_token[req.req_id] = tok

    # ------------------------------------------------------------------ sampling
    def _sample(self, req: Request, logits: np.ndarray) -> int:
        s = req.sampling
        if not s.do_sample:
            return int(np.argmax(logits))
        logits = logits.astype(np.float64) / max(s.temperature, 1e-6)
        if s.top_k and s.top_k > 0:
            kth = np.partition(logits, -s.top_k)[-s.top_k]
            logits = np.where(logits < kth, -np.inf, logits)
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        if s.top_p < 1.0:
            order = np.argsort(probs)[::-1]
            csum = np.cumsum(probs[order])
            cutoff = np.searchsorted(csum, s.top_p) + 1
            mask = np.zeros_like(probs)
            mask[order[:cutoff]] = probs[order[:cutoff]]
            probs = mask / mask.sum()
        return int(req._rng.choice(len(probs), p=probs))

    def _emit(self, req: Request, tok: int):
        if req.first_token_t is None:
            req.first_token_t = time.time()
        req.output_ids.append(tok)
        is_eos = tok in self.eos_ids
        hit_max = len(req.output_ids) >= req.sampling.max_new_tokens
        req.done = is_eos or hit_max
        if req.stream_cb is not None:
            req.stream_cb(tok, req.done)
