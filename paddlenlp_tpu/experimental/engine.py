"""Continuous-batching inference engine.

Counterpart of the reference's dynamic-insertion serving loop:
``GenerationBlockInferenceModel.sample`` per-token loop
(experimental/transformers/generation_utils.py:403) + the ``step_paddle`` block
scheduler (csrc/gpu/step.cu:316 — dispatch/free/preempt/recover) + the on-GPU
sampling/penalty/stop ops (top_p_sampling_reject.cu, token_penalty_multi_scores.cu,
stop_generation_multi_ends.cu, update_inputs.cu). Host-side scheduler + two jitted
device programs:

- admission: waiting requests prefill in BATCHES grouped by power-of-two padded
  prompt length; the first token is sampled on device inside the prefill jit;
- chunked prefill (``prefill_chunk_tokens=N``): prompt processing is split into
  fixed-size chunks interleaved with decode tokens — each engine step feeds at
  most N prompt tokens (tracked per slot via ``Request.prefilled_len``) plus one
  decode token per running sequence through ONE ragged mixed forward, so a
  long-prompt admission never stalls running decodes for the whole prompt; the
  sampler fires only when a request's last chunk lands (the *Ragged Paged
  Attention* TPU-serving design);
- decode: ALL slots advance up to ``decode_steps`` tokens in ONE jit —
  sampling, repetition/presence/frequency penalties, eos and length stops all
  run on device; the host round-trip carries int32 ids + flags only (the
  reference avoids per-token host sync the same way, with CUDA ops);
- preemption: on block exhaustion the youngest sequence is evicted and requeued
  with prompt+generated as its new prompt (recompute-style recovery, the
  ``is_block_step``/recover list of step.cu). Sampling keys are
  (seed, absolute position), so a recomputed sequence resamples identically;
- streaming: per-request callbacks fire as tokens land (the reference pushes
  tokens over a SysV message queue to the serving process; in-process callbacks
  replace the IPC hop).
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.flight_recorder import RECORDER
from ..observability.goodput import (
    GoodputLedger,
    compile_attribution,
    device_peak_flops,
    efficiency_doc,
    estimate_model_flops_per_token,
    install_compile_listener,
)
from ..observability.tracer import TRACER
from ..serving.tenancy.adapters import AdapterPressure, UnknownAdapterError
from ..serving.tenancy.quotas import DEFAULT_TENANT, TenantQuotas, tenant_goodput_fold
from ..utils.faults import FaultPoint
from ..utils.log import logger
from .backend import MixedRow, ModelBackend, SingleDeviceBackend, _bucket
from .kv_host_tier import HostKVTier, pool_block_bytes
from .paged_cache import BlockManager

__all__ = ["InferenceEngine", "Request", "SamplingParams"]

_F_STEP = FaultPoint("engine.step")
_F_CHUNK = FaultPoint("engine.prefill_chunk")
_F_MIGRATE = FaultPoint("engine.kv_migrate")
_F_SPILL = FaultPoint("engine.kv_spill")
_F_PROMOTE = FaultPoint("engine.kv_promote")


@dataclasses.dataclass
class SamplingParams:
    max_new_tokens: int = 64
    do_sample: bool = False
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0
    seed: int = 0
    repetition_penalty: float = 1.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0


#: admission preference rank per serving priority class (lower admits first);
#: unknown strings rank as interactive so a bare engine user can ignore this
_PRIORITY_RANK = {"interactive": 0, "batch": 1, "best_effort": 2}


@dataclasses.dataclass
class Request:
    req_id: int
    prompt_ids: np.ndarray
    sampling: SamplingParams
    output_ids: List[int] = dataclasses.field(default_factory=list)
    done: bool = False
    stream_cb: Optional[Callable[[int, bool], None]] = None
    arrival_t: float = 0.0
    sched_t: Optional[float] = None  # first admitted to a slot (prefill launch)
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    finish_reason: Optional[str] = None  # stop | length | abort | capacity
    aborted: bool = False
    base_prompt_len: int = 0  # original prompt length (preemption grows prompt_ids)
    trace: Optional[str] = None  # observability trace id (serving request context)
    # serving request priority ("interactive" | "batch" | "best_effort"):
    # orders the waiting queue under load — interactive admits ahead of batch,
    # batch ahead of best_effort; FIFO within a class (0/1/2 rank, see
    # InferenceEngine.add_request)
    priority: str = "interactive"
    # multi-tenant serving: which tenant the request bills to (quotas, the
    # per-tenant goodput fold, shed/served metric labels) ...
    tenant: str = DEFAULT_TENANT
    # ... which registered LoRA adapter its rows gather (None = base model;
    # also the prefix-cache salt, so adapter outputs never share KV) ...
    adapter_id: Optional[str] = None
    # ... and the adapter-pool slot held while admitted (0 = identity slot;
    # a real slot carries a registry refcount, released with the KV blocks
    # in _free_kv and re-acquired on re-admission)
    adapter_slot: int = 0
    prefilled_len: int = 0  # prompt tokens whose KV is in the pool (chunked prefill)
    # which stage's pool holds this sequence's KV (disaggregated backends):
    # "prefill" while chunks run, "migrating" while blocks move between stage
    # pools, "decode" once landed (single-pool backends stay "decode" always)
    kv_stage: str = "decode"
    # latency-attribution bookkeeping (engine_loop.request_attribution):
    # first time the request was head-of-queue but deferred by an admission
    # gate (splits queue_wait into pure-queue vs admission-gate) ...
    gated_t: Optional[float] = None
    # ... decode-window seconds spent riding mixed steps that also carried
    # other requests' prefill chunks (the per-request decode-stall share) ...
    chunk_stall_s: float = 0.0
    # ... and seconds spent waiting for prefill->decode block migration
    # (accumulated on land; migrate_start_t marks an episode still open)
    migration_wait_s: float = 0.0
    migrate_start_t: Optional[float] = None
    # ... and seconds waiting for a host-tier KV promotion (H2D copy of
    # spilled prefix blocks) to land before prefill could proceed
    # (accumulated on land; promote_start_t marks an episode still open)
    promote_wait_s: float = 0.0
    promote_start_t: Optional[float] = None
    # goodput-ledger bookkeeping: highest absolute position ever fed through
    # a forward for this request (prompt+output indexing survives the
    # preemption fold) — re-feeding below the mark is rework, not useful ...
    fed_hwm: int = 0
    # ... COW tail tokens owed by a full-cover prefix-cache admission (they
    # re-prefill KV another request already built: rework kind "cow_token") ...
    cow_pending: int = 0
    # ... and which rework bucket this request's re-fed positions land in
    # (preemption recompute vs a supervisor requeue across a rebuild)
    rework_src: str = "preempt_refill"
    # usage-metering bookkeeping (serving.tenancy.metering.UsageMeter reads
    # these at finish): prefix-cache tokens credited at FIRST admission only
    # (None until admitted — a preemption re-admission must not re-credit) ...
    cached_tokens: Optional[int] = None
    # ... engine-attributed useful fed positions, mirroring the per-tenant
    # goodput fold token for token so summed finished-request usage
    # reconciles exactly against the ledger's useful total ...
    useful_tokens: int = 0
    # ... speculative work billed to this request ...
    spec_drafted: int = 0
    spec_accepted: int = 0
    # ... the block·seconds integral of KV residency (advanced by a per-step
    # checkpoint while kv_occ_t holds the open episode's start; finalized in
    # _free_kv, so it accumulates across preemption episodes) ...
    kv_block_seconds: float = 0.0
    kv_occ_t: Optional[float] = None
    # ... and wall seconds holding a real adapter-pool slot (refcount
    # bracket: acquire in _admit_slots, release in _free_kv)
    adapter_slot_seconds: float = 0.0
    adapter_acq_t: Optional[float] = None

    @property
    def needs_prefill(self) -> bool:
        """True while part of the prompt still awaits a prefill chunk."""
        return self.prefilled_len < len(self.prompt_ids)

    @property
    def total_len(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def queue_wait(self) -> Optional[float]:
        """Seconds spent waiting before first admission (TTFT = queue + prefill)."""
        if self.sched_t is None:
            return None
        return self.sched_t - self.arrival_t

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.arrival_t

    @property
    def decode_time(self) -> Optional[float]:
        """Seconds from first token to completion (0 for single-token results)."""
        if self.finish_t is None or self.first_token_t is None:
            return None
        return self.finish_t - self.first_token_t

    @property
    def gen_offset(self) -> int:
        """Tokens already regenerated into prompt_ids by a preemption-requeue."""
        return len(self.prompt_ids) - self.base_prompt_len

    @property
    def remaining_new(self) -> int:
        return self.sampling.max_new_tokens - self.gen_offset - len(self.output_ids)


class InferenceEngine:
    def __init__(
        self,
        model,
        tokenizer=None,
        max_batch_size: int = 8,
        block_size: int = 16,
        num_blocks: int = 512,
        max_blocks_per_seq: int = 64,
        eos_token_id: Optional[int] = None,
        dtype=jnp.float32,
        decode_steps: int = 8,
        kv_cache_quant: Optional[str] = None,  # None | "int8" | "fp8" (cachekv_int8 knob)
        use_speculative: bool = False,
        spec_draft_len: int = 4,
        spec_ngram: int = 2,
        draft_model=None,  # small causal LM proposer (reference speculate_method=draft_model)
        spec_seed: int = 0,
        # share KV blocks across common prompt prefixes. Content-addressed:
        # only valid while params are frozen — callers that update weights
        # between requests must disable this or call clear_prefix_cache()
        enable_prefix_cache: bool = True,
        # split prompt processing into chunks of at most this many tokens,
        # interleaved with decode tokens (one ragged mixed step per chunk) so
        # no engine step does unbounded prefill. None/0 = monolithic prefill.
        prefill_chunk_tokens: Optional[int] = None,
        # shard the forward + KV pool over a device mesh: int tp degree,
        # (dp, tp) tuple, or a parallel.mesh.MeshConfig. None = single device.
        mesh_shape=None,
        # disaggregated prefill/decode serving: (P, D) device counts — prompt
        # work runs on a P-device prefill stage, decode on a D-device decode
        # stage, KV blocks migrating between the stage pools. Overrides
        # mesh_shape. None = single-stage.
        disagg_stages=None,
        # migration scheduling knobs (staged backends only): at most this many
        # block migrations in flight at once ...
        migration_inflight_limit: int = 4,
        # ... and new migrations are deferred while the decode stage's share
        # of KV blocks exceeds this fraction (decode pressure gates handoff)
        decode_pressure_gate: float = 0.92,
        # stage-aware admission: new prompts stop admitting while the prefill
        # stage's share of KV blocks (mid-prefill + migrating sequences)
        # would exceed this fraction
        prefill_pressure_gate: float = 0.95,
        # mixed-step layout: True = token-flattened segments, False = one
        # padded [B, chunk] launch, None = auto (flatten on the XLA fallback)
        token_flatten: Optional[bool] = None,
        # a prebuilt ModelBackend instance overrides mesh_shape (tests /
        # future MPMD stage-split backends plug in here)
        backend: Optional[ModelBackend] = None,
        # multi-LoRA serving: a tenancy.AdapterRegistry whose device pool the
        # backend gathers per-row deltas from. None = base model only (the
        # historical jit programs, untouched).
        adapter_registry=None,
        # per-tenant KV-block share limits: a tenancy.TenantQuotas (or its
        # dict form). The max_inflight leg is enforced upstream by the
        # serving scheduler; the engine owns the block-share admission gate.
        tenant_quotas=None,
        # hierarchical KV cache: host-RAM spill tier capacity in BLOCKS
        # (0 = off). Zero-ref prefix blocks popped off the cache LRU demote
        # to pinned host memory (batched async D2H) instead of being
        # destroyed; a prefix match landing on them promotes back with an
        # async H2D copy overlapped with decode. Requires enable_prefix_cache.
        host_kv_blocks: int = 0,
    ):
        self.model = model
        self.tokenizer = tokenizer
        eos = eos_token_id if eos_token_id is not None else getattr(model.config, "eos_token_id", None)
        self.eos_ids = set(eos) if isinstance(eos, (list, tuple)) else ({eos} if eos is not None else set())
        backend_kw = dict(
            max_batch_size=max_batch_size, block_size=block_size, num_blocks=num_blocks,
            max_blocks_per_seq=max_blocks_per_seq, dtype=dtype, decode_steps=decode_steps,
            eos_ids=self.eos_ids, kv_cache_quant=kv_cache_quant, token_flatten=token_flatten,
            adapter_registry=adapter_registry,
        )
        if disagg_stages is not None and mesh_shape is not None:
            raise ValueError(
                "mesh_shape and disagg_stages are mutually exclusive: a disagg "
                "stage is itself a sharded device group (sized by disagg_stages)")
        if backend is not None:
            self.backend = backend
        elif disagg_stages is not None:
            from .disagg_backend import DisaggBackend

            self.backend = DisaggBackend(model, stages=disagg_stages, **backend_kw)
        elif mesh_shape is not None:
            from .sharded_backend import ShardedBackend

            self.backend = ShardedBackend(model, mesh_shape=mesh_shape, **backend_kw)
        else:
            self.backend = SingleDeviceBackend(model, **backend_kw)
        # the backend's registry is authoritative (a prebuilt backend carries
        # its own); the engine uses it for slot acquire/release at admission
        self.adapter_registry = (getattr(self.backend, "adapter_registry", None)
                                 or adapter_registry)
        self.tenant_quotas = (
            tenant_quotas
            if tenant_quotas is None or isinstance(tenant_quotas, TenantQuotas)
            else TenantQuotas(tenant_quotas))
        # per-tenant attributable-token accounting (the tenancy fold over the
        # PR 15 ledger): monotone engine totals, surviving reset() like the
        # ledger's — the metrics plane rebaselines on rebind
        self.tenant_goodput: Dict[str, Dict[str, int]] = {}
        # stage-split scheduling state (engine-owned; the backend only copies
        # blocks): req_id -> in-flight MigrationTicket, plus the deferred
        # queue migrations wait on while the decode stage is under pressure
        self.staged = bool(getattr(self.backend, "staged", False))
        self.migration_inflight_limit = migration_inflight_limit
        self.decode_pressure_gate = decode_pressure_gate
        self.prefill_pressure_gate = prefill_pressure_gate
        # is_ready-less runtimes: force-land a migration after this many polls
        # (the functional pool threading already guarantees correctness)
        self.migration_force_land_polls = 8
        self._migrating: Dict[int, object] = {}
        self._migrate_pending: deque = deque()
        # req_ids whose migration deferral was already recorded this episode
        # (one migrate.defer event per wait, not one per engine step)
        self._migrate_defer_noted: set = set()
        self.enable_prefix_cache = enable_prefix_cache
        self.mgr = BlockManager(num_blocks, block_size, max_blocks_per_seq,
                                enable_prefix_cache=enable_prefix_cache)
        # hierarchical KV: the optional host-RAM tier under the BlockManager,
        # plus the engine-held in-flight promotion tickets (req_id -> ticket;
        # the same marker-poll scheduling gate as stage migrations)
        self.host_kv_blocks = int(host_kv_blocks or 0)
        self._host_tier: Optional[HostKVTier] = None
        if self.host_kv_blocks > 0:
            if not enable_prefix_cache:
                raise ValueError(
                    "host_kv_blocks requires enable_prefix_cache=True: the "
                    "tier is the prefix cache's second level")
            self._host_tier = HostKVTier(
                self.host_kv_blocks,
                block_bytes=pool_block_bytes(self.backend.pool))
            self.mgr.attach_host_tier(self._host_tier)
        self._promoting: Dict[int, object] = {}
        self.max_batch_size = max_batch_size
        self.decode_steps = decode_steps
        self.waiting: deque[Request] = deque()
        self.slots: List[Optional[Request]] = [None] * max_batch_size
        self._next_id = itertools.count()
        self._last_token = np.zeros(max_batch_size, np.int32)
        # speculative decoding: n-gram prompt-lookup OR draft-model proposer,
        # batched verify; greedy acceptance or rejection sampling
        self.use_speculative = use_speculative or draft_model is not None
        self.spec_draft_len = spec_draft_len
        self.spec_ngram = spec_ngram
        self.draft_model = draft_model
        self._spec_seed = spec_seed
        self._spec_rngs: Dict[int, np.random.Generator] = {}
        self.spec_stats = {"verify_steps": 0, "tokens_emitted": 0, "drafted": 0, "accepted": 0}
        self.num_preemptions = 0
        if prefill_chunk_tokens is not None and prefill_chunk_tokens < 0:
            raise ValueError(f"prefill_chunk_tokens must be >= 0, got {prefill_chunk_tokens}")
        self.prefill_chunk_tokens = prefill_chunk_tokens or None
        # chunked-prefill accounting: monotone totals (stats()) plus bounded
        # event rings the metrics plane drains by sequence number — a stats()
        # read from an HTTP thread must never consume a histogram observation
        self.chunk_stats = {"chunks": 0, "chunk_tokens": 0}
        self._chunk_seq = itertools.count(1)
        self.recent_chunk_sizes: deque = deque(maxlen=256)  # (seq, n_tokens)
        self.recent_decode_stalls: deque = deque(maxlen=256)  # (seq, seconds)
        # monotone step id: stamped on host spans AND on the device timeline
        # via jax.profiler.StepTraceAnnotation, so a span in /debug/trace and
        # an XLA op in a device profile join on the same number
        self._step_seq = itertools.count()
        self._cur_step = -1
        # goodput ledger: per-step token-conservation accounting
        # (fed == useful + padding + spec_rejected + rework, exact) + compile
        # telemetry + step anatomy. Loop-thread-confined like chunk_stats;
        # totals survive reset() (monotone engine totals, rebaselined by the
        # metrics plane on rebind exactly like the chunk counters)
        self.ledger = GoodputLedger(
            flops_per_token=estimate_model_flops_per_token(model.config),
            peak_flops=device_peak_flops()
            * max(self.backend.describe().get("devices", 1), 1))
        install_compile_listener()
        # step-time anatomy event ring, drained by seq like the chunk rings:
        # (seq, gap_s, device_s, host_s); gap_s < 0 = unmeasured (post-idle)
        self.recent_step_times: deque = deque(maxlen=512)
        self._step_time_seq = itertools.count(1)
        self._last_step_end: Optional[float] = None
        self._prev_step_busy = False
        self._step_device_s = 0.0
        # serving hook: called after every step() with a stats dict (queue
        # depth, running slots, free KV blocks) — the metrics plane subscribes
        # here instead of monkey-patching the loop
        self.step_cb: Optional[Callable[[Dict], None]] = None

    # device state lives in the backend; these stay as read paths for tests,
    # tools and the metrics plane that predate the backend split
    @property
    def infer(self):
        return self.backend.infer

    @property
    def pool(self):
        return self.backend.pool

    @property
    def counts(self):
        return self.backend.counts

    # ------------------------------------------------------------------ api
    def add_request(self, prompt_ids, sampling: Optional[SamplingParams] = None,
                    stream_cb: Optional[Callable] = None, trace: Optional[str] = None,
                    priority: str = "interactive", rework_hwm: int = 0,
                    adapter_id: Optional[str] = None,
                    tenant: str = DEFAULT_TENANT) -> int:
        """``rework_hwm`` marks the first ``rework_hwm`` prompt positions as
        already-fed-once (a supervisor requeue resubmitting a folded prompt
        after an engine rebuild): the goodput ledger then books their
        re-prefill as ``requeue_refill`` rework instead of useful work.

        ``adapter_id`` selects a LoRA adapter registered with the engine's
        :class:`~..serving.tenancy.AdapterRegistry` (validated HERE so an
        unknown id fails at submit, not mid-batch); ``tenant`` names the
        billing/quota identity the request's work is attributed to."""
        sampling = sampling or SamplingParams()
        if adapter_id is not None:
            if self.adapter_registry is None:
                raise UnknownAdapterError(
                    f"adapter {adapter_id!r} requested but the engine has no "
                    "adapter_registry")
            if adapter_id not in self.adapter_registry:
                raise UnknownAdapterError(
                    f"adapter {adapter_id!r} is not registered "
                    f"(known: {sorted(self.adapter_registry.ids())})")
        req = Request(
            req_id=next(self._next_id),
            prompt_ids=np.asarray(prompt_ids, dtype=np.int32).reshape(-1),
            sampling=sampling,
            stream_cb=stream_cb,
            arrival_t=time.time(),
            trace=trace,
            priority=priority,
            tenant=tenant,
            adapter_id=adapter_id,
        )
        req.base_prompt_len = len(req.prompt_ids)
        self._tenant_counts(tenant)["requests"] += 1
        if rework_hwm > 0:
            req.fed_hwm = min(int(rework_hwm), len(req.prompt_ids))
            req.rework_src = "requeue_refill"
        # priority-ordered admission: insert before the first waiting request
        # of a STRICTLY lower class so interactive work overtakes queued batch/
        # best-effort prompts under load, while same-class order stays FIFO
        # (the default "interactive"-everywhere case degenerates to append).
        # Preemption-requeues keep their appendleft fast path untouched.
        rank = _PRIORITY_RANK.get(priority, 0)
        if not self.waiting or _PRIORITY_RANK.get(self.waiting[-1].priority, 0) <= rank:
            self.waiting.append(req)
        else:
            for i, queued in enumerate(self.waiting):
                if _PRIORITY_RANK.get(queued.priority, 0) > rank:
                    self.waiting.insert(i, req)
                    break
        return req.req_id

    def has_work(self) -> bool:
        return bool(self.waiting) or any(r is not None for r in self.slots)

    def abort(self, req_id: int) -> Optional[Request]:
        """Cancel a request wherever it is (waiting queue or a running slot).

        Counterpart of the reference's stop-flag write into the running batch
        (step.cu clears the slot; here the host owns scheduling so it is a
        plain dict/slot edit). Frees the request's KV blocks, marks it
        ``aborted`` with ``finish_reason='abort'`` and returns it; returns
        None for ids that are unknown or already finished. The stream callback
        is NOT fired — cancellation notification is the caller's job (the
        serving loop resolves the handle)."""
        for i, req in enumerate(self.waiting):
            if req.req_id == req_id:
                del self.waiting[i]
                self._finish_abort(req)
                return req
        for slot, req in enumerate(self.slots):
            if req is not None and req.req_id == req_id:
                self._free_kv(req)
                self.slots[slot] = None
                self._drop_migration(req_id)
                self._drop_promotion(req_id)
                self._finish_abort(req)
                return req
        return None

    def _free_kv(self, req: Request, cache: bool = False):
        """Release a request's KV blocks (+ an alloc/free trace marker).

        ``cache=True`` (normal finishes) registers the request's full prompt
        blocks in the prefix index instead of freeing them, so the next
        request sharing the prefix skips their prefill; aborts and
        preemptions release by refcount without registering."""
        freed = self.mgr.lengths.get(req.req_id)
        if req.kv_occ_t is not None:
            # close the open KV-occupancy episode while the block table still
            # exists: the block·seconds integral is what usage metering bills
            # for cache residency
            req.kv_block_seconds += (time.perf_counter() - req.kv_occ_t) \
                * len(self.mgr.tables.get(req.req_id, ()))
            req.kv_occ_t = None
        if cache and self.enable_prefix_cache and req.finish_reason in ("stop", "length"):
            # salt = adapter_id: an adapter's KV is the product of base+delta
            # forwards, so cached prefixes are only shareable within the SAME
            # adapter (base-model requests keep the historical unsalted hashes).
            # GENERATED blocks register too (conversation-lifetime caching: a
            # chat turn's completion is the next turn's prompt prefix) — the
            # last sampled token is excluded because it was emitted, never fed,
            # so its KV position was never written
            token_ids = req.prompt_ids
            if len(req.output_ids) > 1:
                gen = np.asarray(req.output_ids[:-1], np.int32)  # sync-ok: host int list, no device sync
                token_ids = np.concatenate([req.prompt_ids, gen])
            bs = self.mgr.block_size
            nb_full = len(token_ids) // bs
            wb = nb_full - len(req.prompt_ids) // bs
            if self.staged and wb > 0 and req.req_id in self.mgr.tables:
                # staged backends: decode wrote the generated positions into
                # the DECODE pool, but cached prefixes serve prefill from the
                # PREFILL pool — copy the generation-bearing full blocks back
                # before registering them. The prompt/generation boundary
                # block is complete in the decode pool (migration moved it
                # whole before decode appended), so the write-back slice
                # starts there, not one block later.
                table = self.mgr.tables[req.req_id]
                self.backend.kv_writeback(
                    list(table[len(req.prompt_ids) // bs : nb_full]))
            self.mgr.finish_seq_cached(req.req_id, token_ids, salt=req.adapter_id)
        else:
            self.mgr.free_seq(req.req_id)
        if req.adapter_slot:
            # the adapter-pool refcount travels with the KV blocks: finish,
            # abort, preemption and quarantine all pass through here, and
            # re-admission re-acquires (content-addressed => token-exact)
            self.adapter_registry.release(req.adapter_id)
            req.adapter_slot = 0
            if req.adapter_acq_t is not None:
                req.adapter_slot_seconds += time.perf_counter() - req.adapter_acq_t
                req.adapter_acq_t = None
        TRACER.instant("kv_free", cat="engine", trace=req.trace,
                       req_id=req.req_id, tokens_held=freed,
                       free_blocks=self.mgr.num_free,
                       cached_blocks=self.mgr.num_cached_blocks)

    def _finish_abort(self, req: Request):
        req.done = True
        req.aborted = True
        req.finish_reason = "abort"
        req.finish_t = time.time()
        self._spec_rngs.pop(req.req_id, None)

    def release_request(self, req_id: int) -> bool:
        """Drop a request from the scheduler (waiting queue or its slot) and
        free its KV blocks WITHOUT touching its finish fields — the serving
        supervisor's slot-level quarantine, where the supervisor (not the
        engine) owns the request's resolution. Unlike :meth:`abort` this never
        fabricates a ``finish_reason`` and fires no callback; unlike
        :meth:`reset` it leaves every other slot untouched, so unaffected
        streams keep decoding. Returns True iff the engine held the request."""
        for i, req in enumerate(self.waiting):
            if req.req_id == req_id:
                # waiting requests hold no KV blocks (allocation happens at
                # admission; preemption frees before requeue)
                del self.waiting[i]
                self._spec_rngs.pop(req_id, None)
                return True
        for slot, req in enumerate(self.slots):
            if req is not None and req.req_id == req_id:
                self._free_kv(req)
                self.slots[slot] = None
                self._drop_migration(req_id)
                self._drop_promotion(req_id)
                self._spec_rngs.pop(req_id, None)
                return True
        self._spec_rngs.pop(req_id, None)
        if req_id in self.mgr.lengths:
            # allocated but bound to no slot yet: the failure escaped mid-
            # admission, between KV allocation and the slot write — the
            # blocks are real even though the scheduler never saw the request
            self.mgr.free_seq(req_id)
            return True
        # already retired (finish raced the failure): nothing held
        return False

    def resync_counts(self):
        """Re-seed the device-side penalty counts of every live slot from
        host-known token history (``prompt[:prefilled_len] + output_ids``).
        The supervisor's slot quarantine calls this after releasing a
        poisoned request: the failed step may have committed count updates
        on device for tokens whose host-side emit never ran — those tokens
        will be regenerated from host state, and without the resync a
        penalty-sampling neighbor would see them double-counted."""
        entries, slot_idx = [], []
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            hist = np.concatenate([req.prompt_ids[: req.prefilled_len],
                                   np.asarray(req.output_ids, np.int32)])
            entries.append((len(slot_idx), hist, len(hist)))
            slot_idx.append(slot)
        if slot_idx:
            self.backend.seed_counts(slot_idx, entries)

    def clear_prefix_cache(self):
        """Invalidate every cached prefix block (idle ones return to the free
        list). Required after a weight update: cached KV is only valid under
        the params that produced it."""
        self.mgr.clear_prefix_cache()

    def sync_params(self, new_params):
        """Install a new base-weight tree through the backend seam (eager
        placement on the backend's existing device layout — see
        :meth:`ModelBackend.sync_params`). Callers own the rest of the swap
        protocol: quiesce, :meth:`clear_prefix_cache` (cached KV is only
        valid under the params that produced it), and
        :meth:`resync_counts` for any slots kept live across the swap."""
        self.backend.sync_params(new_params)

    # ------------------------------------------------------------------ stage migration
    def _slot_of(self, req_id: int) -> Optional[int]:
        for slot, r in enumerate(self.slots):
            if r is not None and r.req_id == req_id:
                return slot
        return None

    def _stage_blocks(self) -> Dict[str, int]:
        """KV blocks held per stage (host bookkeeping off the single shared
        block-id space): ``prefill`` = sequences mid-prefill or migrating,
        ``decode`` = decode-eligible sequences. The pressure inputs for
        stage-aware admission and the migration gate."""
        held = {"prefill": 0, "decode": 0}
        for r in self.slots:
            if r is None or r.req_id not in self.mgr.tables:
                continue
            key = "decode" if r.kv_stage == "decode" else "prefill"
            held[key] += len(self.mgr.tables[r.req_id])
        return held

    def _drop_migration(self, req_id: int):
        """Forget a request's migration state (abort / preempt / quarantine).
        An already-dispatched copy needs no cancellation: it only wrote the
        request's own blocks, which are about to be freed — any future owner
        re-prefills and re-migrates over them."""
        self._migrating.pop(req_id, None)
        self._migrate_defer_noted.discard(req_id)
        try:
            self._migrate_pending.remove(req_id)
        except ValueError:
            pass

    def _advance_migrations(self):
        """Poll in-flight prefill→decode block migrations and start deferred
        ones. Landing flips the sequence to ``kv_stage="decode"`` — the only
        thing that makes it decode-eligible. Starts are gated by the in-flight
        bound and by decode-stage KV pressure (a saturated decode pool must
        drain before it accepts more handoffs — the backpressure that keeps
        the two SLOs decoupled instead of re-coupling them through the pool)."""
        for req_id, ticket in list(self._migrating.items()):
            ticket.polls += 1
            if not (self.backend.migration_ready(ticket)
                    or ticket.polls >= self.migration_force_land_polls):
                continue
            del self._migrating[req_id]
            slot = self._slot_of(req_id)
            if slot is None:
                continue  # aborted/preempted while the blocks were in flight
            req = self.slots[slot]
            req.kv_stage = "decode"
            if req.migrate_start_t is not None:
                # the migration-wait episode closes: bank it for attribution
                req.migration_wait_s += time.time() - req.migrate_start_t
                req.migrate_start_t = None
            RECORDER.record("migrate.land", req_id=req_id, trace=req.trace,
                            blocks=ticket.n_blocks, polls=ticket.polls)
            TRACER.instant("kv_migrated", cat="engine", trace=req.trace,
                           req_id=req_id, blocks=ticket.n_blocks,
                           polls=ticket.polls)
        total = max(self.mgr.total_usable_blocks, 1)
        if self._migrate_pending and len(self._migrating) >= self.migration_inflight_limit:
            self._note_migrate_deferred(self._migrate_pending[0], "inflight_limit")
        while self._migrate_pending and len(self._migrating) < self.migration_inflight_limit:
            if self._stage_blocks()["decode"] / total > self.decode_pressure_gate:
                self._note_migrate_deferred(self._migrate_pending[0], "decode_pressure")
                break  # decode pressure gates handoff; finishing seqs free it
            req_id = self._migrate_pending[0]
            slot = self._slot_of(req_id)
            if slot is None or self.slots[slot].kv_stage != "migrating":
                self._migrate_pending.popleft()
                continue  # retired/preempted while deferred
            req = self.slots[slot]
            # fired BEFORE the queue pop: an injected failure leaves the
            # handoff queued, so recovery (or a bare retry) finds it intact
            _F_MIGRATE.fire(req_id=req_id)
            self._migrate_pending.popleft()
            blocks = self.mgr.tables[req_id]
            hist = np.concatenate([req.prompt_ids[: req.prefilled_len],
                                   np.asarray(req.output_ids, np.int32)])  # sync-ok: host-side id lists (decode-stage count seed)
            t0 = time.perf_counter()
            self._migrating[req_id] = self.backend.kv_migrate(
                req_id, list(blocks), slot, hist)
            # goodput: the decode-stage penalty-count re-seed re-processes the
            # sequence's whole token history — pure rework, zero useful
            self.ledger.record("reseed", len(hist), 0, rework=len(hist),
                               rework_by={"migration_reseed": len(hist)})
            self._migrate_defer_noted.discard(req_id)
            RECORDER.record("migrate.start", req_id=req_id, trace=req.trace,
                            blocks=len(blocks), inflight=len(self._migrating))
            TRACER.add_span("kv_migrate", TRACER.epoch_time(t0),
                            time.perf_counter() - t0, cat="engine",
                            trace=req.trace, req_id=req_id, blocks=len(blocks),
                            inflight=len(self._migrating))

    def _note_migrate_deferred(self, req_id: int, reason: str):
        """One migrate.defer event per wait episode for the head pending
        handoff (the gate re-evaluates every step; the recorder must not)."""
        if req_id in self._migrate_defer_noted:
            return
        self._migrate_defer_noted.add(req_id)
        slot = self._slot_of(req_id)
        trace = self.slots[slot].trace if slot is not None else None
        RECORDER.record("migrate.defer", req_id=req_id, trace=trace,
                        reason=reason, inflight=len(self._migrating),
                        pending=len(self._migrate_pending))

    # ------------------------------------------------------------------ host KV tier
    def _drop_promotion(self, req_id: int):
        """Forget a request's in-flight promotion (abort / preempt /
        quarantine). A dispatched H2D copy needs no cancellation: functional
        pool threading orders it before any later read, and it only wrote
        the request's own blocks, which are about to be freed."""
        self._promoting.pop(req_id, None)

    def _drain_spills(self):
        """Flush prefix blocks the allocator popped off the cache LRU since
        the last drain into the host tier: ONE batched D2H gather, dispatched
        BEFORE any launch that could overwrite the recycled blocks (JAX
        dispatch order makes the gather read the pre-write values, and
        ``copy_to_host_async`` overlaps the transfer with the step's real
        work). A failure drops the spill — the blocks were already recycled,
        which is exactly the pre-tier behavior — and leaks nothing."""
        if self._host_tier is None:
            return
        pairs = self.mgr.drain_pending_spills()
        if not pairs:
            return
        t0 = time.perf_counter()
        try:
            _F_SPILL.fire(blocks=len(pairs))
            kv, scale = self.backend.kv_spill([b for _h, b in pairs])
            self._host_tier.put([h for h, _b in pairs], kv, scale)
        except Exception as e:
            RECORDER.record("spill.drop", blocks=len(pairs),
                            error=type(e).__name__)
            logger.warning(f"host-tier spill of {len(pairs)} blocks dropped: {e}")
            return
        RECORDER.record("spill.batch", blocks=len(pairs),
                        resident=self._host_tier.num_blocks)
        TRACER.add_span("kv_spill", TRACER.epoch_time(t0),
                        time.perf_counter() - t0, cat="engine",
                        blocks=len(pairs), resident=self._host_tier.num_blocks,
                        step=self._cur_step)

    def _advance_promotions(self, finished: List[Request]):
        """Poll in-flight host→device KV promotions (same marker-poll gate as
        stage migrations). Landing re-opens the request's prefill path:
        chunked engines start feeding its remaining suffix next
        ``_mixed_step``; monolithic engines launch the deferred prefill batch
        right here, in the same step the copy landed."""
        to_prefill: List[tuple] = []
        for req_id, ticket in list(self._promoting.items()):
            ticket.polls += 1
            if not (self.backend.migration_ready(ticket)
                    or ticket.polls >= self.migration_force_land_polls):
                continue
            del self._promoting[req_id]
            slot = self._slot_of(req_id)
            if slot is None:
                continue  # aborted/preempted while the copy was in flight
            req = self.slots[slot]
            # staged backends resume the ordinary prefill→migrate→decode walk
            # (promoted blocks landed in the prefill-stage pool); single-pool
            # backends just become row-eligible again
            req.kv_stage = "prefill" if self.staged else "decode"
            if req.promote_start_t is not None:
                # the promote-wait episode closes: bank it for attribution
                req.promote_wait_s += time.time() - req.promote_start_t
                req.promote_start_t = None
            RECORDER.record("promote.land", req_id=req_id, trace=req.trace,
                            blocks=ticket.n_blocks, polls=ticket.polls)
            TRACER.instant("kv_promoted", cat="engine", trace=req.trace,
                           req_id=req_id, blocks=ticket.n_blocks,
                           polls=ticket.polls)
            if not self.prefill_chunk_tokens and req.needs_prefill:
                to_prefill.append((slot, req, req.prefilled_len))
        if to_prefill:
            self._prefill_batch(to_prefill, finished)

    def reset(self):
        """Drop ALL scheduler/allocator state after a failed step — the
        in-place recovery the serving supervisor uses when it has no
        ``engine_factory``. The device pool tensor is kept (stale KV is
        unreachable once the block tables are rebuilt; prefill overwrites
        live slots), so reset is O(host state), not O(HBM).

        In-flight requests are NOT resolved here: the supervisor owns their
        retry/abort disposition and must triage before calling reset."""
        self.waiting.clear()
        self.slots = [None] * self.max_batch_size
        self.mgr = BlockManager(self.mgr.total_usable_blocks + 1, self.mgr.block_size,
                                self.mgr.max_blocks_per_seq,
                                enable_prefix_cache=self.enable_prefix_cache)
        self._last_token[:] = 0
        self.backend.reset_counts()
        if self.adapter_registry is not None:
            # dropped requests can no longer release their pool refcounts;
            # adapters stay RESIDENT (content intact for re-acquisition)
            self.adapter_registry.reset_refs()
        self._spec_rngs.clear()
        self._migrating.clear()
        self._migrate_pending.clear()
        self._migrate_defer_noted.clear()
        self._promoting.clear()
        if self._host_tier is not None:
            # tier content stays valid across reset (content-addressed KV
            # under unchanged params) — only the device-side index dropped
            # with the manager; re-attach so spills keep flowing. Pending
            # spills died with the old manager: their block ids are stale.
            self.mgr.attach_host_tier(self._host_tier)
        # the failed step never ran its anatomy tail: without this, the first
        # post-recovery step would book the whole outage (triage + reset) as
        # a "step gap" and pollute the histogram the bench gate reads
        self._last_step_end = None
        self._prev_step_busy = False
        logger.warning("inference engine reset: scheduler + KV allocator state dropped")

    def stats(self) -> Dict:
        """Point-in-time scheduler/allocator stats (the step_cb payload)."""
        out = {
            "queue_depth": len(self.waiting),
            "running": sum(1 for r in self.slots if r is not None),
            "max_batch_size": self.max_batch_size,
            "free_blocks": self.mgr.num_free,
            "total_blocks": self.mgr.total_usable_blocks,
            "num_preemptions": self.num_preemptions,
            "spec_stats": dict(self.spec_stats),
            "prefix_cache": {
                "enabled": self.enable_prefix_cache,
                "hits": self.mgr.cache_hits,
                "cached_tokens": self.mgr.cached_tokens_total,
                "evictions": self.mgr.evictions,
                "cached_blocks": self.mgr.num_cached_blocks,
                # the host-RAM spill tier under the device cache: always
                # present (zeros when off) so the metrics plane reads one shape
                "host": dict(
                    {"enabled": self._host_tier is not None,
                     "promotes_inflight": len(self._promoting)},
                    **(self._host_tier.snapshot() if self._host_tier is not None
                       else {"blocks": 0, "capacity": 0, "spills": 0,
                             "spill_batches": 0, "promotes": 0,
                             "promoted_blocks": 0, "promote_bytes": 0,
                             "evictions": 0}),
                ),
            },
            "chunked_prefill": {
                "enabled": bool(self.prefill_chunk_tokens),
                "chunk_tokens": self.prefill_chunk_tokens or 0,
                "chunks": self.chunk_stats["chunks"],
                "chunk_tokens_total": self.chunk_stats["chunk_tokens"],
            },
            "backend": self.backend.describe(),
            # the goodput ledger rides stats() so the step_cb metrics plane,
            # /health and postmortem bundles all carry the waste accounting
            "goodput": self.ledger.snapshot(),
        }
        if self.adapter_registry is not None or self.tenant_goodput:
            out["tenancy"] = {
                # per-tenant goodput fold over the engine's attributable-token
                # accounting (the tenancy leg of the PR 15 ledger)
                "tenants": tenant_goodput_fold(self.tenant_goodput),
                "adapters": (self.adapter_registry.stats()
                             if self.adapter_registry is not None else None),
                "quotas": (self.tenant_quotas.describe()
                           if self.tenant_quotas is not None else None),
            }
        if self.staged:
            held = self._stage_blocks()
            total = max(self.mgr.total_usable_blocks, 1)
            n_prefilling = sum(1 for r in self.slots
                               if r is not None and r.needs_prefill)
            n_migrating = sum(1 for r in self.slots
                              if r is not None and r.kv_stage == "migrating")
            out["disagg"] = {
                # TTFT comes from this pool ...
                "prefill_stage": {
                    "kv_blocks": held["prefill"],
                    "kv_utilization": held["prefill"] / total,
                    "queue_depth": len(self.waiting) + n_prefilling,
                },
                # ... inter-token latency from this one
                "decode_stage": {
                    "kv_blocks": held["decode"],
                    "kv_utilization": held["decode"] / total,
                    "queue_depth": n_migrating,
                },
                "migrations": dict(getattr(self.backend, "migration_stats",
                                           {"migrations": 0, "blocks": 0, "bytes": 0})),
                "migrations_inflight": len(self._migrating),
                "migrations_pending": len(self._migrate_pending),
            }
        return out

    def kv_fragmentation(self) -> float:
        """Internal fragmentation of allocated KV blocks: 1 - held tokens /
        (held blocks * block_size). 0.0 when nothing is allocated. Block-
        granular allocation always strands the tail of the last block; this
        gauge is how much of the allocated pool that amounts to right now.

        Called from HTTP scrape threads while the loop thread mutates the
        BlockManager: the dict snapshots are taken via ``list()`` (atomic in
        CPython) and a mid-resize race degrades to one stale scrape, never a
        500."""
        try:
            tables = list(self.mgr.tables.values())
            lengths = list(self.mgr.lengths.values())
        except RuntimeError:  # dict resized mid-copy by the loop thread
            return 0.0
        blocks = sum(len(t) for t in tables)
        if not blocks:
            return 0.0
        return max(0.0, 1.0 - sum(lengths) / (blocks * self.mgr.block_size))

    def efficiency(self) -> Dict:
        """The ``GET /debug/efficiency`` document: ledger snapshot, MFU /
        FLOPs model, percentiled step anatomy, occupancy and KV
        fragmentation. Readable from any thread (plain attribute reads; at
        worst one step stale — the stats() contract)."""
        running = sum(1 for r in self.slots if r is not None)
        try:
            step_times = list(self.recent_step_times)
        except RuntimeError:  # loop thread appended mid-copy: drop one window
            step_times = []
        return efficiency_doc(
            self.ledger, step_times, tier="serving",
            extra={
                "occupancy": {
                    "running": running,
                    "max_batch_size": self.max_batch_size,
                    "slot_occupancy": running / max(self.max_batch_size, 1),
                },
                "kv_fragmentation": round(self.kv_fragmentation(), 6),
                "spec": {
                    "drafted": self.spec_stats["drafted"],
                    "accepted": self.spec_stats["accepted"],
                    "acceptance_rate": self.spec_stats["accepted"]
                    / max(self.spec_stats["drafted"], 1),
                },
                "backend": self.backend.describe(),
            })

    def generate(self, prompts: List, sampling: Optional[SamplingParams] = None) -> List[List[int]]:
        """Submit a batch and run to completion (convenience API)."""
        ids = [self.add_request(p, sampling) for p in prompts]
        results: Dict[int, Request] = {}
        while self.has_work():
            for req in self.step():
                results[req.req_id] = req
        return [results[i].output_ids for i in ids]

    # ------------------------------------------------------------------ scheduling
    def step(self) -> List[Request]:
        """One engine iteration: admit + decode. Returns requests finished this step."""
        _F_STEP.fire()
        self._cur_step = next(self._step_seq)
        # step anatomy: host gap since the previous BUSY step ended (loop
        # overhead between steps) vs device time inside backend calls vs the
        # step's own host scheduling time. Post-idle steps have no meaningful
        # gap (the loop slept on purpose) — marked unmeasured (-1)
        t_step0 = time.perf_counter()
        gap_s = (t_step0 - self._last_step_end
                 if self._last_step_end is not None and self._prev_step_busy
                 else -1.0)
        self._step_device_s = 0.0
        finished: List[Request] = []
        # StepTraceAnnotation brackets this step on the device timeline: a
        # jax.profiler capture (POST /debug/profile) shows per-step lanes
        # whose step_num matches the step= arg on the host prefill/decode
        # spans — host stall or device stall is one cross-reference away
        with jax.profiler.StepTraceAnnotation("engine_step", step_num=self._cur_step):
            if self._promoting:
                # land finished host→device promotions FIRST, so a landed
                # request prefills (or chunks) in this very step
                self._advance_promotions(finished)
            if self.staged:
                # land finished prefill→decode block copies and start deferred
                # ones BEFORE row selection, so a landed sequence decodes in
                # this very step
                self._advance_migrations()
            if self.prefill_chunk_tokens:
                self._admit_chunked(finished)
                if any(r is not None and r.needs_prefill for r in self.slots):
                    # >=1 slot mid-prefill: one ragged mixed step (chunks +
                    # one decode token per running sequence)
                    self._mixed_step(finished)
                else:
                    # steady state: the multi-token decode jit as usual
                    self._decode_running(finished)
            else:
                self._admit(finished)
                self._decode_running(finished)
        # usage metering: advance each admitted request's kv_block_seconds
        # integral piecewise per step (block counts grow during decode, so a
        # single count-at-free rectangle would misbill long requests)
        t_occ = time.perf_counter()
        for req in self.slots:
            if req is not None and req.kv_occ_t is not None:
                req.kv_block_seconds += (t_occ - req.kv_occ_t) \
                    * len(self.mgr.tables.get(req.req_id, ()))
                req.kv_occ_t = t_occ
        t_end = time.perf_counter()
        host_s = max(t_end - t_step0 - self._step_device_s, 0.0)
        self.ledger.note_step(max(gap_s, 0.0), self._step_device_s, host_s)
        self.recent_step_times.append(
            (next(self._step_time_seq), gap_s, self._step_device_s, host_s))
        self._last_step_end = t_end
        self._prev_step_busy = self.has_work()
        if self.step_cb is not None:
            self.step_cb(self.stats())
        return finished

    def _free_slot_indices(self) -> List[int]:
        return [i for i, r in enumerate(self.slots) if r is None]

    def _tenant_counts(self, tenant: str) -> Dict[str, int]:
        tg = self.tenant_goodput.get(tenant)
        if tg is None:
            tg = self.tenant_goodput[tenant] = {
                "useful": 0, "rework": 0, "requests": 0, "tokens_out": 0}
        return tg

    def _tenant_held_blocks(self, tenant: str) -> int:
        """KV blocks currently held by a tenant's admitted requests (the
        engine-side input to the per-tenant block-share gate)."""
        return sum(len(self.mgr.tables[r.req_id]) for r in self.slots
                   if r is not None and r.tenant == tenant
                   and r.req_id in self.mgr.tables)

    def _note_fed_span(self, req: Request, start: int, n: int):
        """Goodput split of one fed span ``[start, start+n)``: positions below
        the request's fed high-water mark (re-prefill after preemption or a
        supervisor requeue) plus owed COW tail tokens are rework; the rest is
        useful. Advances the mark. Returns ``(rework, rework_by|None)``."""
        if n <= 0:
            return 0, None
        overlap = min(max(req.fed_hwm - start, 0), n)
        by = {}
        if overlap:
            by[req.rework_src] = overlap
        cow = min(req.cow_pending, n - overlap)
        if cow:
            by["cow_token"] = cow
            req.cow_pending -= cow
        req.fed_hwm = max(req.fed_hwm, start + n)
        rework = overlap + cow
        # the per-tenant fold: this request's attributable positions (padding
        # and speculative rejection are step-global, deliberately not here)
        tg = self._tenant_counts(req.tenant)
        tg["useful"] += n - rework
        tg["rework"] += rework
        # per-request mirror of the same attribution: the usage record's
        # useful_tokens must reconcile against the ledger token for token
        req.useful_tokens += n - rework
        return rework, (by or None)

    @staticmethod
    def _merge_rework(total_by: Dict[str, int], by: Optional[Dict[str, int]]):
        if by:
            for k, v in by.items():
                total_by[k] = total_by.get(k, 0) + v

    def _note_gated(self, req: Request, reason: str):
        """Mark the head-of-queue request as gate-deferred, ONCE per wait
        episode: the timestamp splits its eventual queue_wait into pure-queue
        vs admission-gate time (latency attribution), and the single decision
        event keeps a blocked queue from flooding the flight recorder with
        one identical record per engine step."""
        if req.gated_t is not None:
            return
        # stamped even on a preemption-requeue (sched_t already set) so the
        # event fires once, not per step; attribution only *uses* the stamp
        # when it falls inside the arrival -> first-admission window
        req.gated_t = time.time()
        RECORDER.record("admit.defer", req_id=req.req_id, trace=req.trace,
                        reason=reason, queue_depth=len(self.waiting),
                        free_blocks=self.mgr.num_free)

    def _admit_slots(self, finished: List[Request]) -> List[tuple]:
        """Shared admission front half: bind waiting requests to free slots and
        allocate their KV blocks (prefix-cache match + COW included). Returns
        ``[(slot, req, n_cached), ...]``; the caller owns the prefill launch —
        monolithic (:meth:`_admit`) or chunked (:meth:`_admit_chunked`)."""
        free = self._free_slot_indices()
        if not self.waiting or not free:
            return []
        queue_depth = len(self.waiting)
        n_finished0 = len(finished)
        admit_t0 = time.perf_counter()
        cache_on = self.enable_prefix_cache
        hits0, cached0 = self.mgr.cache_hits, self.mgr.cached_tokens_total
        admitted: List[tuple] = []  # (slot, req, n_cached)
        # stage-aware admission (staged backends): new prompts are prefill-
        # stage work, so their gate is PREFILL-stage KV pressure — blocks held
        # by mid-prefill + migrating sequences — not the shared total alone
        held_prefill = self._stage_blocks()["prefill"] if self.staged else 0
        total_blocks = max(self.mgr.total_usable_blocks, 1)
        # requests deferred by their TENANT's block-share cap step aside for
        # the rest of this pass (re-queued in order afterwards): one capped
        # tenant must not head-of-line block every other tenant's admissions
        tenant_deferred: List[Request] = []
        while self.waiting and free:
            req = self.waiting[0]
            prompt_len = len(req.prompt_ids)
            # a request that can NEVER fit must fail fast, not spin has_work()
            # forever. remaining_new (not max_new_tokens) so a preempted request
            # whose generated tokens were folded into the prompt is not
            # over-counted and spuriously rejected on re-admission.
            need = self.mgr.blocks_needed(prompt_len + req.remaining_new)
            if need > self.mgr.max_blocks_per_seq or need > self.mgr.total_usable_blocks:
                self.waiting.popleft()
                req.done = True
                req.finish_reason = "capacity"
                req.finish_t = time.time()
                RECORDER.record("admit.reject", req_id=req.req_id, trace=req.trace,
                                reason="capacity", blocks_needed=need,
                                prompt_len=prompt_len)
                logger.warning(f"req {req.req_id}: needs {need} KV blocks (> capacity); rejected")
                finished.append(req)
                continue
            # the gate charges only what admission actually reserves
            # (prompt + 1; decode growth happens on the decode stage), and an
            # IDLE prefill stage always admits at least one request — a lone
            # prompt larger than the gate fraction must run, not head-of-line
            # block the queue forever
            admit_need = self.mgr.blocks_needed(prompt_len + 1)
            if self.staged and held_prefill > 0 \
                    and held_prefill + admit_need > self.prefill_pressure_gate * total_blocks:
                self._note_gated(req, "prefill_gate")
                break  # prefill stage saturated: admitting would starve handoff
            if self.tenant_quotas is not None:
                cap = self.tenant_quotas.kv_block_cap(req.tenant,
                                                      self.mgr.total_usable_blocks)
                if cap is not None \
                        and self._tenant_held_blocks(req.tenant) + admit_need > cap:
                    # the tenant waits for its own requests to finish; it is
                    # deferred (not shed) and other tenants keep admitting
                    self._note_gated(req, "tenant_kv_share")
                    self.waiting.popleft()
                    tenant_deferred.append(req)
                    continue
            # reserve prompt + 1 so the first decode never immediately preempts;
            # cached prefix blocks need no fresh capacity, so a warm request
            # can be admitted where a cold one of the same length must wait.
            # The prefix match is computed ONCE and shared with allocate
            match = None
            if cache_on:
                # bound check before hashing: if even a perfect full-block
                # match can't fit, a blocked head-of-queue request must not
                # chain-hash its whole prompt again every engine step
                best_need = self.mgr.blocks_needed(prompt_len + 1) \
                    - prompt_len // self.mgr.block_size
                if best_need > self.mgr.num_free:
                    self._note_gated(req, "kv_pressure")
                    break
                match = self.mgr.match_prefix(req.prompt_ids, prompt_len,
                                              salt=req.adapter_id)
            if not self.mgr.can_admit(prompt_len + 1, match=match):
                self._note_gated(req, "kv_pressure")
                break
            adapter_slot = 0
            if req.adapter_id is not None:
                # acquire BEFORE the queue pop and KV allocation: a failed
                # hot-load leaves queue and allocator untouched (no KV or
                # pool-slot leak), and AdapterPressure just waits like
                # kv_pressure for a running adapter's refcount to drop
                try:
                    adapter_slot = self.adapter_registry.acquire(req.adapter_id)
                except AdapterPressure:
                    self._note_gated(req, "adapter_pressure")
                    break
                except Exception as e:
                    # a poisoned load (the engine.adapter_load fault point, a
                    # corrupt source): attribute it so the serving supervisor
                    # quarantines ONLY this request (engine_error/retry) while
                    # every other tenant's stream keeps decoding
                    if getattr(e, "req_id", None) is None:
                        try:
                            e.req_id = req.req_id
                        except Exception:
                            pass
                    raise
            self.waiting.popleft()
            req.adapter_slot = adapter_slot
            if adapter_slot:
                # adapter_slot_seconds episode opens with the refcount; the
                # release in _free_kv closes it (accumulates across preemptions)
                req.adapter_acq_t = time.perf_counter()
            if req.sched_t is None:  # preserved across preemption-requeues
                req.sched_t = time.time()
            if cache_on:
                _cached_blocks, n_cached, _new = self.mgr.allocate(
                    req.req_id, prompt_len, token_ids=req.prompt_ids, match=match)
            else:
                self.mgr.allocate(req.req_id, prompt_len)
                n_cached = 0
            # full-cover COW admissions owe a tail re-prefill of KV another
            # request already built: the ledger books it as cow_token rework.
            # Set, not accumulated — a preemption re-admission must not leak
            # a stale pending count into later spans
            req.cow_pending = (prompt_len - n_cached
                               if (match is not None and match[2] is not None) else 0)
            # hierarchical KV: the device-index match may continue into the
            # host tier — promote those blocks back with an async H2D copy
            # instead of re-prefilling them. The copy is dispatched NOW
            # (ahead of any prefill) and the request sits in kv_stage
            # "promoting" until the marker lands, overlapped with other
            # slots' decode steps. A full-cover COW admission skips this:
            # its whole prompt is already device-resident.
            if cache_on and self._host_tier is not None \
                    and not (match is not None and match[2] is not None):
                bs = self.mgr.block_size
                host_hashes = self.mgr.host_match(
                    req.prompt_ids, prompt_len, salt=req.adapter_id,
                    skip=n_cached // bs)
                # at least one prompt token must remain uncached: the first
                # output token is sampled by the final prompt forward
                while host_hashes and n_cached + len(host_hashes) * bs >= prompt_len:
                    host_hashes = host_hashes[:-1]
                if host_hashes:
                    # drain pending spills FIRST: this very allocate() may
                    # have popped LRU blocks that are about to be promote
                    # targets — their D2H gather must be enqueued before the
                    # promote scatter overwrites them. The drain's put() can
                    # LRU-evict tier entries, so re-truncate the match to the
                    # still-resident prefix afterwards.
                    self._drain_spills()
                    resident: List[bytes] = []
                    for h in host_hashes:
                        if not self._host_tier.contains(h):
                            break
                        resident.append(h)
                    host_hashes = resident
                if host_hashes:
                    promote_blocks = list(_new[: len(host_hashes)])
                    t_pr = time.perf_counter()
                    nbytes = len(host_hashes) * self._host_tier.block_bytes
                    try:
                        _F_PROMOTE.fire(req_id=req.req_id,
                                        blocks=len(host_hashes))
                        host_kv, host_scale, nbytes = \
                            self._host_tier.take(host_hashes)
                        ticket = self.backend.kv_promote(
                            req.req_id, promote_blocks, host_kv, host_scale)
                    except Exception as e:
                        # token-exact fallback: a pre-take failure leaves the
                        # entries tier-resident; a post-take one already
                        # popped them — either way the request keeps its
                        # allocated blocks, prefill just recomputes the span
                        # cold and the finish re-registers it. No host- or
                        # device-tier entry leaks, no stream is lost.
                        RECORDER.record("promote.fail", req_id=req.req_id,
                                        trace=req.trace,
                                        blocks=len(host_hashes),
                                        error=type(e).__name__)
                        logger.warning(
                            f"req {req.req_id}: host-tier promote failed "
                            f"({e}); falling back to cold prefill")
                    else:
                        self.mgr.register_promoted(promote_blocks, host_hashes)
                        if n_cached == 0:
                            self.mgr.cache_hits += 1
                        self.mgr.cached_tokens_total += len(host_hashes) * bs
                        n_cached += len(host_hashes) * bs
                        req.kv_stage = "promoting"
                        req.promote_start_t = time.time()
                        self._promoting[req.req_id] = ticket
                        RECORDER.record("promote.start", req_id=req.req_id,
                                        trace=req.trace,
                                        blocks=len(host_hashes), bytes=nbytes)
                        TRACER.add_span("kv_promote", TRACER.epoch_time(t_pr),
                                        time.perf_counter() - t_pr,
                                        cat="engine", trace=req.trace,
                                        req_id=req.req_id,
                                        blocks=len(host_hashes), bytes=nbytes)
            # usage metering: the KV-occupancy episode opens with the blocks;
            # the cache credit bills ONCE, at first admission — re-admission
            # hits after a preemption are rework economics, not a discount
            req.kv_occ_t = time.perf_counter()
            if req.cached_tokens is None:
                req.cached_tokens = n_cached
            TRACER.instant("kv_alloc", cat="engine", trace=req.trace,
                           req_id=req.req_id, tokens=prompt_len,
                           cached_tokens=n_cached,
                           free_blocks=self.mgr.num_free)
            if self.staged:
                # the sequence's KV is prefill-stage-resident until its last
                # chunk lands and the blocks migrate to the decode pool
                # ("promoting" is prefill-stage too — _stage_blocks agrees —
                # and flips to "prefill" when the H2D copy lands)
                if req.kv_stage != "promoting":
                    req.kv_stage = "prefill"
                held_prefill += len(self.mgr.tables[req.req_id])
            slot = free.pop(0)
            RECORDER.record("admit.accept", req_id=req.req_id, trace=req.trace,
                            slot=slot, prompt_len=prompt_len,
                            cached_tokens=n_cached)
            admitted.append((slot, req, n_cached))
        # capped-tenant requests return to the FRONT in their original order
        # (they were popped from the head before anything behind them)
        for r in reversed(tenant_deferred):
            self.waiting.appendleft(r)
        # admission span closes BEFORE prefill (sibling phases, not nested) and
        # only when something happened — a blocked queue spinning admitted=0
        # every step must not flood the span ring
        if admitted or len(finished) > n_finished0:
            TRACER.add_span("admission", TRACER.epoch_time(admit_t0),
                            time.perf_counter() - admit_t0, cat="engine",
                            step=self._cur_step,
                            queue_depth=queue_depth, admitted=len(admitted),
                            rejected_capacity=len(finished) - n_finished0)
        # spill drain BEFORE the COW copies: a pending spill's D2H gather must
        # be enqueued before any device write can touch the recycled blocks
        # (apply_cow may write into freshly popped LRU blocks)
        self._drain_spills()
        if cache_on and admitted:
            # prefix_cache phase: match/COW bookkeeping + the owed block copies
            pc_t0 = time.perf_counter()
            cow = self.mgr.drain_cow_pairs()
            if cow:
                self.backend.apply_cow(cow)
            TRACER.add_span("prefix_cache", TRACER.epoch_time(pc_t0),
                            time.perf_counter() - pc_t0, cat="engine",
                            hits=self.mgr.cache_hits - hits0,
                            cached_tokens=self.mgr.cached_tokens_total - cached0,
                            cow_copies=len(cow))
        return admitted

    def _admit(self, finished: List[Request]):
        admitted = self._admit_slots(finished)
        if not admitted:
            return
        launch: List[tuple] = []
        for slot, req, n_cached in admitted:
            if req.kv_stage == "promoting":
                # promoted KV is still in flight: the request holds its slot
                # (prefilled_len = device + promoted cache credit) and its
                # prefill launches from _advance_promotions when the copy
                # lands — never against un-landed blocks
                req.prefilled_len = n_cached
                self.slots[slot] = req
            else:
                launch.append((slot, req, n_cached))
        self._prefill_batch(launch, finished)

    def _prefill_batch(self, admitted: List[tuple], finished: List[Request]):
        """Launch monolithic prefill for ``[(slot, req, n_cached), ...]`` —
        the back half of :meth:`_admit`, also invoked from
        :meth:`_advance_promotions` for requests whose prefill was deferred
        behind a host-tier promotion."""
        if not admitted:
            return
        # batch prefills, grouped by padded UNCACHED suffix length (bounded
        # retraces; a cache hit shortens the fed sequence, not just the FLOPs)
        by_bucket: Dict[int, List[tuple]] = {}
        for slot, req, n_cached in admitted:
            by_bucket.setdefault(_bucket(len(req.prompt_ids) - n_cached),
                                 []).append((slot, req, n_cached))
        for padded, group in by_bucket.items():
            n = _bucket(len(group), minimum=1)
            ids = np.zeros((n, padded), np.int32)
            tables = np.zeros((n, self.mgr.max_blocks_per_seq), np.int32)
            suffix_lens = np.zeros(n, np.int32)
            cached_lens = np.zeros(n, np.int32)
            sampling: List = [None] * n
            for j, (slot, req, n_cached) in enumerate(group):
                suffix = req.prompt_ids[n_cached:]
                ids[j, : len(suffix)] = suffix
                tables[j] = self.mgr.table_array(req.req_id)
                suffix_lens[j] = len(suffix)
                cached_lens[j] = n_cached
                sampling[j] = req.sampling
            entries = [(j, req.prompt_ids, c) for j, (_, req, c) in enumerate(group)]
            cached_total = int(cached_lens.sum())  # sync-ok: cached_lens is host numpy
            with TRACER.span("prefill", cat="engine", bucket=padded, batch=len(group),
                             step=self._cur_step,
                             req_ids=[r.req_id for _, r, _ in group],
                             cached_tokens=cached_total), \
                    compile_attribution(self.ledger, "prefill"):
                t_dev = time.perf_counter()
                # adapter_table only with a registry attached: prebuilt test
                # backends predating the kwarg keep working registry-off
                extra = ({"adapter_table": [r.adapter_slot for _, r, _ in group]}
                         if self.adapter_registry is not None else {})
                tokens = self.backend.prefill(
                    ids, tables, suffix_lens, entries, sampling,
                    [slot for slot, _, _ in group], **extra)
                self._step_device_s += time.perf_counter() - t_dev
            # goodput: fed = the padded launch geometry; useful = the uncached
            # suffixes minus any re-fed (post-preemption/requeue/COW) positions
            acct = self.backend.step_accounting
            g_useful = g_rework = 0
            g_by: Dict[str, int] = {}
            for slot, req, n_cached in group:
                n_fed = len(req.prompt_ids) - n_cached
                rw, by = self._note_fed_span(req, n_cached, n_fed)
                g_useful += n_fed - rw
                g_rework += rw
                self._merge_rework(g_by, by)
            self.ledger.note_shape(acct["shape"])
            self.ledger.record(
                "prefill", acct["fed"], g_useful,
                padding=acct["fed"] - g_useful - g_rework,
                rework=g_rework, rework_by=g_by or None)
            for j, (slot, req, _) in enumerate(group):
                req.prefilled_len = len(req.prompt_ids)
                self._settle_sampled(slot, req, int(tokens[j]), finished)  # sync-ok: tokens already host (backend.prefill synced)

    def _settle_sampled(self, slot: int, req: Request, tok: int, finished: List[Request]):
        """Post-sample bookkeeping shared by every sampling site (monolithic
        prefill, mixed-step final chunks, mixed-step decode rows): emit, then
        either retire the request (KV freed / prefix-cache registered, slot
        vacated) or keep it decoding in its slot."""
        self._emit(req, tok)
        if req.done:
            self._free_kv(req, cache=True)
            self.slots[slot] = None
            finished.append(req)
        else:
            self.slots[slot] = req
            self._last_token[slot] = tok
            if self.staged and req.kv_stage == "prefill" and not req.needs_prefill:
                # prefill done (first token sampled on the prefill stage):
                # the sequence decodes only after its blocks land in the
                # decode pool — queue the migration, don't block the step
                req.kv_stage = "migrating"
                req.migrate_start_t = time.time()  # migration-wait episode opens
                self._migrate_pending.append(req.req_id)

    # ------------------------------------------------------------------ chunked prefill
    def _admit_chunked(self, finished: List[Request]):
        """Chunked admission: bind slots + allocate KV, but launch NO prefill —
        the request sits in its slot with ``prefilled_len`` = its prefix-cache
        hit and :meth:`_mixed_step` feeds the rest chunk by chunk."""
        admitted = self._admit_slots(finished)
        if not admitted:
            return
        slot_idx = []
        for slot, req, n_cached in admitted:
            req.prefilled_len = n_cached
            self.slots[slot] = req
            slot_idx.append(slot)
        # seed the device-side penalty counts: the cached span never rides
        # through a chunk forward, so its counts come from a host bincount
        # (zeros rows still land — the slot's previous occupant is stale)
        self.backend.seed_counts(
            slot_idx, [(i, req.prompt_ids, c) for i, (_, req, c) in enumerate(admitted)])

    def _mixed_step(self, finished: List[Request]):
        """One ragged mixed step: up to ``prefill_chunk_tokens`` prompt tokens
        (split across mid-prefill slots, oldest request first) plus ONE decode token
        for every running sequence, in a single forward. Decode keeps flowing
        while a long prompt fills — the per-step stall is bounded by the chunk
        budget, not the prompt length."""
        _F_CHUNK.fire(
            prefilling=sum(1 for r in self.slots if r is not None and r.needs_prefill))
        # capacity pass: every decoding slot needs a block covering this step's
        # KV write. Oldest slots secure theirs first; exhaustion preempts the
        # YOUNGEST active slot — which may be a mid-prefill request (its chunk
        # progress resets on requeue; mid-prefill rows themselves never grow,
        # their full-prompt blocks were reserved at admission).
        for slot in sorted(
                [s for s, r in enumerate(self.slots)
                 if r is not None and not r.needs_prefill
                 and r.kv_stage == "decode"],
                key=lambda s: self.slots[s].req_id):
            req = self.slots[slot]
            if req is None or req.needs_prefill:
                continue  # victim of an earlier iteration's preemption
            while True:
                grow = req.total_len - self.mgr.lengths[req.req_id]
                if grow <= 0 or self.mgr.extend(req.req_id, grow) is not None:
                    break
                active = [s for s, r in enumerate(self.slots) if r is not None]
                victim = max(active, key=lambda s: self.slots[s].req_id)
                self._preempt(victim, cause="mixed_capacity")
                if victim == slot:
                    break
        # the capacity pass may have popped LRU blocks: enqueue their D2H
        # gather before the mixed forward can overwrite them
        self._drain_spills()
        budget = self.prefill_chunk_tokens
        chunk_rows: List[tuple] = []  # (slot, req, n_new)
        decode_rows: List[tuple] = []  # (slot, req)
        prefilling: List[int] = []
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if req.kv_stage == "promoting":
                continue  # promoted KV still in flight: no row until it lands
            if req.needs_prefill:
                prefilling.append(slot)
            elif req.kv_stage == "decode":
                decode_rows.append((slot, req))
            # else: migrating — contributes no row until its blocks land
        # the OLDEST mid-prefill request drinks the chunk budget first: slot
        # order would let a newly-admitted prompt landing in a lower slot
        # starve an older one indefinitely under sustained admissions
        for slot in sorted(prefilling, key=lambda s: self.slots[s].req_id):
            if budget <= 0:
                break
            req = self.slots[slot]
            n = min(budget, len(req.prompt_ids) - req.prefilled_len)
            chunk_rows.append((slot, req, n))
            budget -= n
            RECORDER.record("chunk.grant", req_id=req.req_id, trace=req.trace,
                            tokens=n, budget_left=budget, step=self._cur_step)
        if not chunk_rows and not decode_rows:
            return
        t0 = time.perf_counter()
        chunk_payload = []
        for slot, req, n in chunk_rows:
            p0 = req.prefilled_len
            chunk_payload.append(MixedRow(
                slot=slot, tokens=req.prompt_ids[p0 : p0 + n], start=p0,
                table=self.mgr.table_array(req.req_id),
                emit=p0 + n == len(req.prompt_ids),  # sampler on last chunk
                sampling=req.sampling, is_chunk=True,
                adapter=req.adapter_slot))
        dec_payload = [
            MixedRow(slot=slot, tokens=np.asarray([self._last_token[slot]], np.int32),  # sync-ok: _last_token is a host array
                     start=req.total_len - 1,  # position of the token being fed
                     table=self.mgr.table_array(req.req_id), emit=True,
                     sampling=req.sampling, is_chunk=False,
                     adapter=req.adapter_slot)
            for slot, req in decode_rows]
        with TRACER.span("mixed_step", cat="engine", step=self._cur_step,
                         chunks=len(chunk_rows), decodes=len(decode_rows),
                         chunk_tokens=int(sum(n for _, _, n in chunk_rows)),
                         req_ids=[r.req_id for _, r, _ in chunk_rows]), \
                compile_attribution(self.ledger, "mixed"):
            t_dev = time.perf_counter()
            tokens = self.backend.mixed_step(chunk_payload, dec_payload)
            self._step_device_s += time.perf_counter() - t_dev
        dur = time.perf_counter() - t0
        # goodput accounting BEFORE settle mutates prefilled_len/total_len:
        # chunk tokens + the one fed token per decode row are useful (minus
        # re-fed positions); the padded launch remainder is padding
        acct = self.backend.step_accounting
        g_useful = g_rework = 0
        g_by: Dict[str, int] = {}
        for _slot, req, n in chunk_rows:
            rw, by = self._note_fed_span(req, req.prefilled_len, n)
            g_useful += n - rw
            g_rework += rw
            self._merge_rework(g_by, by)
        for _slot, req in decode_rows:
            rw, by = self._note_fed_span(req, req.total_len - 1, 1)
            g_useful += 1 - rw
            g_rework += rw
            self._merge_rework(g_by, by)
        self.ledger.note_shape(acct["shape"])
        self.ledger.record(
            "mixed", acct["fed"], g_useful,
            padding=acct["fed"] - g_useful - g_rework,
            rework=g_rework, rework_by=g_by or None)
        if chunk_rows:
            # every decode token in this step waited out the chunk work: the
            # step duration is each riding request's decode-stall share
            # (accumulated BEFORE settle so a request finishing this very
            # step still carries it into its attribution)
            for _slot, req in decode_rows:
                req.chunk_stall_s += dur
        for j, (slot, req, n) in enumerate(chunk_rows):
            req.prefilled_len += n
            self.chunk_stats["chunks"] += 1
            self.chunk_stats["chunk_tokens"] += n
            self.recent_chunk_sizes.append((next(self._chunk_seq), n))
            if not req.needs_prefill:
                self._settle_sampled(slot, req, int(tokens[j]), finished)  # sync-ok: tokens already host (backend.mixed_step synced)
        for j, (slot, req) in enumerate(decode_rows):
            self._settle_sampled(slot, req, int(tokens[len(chunk_rows) + j]), finished)  # sync-ok: tokens already host (backend.mixed_step synced)
        if chunk_rows and decode_rows:
            # every decode token in this step waited out the chunk work: the
            # step duration IS the decode stall attributable to prefill
            self.recent_decode_stalls.append((next(self._chunk_seq), dur))

    # ------------------------------------------------------------------ speculative
    def _spec_mode(self) -> Optional[str]:
        """'greedy' when every active request decodes greedily with penalties
        off (deterministic acceptance); 'sample' when a draft model is attached
        and every request does plain temperature sampling (top-k/top-p and
        penalties off) — that path accepts drafts by REJECTION SAMPLING, which
        preserves the target distribution exactly (the generalization the
        reference implements in top_p_sampling_reject.cu); None otherwise."""
        greedy = sample = True
        for r in self.slots:
            if r is None:
                continue
            s = r.sampling
            if s.repetition_penalty != 1.0 or s.presence_penalty != 0.0 \
                    or s.frequency_penalty != 0.0:
                return None
            if s.do_sample:
                greedy = False
                if s.top_k or (s.top_p < 1.0):
                    sample = False
            else:
                sample = False
        if greedy:
            return "greedy"
        if sample and self.draft_model is not None:
            return "sample"
        return None

    def _propose_drafts(self, req: Request) -> np.ndarray:
        """Prompt-lookup (n-gram) proposer: find the most recent earlier
        occurrence of the sequence's final n-gram and propose the tokens that
        followed it. Draft-model-free — the proposer the reference pairs with
        its speculative write ops for repetitive/extractive workloads."""
        k = min(self.spec_draft_len, max(req.remaining_new - 1, 0))
        n = self.spec_ngram
        if k == 0:
            return np.zeros(0, np.int32)
        hist = np.concatenate([req.prompt_ids, np.asarray(req.output_ids, np.int32)])
        if len(hist) <= n:
            return np.zeros(0, np.int32)
        pat = hist[-n:]
        windows = np.lib.stride_tricks.sliding_window_view(hist, n)
        starts = np.nonzero((windows == pat).all(axis=1))[0]
        starts = starts[starts < len(hist) - n]  # exclude the suffix itself
        if len(starts) == 0:
            return np.zeros(0, np.int32)
        s = int(starts[-1])
        return hist[s + n : s + n + k].astype(np.int32)

    def _propose_drafts_draft_model(self, mode: str):
        """Autoregressive draft-model proposer: K greedy/sampled steps of the
        small model over a FIXED padded buffer (one compile per length bucket;
        the draft is orders of magnitude cheaper than the target so the full
        recompute per step is noise). Returns (drafts per slot, draft probs per
        slot — [k, V] fp32 temperature-applied, None in greedy mode)."""
        active = [i for i, r in enumerate(self.slots) if r is not None]
        K = self.spec_draft_len
        ctxs = {i: np.concatenate([self.slots[i].prompt_ids,
                                   np.asarray(self.slots[i].output_ids, np.int32)])
                for i in active}
        ks = {i: min(K, max(self.slots[i].remaining_new - 1, 0)) for i in active}
        if not active or all(ks[i] == 0 for i in active):
            return [np.zeros(0, np.int32)] * len(self.slots), [None] * len(self.slots)
        max_len = max(len(c) for c in ctxs.values())
        L = 1 << max(6, (max_len + K - 1).bit_length())  # pow2 bucket caps recompiles
        B = len(active)
        ids = np.zeros((B, L), np.int32)
        lens = np.zeros(B, np.int32)
        for j, i in enumerate(active):
            ids[j, : len(ctxs[i])] = ctxs[i]
            lens[j] = len(ctxs[i])
        drafts = {i: [] for i in active}
        qprobs = {i: [] for i in active}
        for t in range(K):
            mask = (np.arange(L)[None, :] < (lens + t)[:, None]).astype(np.int32)
            out = self.draft_model(input_ids=jnp.asarray(ids), attention_mask=jnp.asarray(mask))
            # gather each sequence's next-token row ON DEVICE: only [B, V]
            # crosses to host, not the [B, L, V] tensor
            rows = np.asarray(jnp.take_along_axis(
                out.logits, jnp.asarray(lens + t - 1)[:, None, None], axis=1)[:, 0],
                dtype=np.float32)
            for j, i in enumerate(active):
                if t >= ks[i]:
                    continue
                row = rows[j]
                temp = max(self.slots[i].sampling.temperature, 1e-6)
                if mode == "sample":
                    row = row / temp
                    p = np.exp(row - row.max())
                    p /= p.sum()
                    nxt = int(self._req_rng(self.slots[i]).choice(len(p), p=p))
                    qprobs[i].append(p)
                else:
                    nxt = int(np.argmax(row))
                drafts[i].append(nxt)
                ids[j, lens[j] + t] = nxt
        out_d = [np.asarray(drafts.get(i, []), np.int32) for i in range(len(self.slots))]
        out_q = [np.asarray(qprobs[i], np.float32) if i in qprobs and qprobs[i] else None
                 for i in range(len(self.slots))]
        return out_d, out_q

    def _preempt(self, slot: int, cause: str = "decode_growth"):
        """Evict + requeue with prompt+generated as the new prompt (recompute
        recovery, the step.cu is_block_step/recover list). ``cause`` names
        which capacity pass chose the victim (decode table growth, a mixed
        step's capacity pass, or the speculative K+1 reservation)."""
        req = self.slots[slot]
        logger.warning(f"req {req.req_id}: KV blocks exhausted; preempting (recompute)")
        self.num_preemptions += 1
        RECORDER.record("preempt", req_id=req.req_id, trace=req.trace,
                        reason=cause, generated=len(req.output_ids),
                        free_blocks=self.mgr.num_free)
        TRACER.instant("preempt", cat="engine", trace=req.trace, req_id=req.req_id,
                       generated=len(req.output_ids), free_blocks=self.mgr.num_free)
        if req.migrate_start_t is not None:
            # an open migration-wait episode ends here (the blocks are gone;
            # re-admission restarts the walk) — bank the wait for attribution
            req.migration_wait_s += time.time() - req.migrate_start_t
            req.migrate_start_t = None
        if req.promote_start_t is not None:
            # same for an open promote-wait episode: the in-flight H2D copy
            # targets blocks being freed; re-admission re-matches the tier
            req.promote_wait_s += time.time() - req.promote_start_t
            req.promote_start_t = None
        self._drop_promotion(req.req_id)
        if not self.staged and req.kv_stage == "promoting":
            req.kv_stage = "decode"  # the single-pool default
        self._free_kv(req)
        self.slots[slot] = None
        req.prompt_ids = np.concatenate([req.prompt_ids, np.asarray(req.output_ids, np.int32)])  # sync-ok: host-side id lists
        req.output_ids = []
        # a half-prefilled request's KV is gone with its blocks: re-admission
        # starts the chunk walk over (prefix-cache hits re-credit what they can)
        req.prefilled_len = 0
        # from here on, re-fed positions are THIS preemption's recompute —
        # even for a request that originally arrived as a supervisor requeue
        req.rework_src = "preempt_refill"
        if self.staged:
            # any in-flight/deferred migration is moot: re-admission
            # re-prefills on the prefill stage and re-migrates
            self._drop_migration(req.req_id)
            req.kv_stage = "prefill"
        self.waiting.appendleft(req)

    def _req_rng(self, req) -> np.random.Generator:
        """Per-request generator seeded by (engine seed, SamplingParams.seed,
        req_id) — a request's rejection-sampling draws reproduce under re-runs
        with the same seed, matching the device sampler's per-request contract."""
        if req.req_id not in self._spec_rngs:
            self._spec_rngs[req.req_id] = np.random.default_rng(
                (self._spec_seed, req.sampling.seed, req.req_id))
        return self._spec_rngs[req.req_id]

    def _decode_spec(self, finished: List[Request], drafts: List[np.ndarray],
                     qprobs=None, mode: str = "greedy"):
        """One speculative iteration: verify the proposed drafts for the whole
        batch in ONE [B, K+1] forward, then accept on the host — greedy mode
        takes the longest argmax-matching prefix plus the model's bonus token;
        sample mode runs Leviathan rejection sampling against the draft probs
        (accept x_i w.p. min(1, p_i(x_i)/q_i(x_i)); on reject draw from
        normalize(max(p_i - q_i, 0))), which emits EXACT target-distribution
        samples. 1..K+1 tokens per sequence per forward either way."""
        K = self.spec_draft_len
        # reserve capacity for all K+1 optimistic KV writes; preempt on OOM
        active = [s for s in range(len(self.slots)) if self.slots[s] is not None]
        for slot in sorted(active, key=lambda s: -self.slots[s].req_id):
            req = self.slots[slot]
            grow = req.total_len + K - self.mgr.lengths[req.req_id]
            if grow > 0 and self.mgr.extend(req.req_id, grow) is None:
                self._preempt(slot, cause="spec_reserve")
        # the reservation pass may have popped LRU blocks: enqueue their D2H
        # gather before the verify forward can overwrite them
        self._drain_spills()
        if not any(r is not None for r in self.slots):
            return

        B = self.max_batch_size
        tokens = np.zeros((B, K + 1), np.int32)
        tables = np.zeros((B, self.mgr.max_blocks_per_seq), np.int32)
        start = np.zeros(B, np.int32)
        for i, req in enumerate(self.slots):
            if req is None:
                drafts[i] = np.zeros(0, np.int32)
                continue
            d = drafts[i]
            tokens[i, 0] = self._last_token[i]
            tokens[i, 1 : 1 + len(d)] = d
            tables[i] = self.mgr.table_array(req.req_id)
            start[i] = req.total_len - 1  # position of the token being fed
        with TRACER.span("spec_verify", cat="engine", mode=mode, step=self._cur_step,
                         drafted=int(sum(len(d) for d in drafts))), \
                compile_attribution(self.ledger, "verify"):
            # greedy acceptance never reads the logits: need_logits=False keeps
            # the [B, K+1, V] fp32 buffer from materializing at all
            t_dev = time.perf_counter()
            extra = ({"adapter_table": [0 if r is None else r.adapter_slot
                                        for r in self.slots]}
                     if self.adapter_registry is not None else {})
            argmax, logits = self.backend.verify(
                tokens, tables, start, need_logits=mode == "sample", **extra)
            self._step_device_s += time.perf_counter() - t_dev
        self.spec_stats["verify_steps"] += 1
        # goodput: drafted-but-rejected positions are the spec_rejected waste
        # bucket; emitted (accepted + correction/bonus) positions are useful
        g_acc0 = self.spec_stats["accepted"]
        g_drafted = g_emitted = 0
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            d = drafts[i]
            g_drafted += len(d)
            self.spec_stats["drafted"] += len(d)
            req.spec_drafted += len(d)
            if mode == "sample":
                with TRACER.span("sampling", cat="engine", trace=req.trace,
                                 req_id=req.req_id, kind="rejection", drafted=len(d)):
                    emitted = self._accept_rejection(i, req, d, logits[i], qprobs[i])
            else:
                targets = argmax[i]
                n_acc = 0
                while n_acc < len(d) and targets[n_acc] == d[n_acc]:
                    n_acc += 1
                emitted = list(d[:n_acc]) + [int(targets[n_acc])]  # sync-ok: argmax already host (backend.verify synced)
                self.spec_stats["accepted"] += n_acc
                req.spec_accepted += n_acc
            for tok in emitted:
                self._emit(req, int(tok))
                self._last_token[i] = int(tok)
                self.spec_stats["tokens_emitted"] += 1
                g_emitted += 1
                # per-tenant fold: accepted/bonus tokens are the useful verify
                # positions (rejected drafts are step-global spec waste)
                self._tenant_counts(req.tenant)["useful"] += 1
                req.useful_tokens += 1
                if req.done:
                    break
            # the last emitted token was sampled, not fed: mark to total-1
            req.fed_hwm = max(req.fed_hwm, req.total_len - 1)
            if req.done:
                self._free_kv(req, cache=True)
                self.slots[i] = None
                finished.append(req)
            else:
                # release the optimistic blocks past the accepted tokens
                self.mgr.shrink(req.req_id, req.total_len)
        g_rejected = g_drafted - (self.spec_stats["accepted"] - g_acc0)
        acct = self.backend.step_accounting
        self.ledger.note_shape(acct["shape"])
        self.ledger.record(
            "verify", acct["fed"], g_emitted,
            padding=acct["fed"] - g_emitted - g_rejected,
            spec_rejected=g_rejected)

    def _accept_rejection(self, slot: int, req, d: np.ndarray, logits_row: np.ndarray,
                          q: Optional[np.ndarray]) -> List[int]:
        """Leviathan et al. rejection sampling over one row: returns the tokens
        to emit (accepted prefix + correction-or-bonus sample)."""
        temp = max(req.sampling.temperature, 1e-6)
        rng = self._req_rng(req)
        emitted: List[int] = []
        for t in range(len(d)):
            row = logits_row[t] / temp
            p = np.exp(row - row.max())
            p /= p.sum()
            x = int(d[t])
            qv = float(q[t][x]) if q is not None else 1.0
            if rng.uniform() < min(1.0, float(p[x]) / max(qv, 1e-20)):
                emitted.append(x)
                self.spec_stats["accepted"] += 1
                req.spec_accepted += 1
                continue
            residual = np.maximum(p - (q[t] if q is not None else 0.0), 0.0)
            s = residual.sum()
            residual = residual / s if s > 0 else p
            emitted.append(int(rng.choice(len(residual), p=residual)))
            return emitted
        # every draft accepted: bonus token from the position after the last draft
        row = logits_row[len(d)] / temp
        p = np.exp(row - row.max())
        p /= p.sum()
        emitted.append(int(rng.choice(len(p), p=p)))
        return emitted

    def _decode_running(self, finished: List[Request]):
        # migrating slots (staged backends) hold KV that has not landed in the
        # decode pool yet: they ride no decode row this step — a step with
        # ONLY migrating slots launches nothing and just re-polls next step
        if not any(r is not None and r.kv_stage == "decode" for r in self.slots):
            return
        # speculative decoding needs every active slot advancing in lockstep;
        # a mid-migration slot would verify against un-landed KV, so the spec
        # path waits for an all-decode-ready batch (the chunked-prefill
        # carve-out, extended to the stage handoff window)
        all_ready = all(r is None or r.kv_stage == "decode" for r in self.slots)
        mode = self._spec_mode() if (self.use_speculative and all_ready) else None
        if mode is not None:
            # propose first: when NO slot has a draft, a verify forward would
            # emit 1 token/seq for (K+1)x the compute — use the multi-step
            # decode instead and only pay for verification when drafts exist
            with TRACER.span("spec_propose", cat="engine", mode=mode,
                             step=self._cur_step,
                             proposer="draft_model" if self.draft_model is not None else "ngram"):
                if self.draft_model is not None:
                    drafts, qprobs = self._propose_drafts_draft_model(mode)
                else:
                    drafts = [np.zeros(0, np.int32) if r is None else self._propose_drafts(r)
                              for r in self.slots]
                    qprobs = [None] * len(self.slots)
            if any(len(d) for d in drafts):
                return self._decode_spec(finished, drafts, qprobs, mode)
        steps = self.decode_steps
        # grow tables for up to `steps` tokens; preempt (recompute-requeue)
        # youngest on exhaustion. Surplus is shrunk back after the device call.
        start_len: Dict[int, int] = {}
        active = [s for s in range(len(self.slots))
                  if self.slots[s] is not None and self.slots[s].kv_stage == "decode"]
        for slot in sorted(active, key=lambda s: -self.slots[s].req_id):
            req = self.slots[slot]
            needed = min(steps, req.remaining_new)
            start_len[req.req_id] = self.mgr.lengths[req.req_id]
            if self.mgr.extend(req.req_id, max(needed, 1)) is None:
                start_len.pop(req.req_id, None)
                self._preempt(slot)
        # extends may have popped LRU blocks: enqueue their D2H gather before
        # the decode forward can overwrite them
        self._drain_spills()

        if not any(r is not None and r.kv_stage == "decode" for r in self.slots):
            return
        B = self.max_batch_size
        tokens = np.array(self._last_token, np.int32)  # sync-ok: _last_token is a host array
        tables = np.zeros((B, self.mgr.max_blocks_per_seq), np.int32)
        ctx = np.zeros(B, np.int32)
        done0 = np.ones(B, bool)
        remaining = np.zeros(B, np.int32)
        for i, req in enumerate(self.slots):
            if req is None or req.kv_stage != "decode":
                continue  # migrating rows stay frozen (done0) like empty slots
            tables[i] = self.mgr.table_array(req.req_id)
            ctx[i] = req.total_len - 1  # position of the token being fed
            done0[i] = False
            remaining[i] = req.remaining_new
        with TRACER.span("decode", cat="engine", steps=steps, step=self._cur_step,
                         active=int(sum(1 for r in self.slots if r is not None))), \
                compile_attribution(self.ledger, "decode"):
            # ONE host transfer of ids + validity flags (no logits)
            t_dev = time.perf_counter()
            extra = ({"adapter_table": [0 if r is None else r.adapter_slot
                                        for r in self.slots]}
                     if self.adapter_registry is not None else {})
            toks, valid = self.backend.decode(
                tokens, tables, ctx, done0, remaining,
                [None if r is None else r.sampling for r in self.slots], **extra)
            self._step_device_s += time.perf_counter() - t_dev
        n_emitted = 0
        for s in range(toks.shape[0]):
            for i, req in enumerate(self.slots):
                if req is None or req.done or not valid[s, i]:
                    continue
                self._emit(req, int(toks[s, i]))  # sync-ok: toks already host (backend.decode synced)
                self._last_token[i] = int(toks[s, i])  # sync-ok: toks already host (backend.decode synced)
                n_emitted += 1
                # per-tenant fold: each emitted decode token consumed one fed
                # position (this path bypasses _note_fed_span)
                self._tenant_counts(req.tenant)["useful"] += 1
                req.useful_tokens += 1
        # goodput: the decode jit always burns B x decode_steps positions;
        # every emitted token is one useful fed position, the rest (idle
        # slots, post-EOS sub-steps, unconsumed budget) is padding
        acct = self.backend.step_accounting
        for req in self.slots:
            if req is not None and req.kv_stage == "decode":
                # the last emitted token was sampled, not fed: mark to total-1
                req.fed_hwm = max(req.fed_hwm, req.total_len - 1)
        self.ledger.note_shape(acct["shape"])
        self.ledger.record("decode", acct["fed"], n_emitted,
                           padding=acct["fed"] - n_emitted)
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if req.done:
                self._free_kv(req, cache=True)
                self.slots[i] = None
                finished.append(req)
            elif req.req_id in start_len:
                # return speculative blocks past the tokens actually produced
                self.mgr.shrink(req.req_id, req.total_len)

    def _emit(self, req: Request, tok: int):
        try:
            if req.first_token_t is None:
                req.first_token_t = time.time()
            req.output_ids.append(tok)
            self._tenant_counts(req.tenant)["tokens_out"] += 1
            is_eos = tok in self.eos_ids
            hit_max = req.gen_offset + len(req.output_ids) >= req.sampling.max_new_tokens
            req.done = is_eos or hit_max
            if req.done:
                req.finish_t = time.time()
                req.finish_reason = "stop" if is_eos else "length"
            if req.stream_cb is not None:
                req.stream_cb(tok, req.done)
        except Exception as e:
            # per-request host failure (a poisoned stream callback, broken
            # sampling bookkeeping): attribute it so the serving supervisor
            # can quarantine THIS slot instead of rebuilding the whole engine
            if getattr(e, "req_id", None) is None:
                try:
                    e.req_id = req.req_id
                except Exception:
                    pass
            raise
