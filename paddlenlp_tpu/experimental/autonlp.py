"""AutoNLP-lite: hyperparameter search over the Trainer.

Counterpart of ``paddlenlp/experimental/autonlp/``
(``AutoTrainerForTextClassification`` text_classification.py:52 — ray-tune HPO
over model/lr/batch candidates, best-trial export). This build has no ray; the
search is an in-process sequential random/grid search — same API surface
(``train`` / ``predict`` / ``export`` / ``visualize``), deterministic seeding.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..trainer import Trainer, TrainingArguments
from ..utils.log import logger

__all__ = ["AutoTrainerForTextClassification"]


@dataclasses.dataclass
class TrialResult:
    trial_id: str
    candidate: Dict[str, Any]
    metrics: Dict[str, float]
    output_dir: str


class AutoTrainerForTextClassification:
    """Random/grid search over (model, lr, batch size, epochs) candidates.

    train_dataset/eval_dataset yield {"input_ids", ["attention_mask"], "labels"};
    metric_for_best_model keys into evaluate()'s output (default eval_loss,
    minimized; any other metric is maximized, the HF convention).
    """

    def __init__(
        self,
        train_dataset,
        eval_dataset,
        *,
        model_candidates: Optional[List[Dict[str, Any]]] = None,
        model_factory: Optional[Callable[[Dict[str, Any]], Any]] = None,
        metric_for_best_model: str = "eval_loss",
        compute_metrics: Optional[Callable] = None,
        output_dir: str = "autonlp_output",
        seed: int = 0,
    ):
        if model_factory is None:
            raise ValueError("model_factory (candidate-dict -> fresh model) is required")
        self.train_dataset = train_dataset
        self.eval_dataset = eval_dataset
        self.model_factory = model_factory
        self.model_candidates = model_candidates or [
            {"learning_rate": 3e-5}, {"learning_rate": 1e-4}, {"learning_rate": 3e-4},
        ]
        self.metric = metric_for_best_model
        self.compute_metrics = compute_metrics
        self.output_dir = output_dir
        self.seed = seed
        self.trials: List[TrialResult] = []

    # ------------------------------------------------------------------ search
    def train(self, num_models: Optional[int] = None, max_steps: int = 50,
              per_device_train_batch_size: int = 8, **train_kwargs) -> TrialResult:
        """Run up to ``num_models`` candidates (all by default); returns the best."""
        rng = np.random.default_rng(self.seed)
        cands = list(self.model_candidates)
        if num_models is not None and num_models < len(cands):
            idx = rng.choice(len(cands), size=num_models, replace=False)
            cands = [cands[i] for i in sorted(idx)]
        for i, cand in enumerate(cands):
            trial_id = f"trial_{i}"
            out = os.path.join(self.output_dir, trial_id)
            args = TrainingArguments(
                output_dir=out,
                max_steps=int(cand.get("max_steps", max_steps)),
                learning_rate=float(cand.get("learning_rate", 3e-5)),
                per_device_train_batch_size=int(cand.get("per_device_train_batch_size",
                                                         per_device_train_batch_size)),
                save_strategy="no",
                seed=self.seed,
                **train_kwargs,
            )
            model = self.model_factory(cand)
            trainer = Trainer(model=model, args=args, train_dataset=self.train_dataset,
                              eval_dataset=self.eval_dataset, compute_metrics=self.compute_metrics)
            t0 = time.time()
            trainer.train()
            metrics = trainer.evaluate()
            metrics["train_runtime"] = time.time() - t0
            trainer.save_model(out)
            self.trials.append(TrialResult(trial_id, cand, metrics, out))
            logger.info(f"autonlp {trial_id}: {cand} -> {self.metric}={metrics.get(self.metric)}")
        return self.best_trial

    @property
    def best_trial(self) -> TrialResult:
        if not self.trials:
            raise RuntimeError("no trials ran; call train() first")
        minimize = self.metric.endswith("loss")
        key = lambda t: t.metrics.get(self.metric, float("inf") if minimize else float("-inf"))
        return min(self.trials, key=key) if minimize else max(self.trials, key=key)

    # ------------------------------------------------------------------ results
    def predict(self, test_dataset, trial_id: Optional[str] = None):
        trial = self._get_trial(trial_id)
        model = type(self.model_factory(trial.candidate)).from_pretrained(trial.output_dir)
        args = TrainingArguments(output_dir=trial.output_dir, save_strategy="no")
        trainer = Trainer(model=model, args=args, compute_metrics=self.compute_metrics)
        return trainer.predict(test_dataset)

    def export(self, export_path: str, trial_id: Optional[str] = None) -> str:
        """Copy the chosen trial's saved model to ``export_path``."""
        import shutil

        trial = self._get_trial(trial_id)
        os.makedirs(export_path, exist_ok=True)
        for name in os.listdir(trial.output_dir):
            src = os.path.join(trial.output_dir, name)
            if os.path.isfile(src):
                shutil.copy2(src, export_path)
        return export_path

    def visualize(self) -> List[Dict[str, Any]]:
        """Leaderboard rows (the reference prints the ray-tune table)."""
        rows = [{"trial_id": t.trial_id, **t.candidate, self.metric: t.metrics.get(self.metric)}
                for t in self.trials]
        minimize = self.metric.endswith("loss")
        return sorted(rows, key=lambda r: r[self.metric] or 0, reverse=not minimize)

    def _get_trial(self, trial_id: Optional[str]) -> TrialResult:
        if trial_id is None:
            return self.best_trial
        for t in self.trials:
            if t.trial_id == trial_id:
                return t
        raise ValueError(f"unknown trial {trial_id!r}; ran {[t.trial_id for t in self.trials]}")
