"""Checkpoint / artifact resolution.

Counterpart of ``paddlenlp/utils/downloader.py`` + ``paddlenlp/utils/download/``:
the reference resolves model names against BOS / HF hub / aistudio / modelscope.
This build resolves, in order:

1. a local directory path,
2. the local framework cache (``MODEL_HOME/<name>``),
3. the HuggingFace hub via ``huggingface_hub`` **if network access is available**
   (gated — zero-egress environments skip it cleanly).
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional

from .env import MODEL_HOME
from .log import logger

__all__ = ["resolve_file", "resolve_model_dir", "get_path_from_url"]


def _cache_dir(name: str) -> str:
    return os.path.join(MODEL_HOME, *name.split("/"))


def resolve_model_dir(pretrained_model_name_or_path: str, cache_dir: Optional[str] = None) -> str:
    """Return a local directory holding the artifacts for ``name``; raise if unresolvable."""
    name = str(pretrained_model_name_or_path)
    if os.path.isdir(name):
        return name
    local = cache_dir or _cache_dir(name)
    if os.path.isdir(local):
        return local
    raise FileNotFoundError(
        f"'{name}' is not a local directory and is not present in the cache ({local}). "
        "Download it with huggingface_hub or place files there manually."
    )


def resolve_file(
    pretrained_model_name_or_path: str, filename: str, cache_dir: Optional[str] = None, required: bool = True
) -> Optional[str]:
    """Resolve one artifact file (config.json, model.safetensors, ...) to a local path."""
    name = str(pretrained_model_name_or_path)
    if os.path.isfile(name):
        return name
    candidates: List[str] = []
    if os.path.isdir(name):
        candidates.append(os.path.join(name, filename))
    candidates.append(os.path.join(cache_dir or _cache_dir(name), filename))
    for c in candidates:
        if os.path.isfile(c):
            return c
    path = _try_hf_hub(name, filename, cache_dir)
    if path is not None:
        return path
    if required:
        raise FileNotFoundError(f"cannot resolve '{filename}' for '{name}' (searched {candidates})")
    return None


def _try_hf_hub(repo_id: str, filename: str, cache_dir: Optional[str]) -> Optional[str]:
    if os.environ.get("PDNLP_TPU_OFFLINE", "0") == "1":
        return None
    try:
        from huggingface_hub import hf_hub_download

        return hf_hub_download(repo_id=repo_id, filename=filename, cache_dir=cache_dir)
    except Exception as e:  # network-less, missing dep, missing file — all non-fatal
        logger.debug(f"hf hub resolution failed for {repo_id}/{filename}: {e}")
        return None


def get_path_from_url(url: str, root_dir: str) -> str:
    """Fetch ``url`` into ``root_dir`` (reference: downloader.py:get_path_from_url)."""
    fname = os.path.join(root_dir, url.split("/")[-1])
    if os.path.isfile(fname):
        return fname
    os.makedirs(root_dir, exist_ok=True)
    import urllib.request

    tmp = fname + ".tmp"
    with urllib.request.urlopen(url) as resp, open(tmp, "wb") as f:
        shutil.copyfileobj(resp, f)
    os.replace(tmp, fname)
    return fname
