"""Safetensors read/write with lazy per-tensor slicing.

TPU-native counterpart of ``paddlenlp/utils/safetensors.py`` (numpy fast loader with
``__getitem__`` slicing) and ``paddlenlp/transformers/model_utils.py:349-448``
(``_load_part_state_dict`` / ``load_state_dict``). We parse the safetensors header
ourselves and back tensors with ``numpy.memmap`` so that:

- sharded / tensor-parallel loads can slice a tensor without materializing it
  (critical when a v5e host loads only its own NamedSharding shard);
- no framework tensors are created until ``jax.device_put`` places the shard.
"""

from __future__ import annotations

import json
import math
import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

__all__ = [
    "SafeFile",
    "SafeSlice",
    "load_file",
    "save_file",
    "safe_keys",
]

_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "BF16": None,  # handled specially (numpy has no bfloat16)
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
    "U16": np.uint16,
    "U32": np.uint32,
    "U64": np.uint64,
}

_DTYPE_NAMES = {
    np.dtype(np.float64): "F64",
    np.dtype(np.float32): "F32",
    np.dtype(np.float16): "F16",
    np.dtype(np.int64): "I64",
    np.dtype(np.int32): "I32",
    np.dtype(np.int16): "I16",
    np.dtype(np.int8): "I8",
    np.dtype(np.uint8): "U8",
    np.dtype(np.bool_): "BOOL",
    np.dtype(np.uint16): "U16",
    np.dtype(np.uint32): "U32",
    np.dtype(np.uint64): "U64",
}


def _ml_bfloat16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _decode_dtype(name: str):
    if name == "BF16":
        return _ml_bfloat16()
    dt = _DTYPES.get(name)
    if dt is None:
        raise ValueError(f"unsupported safetensors dtype {name}")
    return np.dtype(dt)


def _encode_dtype(dtype: np.dtype) -> str:
    try:
        import ml_dtypes

        if dtype == np.dtype(ml_dtypes.bfloat16):
            return "BF16"
    except ImportError:
        pass
    name = _DTYPE_NAMES.get(np.dtype(dtype))
    if name is None:
        raise ValueError(f"unsupported dtype for safetensors: {dtype}")
    return name


class SafeSlice:
    """Lazy view over one tensor in a safetensors file; supports numpy basic slicing."""

    def __init__(self, mmap: np.memmap, dtype: np.dtype, shape: Tuple[int, ...], start: int, end: int):
        self._mmap = mmap
        self.dtype = dtype
        self.shape = tuple(shape)
        self._start = start
        self._end = end

    def get_shape(self) -> List[int]:
        return list(self.shape)

    def get_dtype(self) -> np.dtype:
        return self.dtype

    @property
    def nbytes(self) -> int:
        return self._end - self._start

    def _view(self) -> np.ndarray:
        raw = self._mmap[self._start : self._end]
        arr = raw.view(self.dtype)
        return arr.reshape(self.shape) if self.shape else arr.reshape(())

    def __getitem__(self, index) -> np.ndarray:
        # memmap-backed: only the touched pages are read from disk.
        out = self._view()[index]
        return np.ascontiguousarray(out).reshape(out.shape)  # keep 0-d as 0-d

    def numpy(self) -> np.ndarray:
        return self[...]


class SafeFile:
    """Zero-copy safetensors reader (header parse + memmap-backed slices)."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            header_len = struct.unpack("<Q", f.read(8))[0]
            header = json.loads(f.read(header_len).decode("utf-8"))
        self.metadata = header.pop("__metadata__", {})
        self._entries = header
        self._data_offset = 8 + header_len
        self._mmap = np.memmap(path, dtype=np.uint8, mode="r", offset=self._data_offset)

    def keys(self) -> List[str]:
        return list(self._entries.keys())

    def __contains__(self, key: str) -> bool:
        return key in self._entries

    def get_slice(self, key: str) -> SafeSlice:
        ent = self._entries[key]
        start, end = ent["data_offsets"]
        return SafeSlice(self._mmap, _decode_dtype(ent["dtype"]), tuple(ent["shape"]), start, end)

    def get_tensor(self, key: str) -> np.ndarray:
        return self.get_slice(key).numpy()

    def items(self) -> Iterator[Tuple[str, np.ndarray]]:
        for k in self.keys():
            yield k, self.get_tensor(k)

    def close(self):
        self._mmap = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def safe_keys(path: str) -> List[str]:
    with open(path, "rb") as f:
        header_len = struct.unpack("<Q", f.read(8))[0]
        header = json.loads(f.read(header_len).decode("utf-8"))
    header.pop("__metadata__", None)
    return list(header.keys())


def load_file(path: str, keys: Optional[List[str]] = None) -> Dict[str, np.ndarray]:
    sf = SafeFile(path)
    out = {}
    for k in keys if keys is not None else sf.keys():
        out[k] = sf.get_tensor(k)
    return out


def save_file(tensors: Dict[str, np.ndarray], path: str, metadata: Optional[Dict[str, str]] = None):
    """Write a safetensors file (streams tensor-by-tensor, no double buffering)."""
    header: Dict[str, dict] = {}
    if metadata:
        header["__metadata__"] = dict(metadata)
    offset = 0
    arrays = {}
    for name, arr in tensors.items():
        orig = np.asarray(arr)
        arr = np.ascontiguousarray(orig)  # NB: promotes 0-d to 1-d; header keeps orig shape
        arrays[name] = arr
        nbytes = arr.nbytes
        header[name] = {
            "dtype": _encode_dtype(arr.dtype),
            "shape": list(orig.shape),
            "data_offsets": [offset, offset + nbytes],
        }
        offset += nbytes
    blob = json.dumps(header, separators=(",", ":")).encode("utf-8")
    # pad header to 8-byte alignment, as the upstream format does
    pad = (-(8 + len(blob))) % 8
    blob += b" " * pad
    tmp = path + ".tmp"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "wb") as f:
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        for name, arr in arrays.items():
            f.write(arr.tobytes())
    os.replace(tmp, path)


def shard_checkpoint(
    tensors: Dict[str, np.ndarray], max_shard_size: int = 5 * 1024**3, weights_name: str = "model.safetensors"
) -> Tuple[List[Tuple[str, Dict[str, np.ndarray]]], Optional[dict]]:
    """Split a state dict into shards under ``max_shard_size`` bytes.

    Mirrors ``paddlenlp/transformers/model_utils.py:561`` (shard_checkpoint): returns
    ``[(filename, shard_dict), ...]`` and an index dict (or None for a single shard).
    """
    shards: List[Dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for name, arr in tensors.items():
        nbytes = np.asarray(arr).nbytes
        if sizes[-1] + nbytes > max_shard_size and sizes[-1] > 0:
            shards.append({})
            sizes.append(0)
        shards[-1][name] = arr
        sizes[-1] += nbytes
    if len(shards) == 1:
        return [(weights_name, shards[0])], None
    stem, ext = os.path.splitext(weights_name)
    n = len(shards)
    named = [(f"{stem}-{i + 1:05d}-of-{n:05d}{ext}", shard) for i, shard in enumerate(shards)]
    weight_map = {}
    for fname, shard in named:
        for key in shard:
            weight_map[key] = fname
    index = {"metadata": {"total_size": int(sum(sizes))}, "weight_map": weight_map}
    return named, index
