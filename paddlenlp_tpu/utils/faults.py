"""Deterministic fault-injection harness: named fault points with trigger specs.

Large-scale TPU training/serving treats preemption and partial failure as the
common case; the only way recovery paths stay honest is to execute them in
tier-1 on every PR. This module gives the codebase *named fault points* —
``FaultPoint("ckpt.write_shard")``, ``FaultPoint("engine.step")``, ... — that
are free when disarmed (one attribute read) and, when armed, fire
deterministically according to a trigger spec:

- **nth**: fire on specific hit numbers (1-based, comma list) — "kill the save
  on the 2nd shard write";
- **p + seed**: fire with fixed-seed probability per hit — reproducible chaos;
- **times**: cap total fires (default 1 — most chaos tests want exactly one
  crash, not a crash loop);
- **action**: ``raise`` (:class:`InjectedFault`), ``delay`` (sleep
  ``delay_s``), or ``partial`` (truncate the file the call site is writing,
  *then* raise — a torn write, not just a missing one).

Arming is programmatic (``FAULTS.arm(...)`` in tests, always through a
``try/finally FAULTS.reset()``) or via the ``PDNLP_TPU_FAULTS`` env var so a
real training job can be chaos-tested without code changes::

    PDNLP_TPU_FAULTS="ckpt.write_shard:nth=2:action=partial;engine.step:p=0.05:seed=7"

Every fault-point name must be registered in :data:`CATALOG` (name → doc);
``tools/check_faults.py`` lints that call sites and catalog agree, and tier-1
enforces it — an undocumented fault point is a typo waiting to disarm a test.

Stdlib-only on purpose: the checkpoint writer, the serving loop, and the lint
tool all import this without pulling in jax.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Dict, Optional, Tuple

__all__ = [
    "CATALOG",
    "FAULTS",
    "FaultPoint",
    "FaultRegistry",
    "InjectedFault",
]

ENV_VAR = "PDNLP_TPU_FAULTS"

#: Single source of truth for fault-point names. A :class:`FaultPoint` whose
#: name is missing here raises at construction; ``tools/check_faults.py``
#: additionally fails if a catalog entry has no call site or no doc.
CATALOG: Dict[str, str] = {
    "ckpt.write_shard": "After each optimizer/model shard file is written in the "
                        "checkpoint staging dir, before the commit manifest. 'partial' "
                        "truncates the shard mid-file — a torn write.",
    "ckpt.commit": "Immediately before the commit manifest is written and the staging "
                   "dir is renamed into place — a crash here must leave the previous "
                   "committed checkpoint as the resume target.",
    "engine.step": "Top of InferenceEngine.step() — an exception here is what the "
                   "engine-loop supervisor must absorb (degrade, rebuild, requeue).",
    "engine.rebuild": "Inside the supervisor's engine-rebuild attempt — failing it "
                      "extends the DEGRADED window (503 + Retry-After) deterministically.",
    "engine.shard_init": "Top of ShardedBackend.__init__, before the device mesh and "
                         "NamedSharding layouts are built — a failure here makes a "
                         "sharded-engine construction (including the supervisor's "
                         "rebuild of one) fail deterministically: the loop must go "
                         "DEGRADED, retry the rebuild and recover with zero stream "
                         "loss.",
    "engine.prefill_chunk": "Top of the engine's ragged mixed prefill/decode step, "
                            "before the capacity pass and chunk schedule — a crash here leaves "
                            "requests partially prefilled (no token emitted) and must "
                            "triage through the supervisor with token-exact retry and "
                            "no leaked KV blocks.",
    "engine.kv_migrate": "Immediately before the engine dispatches one sequence's "
                         "prefill→decode KV-block migration (disaggregated backend) — "
                         "a failure here hits a request whose first token already "
                         "streamed; the supervisor must degrade, rebuild both stages "
                         "and requeue token-exactly with no block leaked in either "
                         "pool.",
    "serving.submit": "Inside Scheduler.submit after the admission slot is taken — "
                      "exercises the release-on-error path and HTTP 500 mapping.",
    "router.forward": "Immediately before the router opens the upstream connection for "
                      "one forwarding attempt — an injected failure is handled exactly "
                      "like a socket error: candidate excluded, request re-routed or "
                      "failed over to the next replica.",
    "router.health_poll": "Inside the ReplicaPool prober before the /health scrape of "
                          "one replica — injected failures drive the HEALTHY → DEGRADED "
                          "→ DOWN demotion deterministically without killing a server.",
    "router.membership": "Top of a ReplicaPool membership mutation (add / drain / "
                         "remove), before any state changes — a failure here must "
                         "leave the replica set exactly as it was (the admin plane "
                         "returns 5xx, the pool stays consistent, traffic unaffected).",
    "router.provision": "Top of one autoscaler provision attempt, before the "
                        "ReplicaProvisioner starts a new replica — a failure here must "
                        "retry with backoff on later control-loop ticks, never strand a "
                        "tombstoned (force-removed DOWN) replica unreplaced, and never "
                        "leave a half-joined replica in the pool.",
    "sched.shed": "Inside the scheduler's brownout shed path, after the shed decision "
                  "but before the rejection is raised — an injected failure here must "
                  "surface as a clean 500 with no admission-window slot taken and no "
                  "engine-side state.",
    "engine.slot_rebuild": "Inside the supervisor's slot-level quarantine of one "
                           "poisoned request, before its KV blocks are released — a "
                           "failure here escalates to the full engine rebuild path "
                           "(DEGRADED, triage, rebuild) deterministically.",
    "usage.seal": "Inside UsageLedger segment sealing, after the open segment's "
                  "last append but before the atomic rename-commit of the sealed "
                  "file — a crash here must leave a loadable ledger (the open "
                  "segment's torn tail dropped + counted, every sealed byte "
                  "intact). 'partial' truncates the open segment mid-line first: "
                  "the torn-write case the reload tolerance exists for.",
    "engine.adapter_load": "Inside AdapterRegistry.acquire, after the pool-slot "
                           "decision but before the adapter weights land in the "
                           "device pool — the failure carries the acquiring "
                           "request's req_id so the supervisor quarantines ONLY "
                           "that tenant's request (engine_error / token-exact "
                           "retry); other tenants' streams must be uninterrupted "
                           "and no adapter slot or KV block may leak.",
    "engine.weight_load": "Inside the /admin/weights handler, before the committed "
                          "checkpoint is validated and loaded — a failure here must "
                          "map to a clean HTTP error with ZERO engine-side mutation "
                          "(no params touched, no cache epoch bumped, the loop "
                          "keeps serving under the old weights).",
    "engine.weight_swap": "Inside the engine loop's quiesced swap execution, after "
                          "the old params are retained but before sync_params "
                          "installs the new tree — a failure here must roll the "
                          "replica back to the retained old weights (cache epoch "
                          "re-bumped, canary skipped) with zero stream loss and "
                          "no param-buffer or KV-block leak.",
    "router.rollout": "Top of one per-replica rollout step (drain → swap → rejoin) "
                      "in the router's fleet weight rollout, before the drain is "
                      "initiated — a failure here must abort the whole rollout, "
                      "roll already-swapped replicas back to the old version, "
                      "undrain everything and leave the fleet serving on the old "
                      "weights with zero client-visible errors.",
    "engine.kv_spill": "Inside the engine's spill drain, before the batched D2H "
                       "gather of LRU-evicted prefix blocks into the host KV tier — "
                       "a failure here must simply drop the spill (the blocks were "
                       "already recycled; pre-tier behavior) with no host- or "
                       "device-tier entry leaked and every live stream unaffected.",
    "engine.kv_promote": "Immediately before the engine dispatches a host→device "
                         "KV promotion for an admitted request whose prefix "
                         "matched host-tier blocks — a failure here must fall "
                         "back token-exactly to a cold re-prefill of the promoted "
                         "span (the request keeps its allocated blocks, prefill "
                         "recomputes them), with zero stream loss and no host- or "
                         "device-tier block leak.",
}


class InjectedFault(RuntimeError):
    """Raised by an armed fault point. Deliberately an *ordinary* exception:
    recovery code must treat it exactly like a real ValueError/OSError from
    the same call site."""

    def __init__(self, point: str, hit: int):
        super().__init__(f"injected fault at {point!r} (hit {hit})")
        self.point = point
        self.hit = hit


@dataclasses.dataclass
class TriggerSpec:
    """How an armed fault point decides to fire (see module docstring)."""

    action: str = "raise"  # raise | delay | partial
    nth: Optional[Tuple[int, ...]] = None  # 1-based hit numbers; None = every hit
    p: Optional[float] = None  # per-hit fire probability (with fixed seed)
    seed: int = 0
    times: Optional[int] = 1  # max total fires; None = unlimited
    delay_s: float = 0.05

    def __post_init__(self):
        if self.action not in ("raise", "delay", "partial"):
            raise ValueError(f"fault action must be raise/delay/partial, got {self.action!r}")
        if self.nth is not None and self.p is not None:
            raise ValueError("trigger spec takes nth= OR p=, not both")


def _parse_spec(text: str) -> Tuple[str, TriggerSpec]:
    """``"name:key=val:key=val"`` → (name, TriggerSpec). Used for env arming."""
    parts = [p for p in text.strip().split(":") if p]
    if not parts:
        raise ValueError("empty fault spec")
    name, kw = parts[0], {}
    for part in parts[1:]:
        if "=" not in part:
            raise ValueError(f"fault spec field {part!r} is not key=value")
        k, v = part.split("=", 1)
        if k == "nth":
            kw["nth"] = tuple(int(x) for x in v.split(","))
        elif k == "p":
            kw["p"] = float(v)
        elif k in ("seed", "times"):
            kw[k] = int(v)
        elif k == "delay_s":
            kw["delay_s"] = float(v)
        elif k == "action":
            kw["action"] = v
        else:
            raise ValueError(f"unknown fault spec field {k!r}")
    return name, TriggerSpec(**kw)


class FaultRegistry:
    """Process-wide armed-fault state. Thread-safe; the disarmed fast path is
    a single attribute read so fault points can sit on hot paths."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, TriggerSpec] = {}
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._enabled = False  # lock-free fast-path flag
        self._env_loaded = False

    # ----------------------------------------------------------------- arming
    def arm(self, name: str, action: str = "raise", nth=None, p: Optional[float] = None,
            seed: int = 0, times: Optional[int] = 1, delay_s: float = 0.05) -> TriggerSpec:
        """Arm ``name`` with a trigger spec (replaces any existing spec and
        resets its hit/fire counters). ``nth`` may be an int or an iterable."""
        if name not in CATALOG:
            raise ValueError(f"unknown fault point {name!r}; register it in faults.CATALOG")
        if isinstance(nth, int):
            nth = (nth,)
        elif nth is not None:
            nth = tuple(int(x) for x in nth)
        spec = TriggerSpec(action=action, nth=nth, p=p, seed=seed, times=times, delay_s=delay_s)
        with self._lock:
            self._armed[name] = spec
            self._hits[name] = 0
            self._fired[name] = 0
            self._rngs[name] = random.Random(seed)
            self._enabled = True
        return spec

    def arm_from_spec(self, text: str):
        """Arm from a ``;``-separated spec string (the ``PDNLP_TPU_FAULTS`` format)."""
        for chunk in text.split(";"):
            chunk = chunk.strip()
            if not chunk:
                continue
            name, spec = _parse_spec(chunk)
            self.arm(name, action=spec.action, nth=spec.nth, p=spec.p, seed=spec.seed,
                     times=spec.times, delay_s=spec.delay_s)

    def load_env(self, force: bool = False):
        """Arm from ``PDNLP_TPU_FAULTS`` once per process (idempotent)."""
        with self._lock:
            if self._env_loaded and not force:
                return
            self._env_loaded = True
        text = os.environ.get(ENV_VAR, "")
        if text:
            self.arm_from_spec(text)

    def disarm(self, name: Optional[str] = None):
        """Disarm one point (or all with ``name=None``); counters survive."""
        with self._lock:
            if name is None:
                self._armed.clear()
            else:
                self._armed.pop(name, None)
            self._enabled = bool(self._armed)

    def reset(self):
        """Disarm everything and clear counters — every test's ``finally``."""
        with self._lock:
            self._armed.clear()
            self._hits.clear()
            self._fired.clear()
            self._rngs.clear()
            self._enabled = False

    # ----------------------------------------------------------------- state
    def armed(self, name: str) -> Optional[TriggerSpec]:
        with self._lock:
            return self._armed.get(name)

    def hits(self, name: str) -> int:
        with self._lock:
            return self._hits.get(name, 0)

    def fired(self, name: str) -> int:
        with self._lock:
            return self._fired.get(name, 0)

    # ----------------------------------------------------------------- firing
    def fire(self, name: str, file: Optional[str] = None, **ctx):
        """One hit of fault point ``name``. No-op unless armed and the trigger
        spec selects this hit. ``file`` names the file the call site is mid-way
        through writing — the ``partial`` action truncates it before raising."""
        if not self._enabled:
            return
        with self._lock:
            spec = self._armed.get(name)
            if spec is None:
                return
            self._hits[name] = hit = self._hits.get(name, 0) + 1
            if spec.times is not None and self._fired.get(name, 0) >= spec.times:
                return
            if spec.nth is not None:
                should = hit in spec.nth
            elif spec.p is not None:
                should = self._rngs[name].random() < spec.p
            else:
                should = True
            if not should:
                return
            self._fired[name] = self._fired.get(name, 0) + 1
            action, delay_s = spec.action, spec.delay_s
        # act outside the lock: sleeping or truncating under it would serialize
        # unrelated fault points
        if action == "delay":
            time.sleep(delay_s)
            return
        if action == "partial" and file is not None and os.path.isfile(file):
            size = os.path.getsize(file)
            with open(file, "r+b") as f:
                f.truncate(size // 2)
        raise InjectedFault(name, hit)


#: process-wide registry (env-armed lazily on first FaultPoint fire)
FAULTS = FaultRegistry()


class FaultPoint:
    """A named place where a fault can be injected.

    Declare once at module level (``_F_STEP = FaultPoint("engine.step")``) and
    call ``.fire(**ctx)`` on the hot path — the disarmed cost is one attribute
    read plus a method call. Construction validates the name against
    :data:`CATALOG` so typos fail at import, not silently never-fire."""

    __slots__ = ("name", "_registry")

    def __init__(self, name: str, registry: Optional[FaultRegistry] = None):
        if name not in CATALOG:
            raise ValueError(f"unknown fault point {name!r}; register it in faults.CATALOG")
        self.name = name
        self._registry = registry or FAULTS

    def fire(self, file: Optional[str] = None, **ctx):
        r = self._registry
        if not r._env_loaded:
            r.load_env()
        if r._enabled:
            r.fire(self.name, file=file, **ctx)

    def __repr__(self):
        return f"FaultPoint({self.name!r})"
