"""Step-windowed device profiling.

Counterpart of ``paddlenlp/utils/profiler.py`` (``ProfilerOptions`` :28,
``add_profiler_step`` :88 — timeline export controlled by the
``--profiler_options`` launch flag). TPU-native: the window drives
``jax.profiler.start_trace``/``stop_trace``, producing an XPlane/TensorBoard
trace of the XLA device timeline — AND the host-side span timeline for the
same step range: when the window closes, every observability-tracer span
recorded inside it is written to ``<profile_path>/span_timeline.json`` (Chrome
trace-event JSON, open in Perfetto next to the device trace) and
``<profile_path>/spans.jsonl``. One flag, both timelines.

Options string: ``key=value`` pairs separated by ``;``, e.g.
``batch_range=[10,20];profile_path=./profile_out`` — the trace covers steps
[start, end) of ``batch_range``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple

from ..observability.tracer import TRACER
from .log import logger

__all__ = ["ProfilerOptions", "ProfilerStepper", "add_profiler_step"]


@dataclasses.dataclass
class ProfilerOptions:
    batch_range: Tuple[int, int] = (10, 12)
    profile_path: str = "profile_out"

    @classmethod
    def parse(cls, options: str) -> "ProfilerOptions":
        out = cls()
        for item in (options or "").split(";"):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(f"profiler option {item!r} is not key=value")
            k, v = (s.strip() for s in item.split("=", 1))
            if k == "batch_range":
                nums = [int(x) for x in v.strip("[]() ").replace(",", " ").split()]
                if len(nums) != 2 or nums[0] < 0 or nums[1] <= nums[0]:
                    raise ValueError(f"batch_range must be [start, end) with end>start>=0, got {v!r}")
                out.batch_range = (nums[0], nums[1])
            elif k == "profile_path":
                out.profile_path = v
            else:
                logger.warning(f"ignoring unknown profiler option {k!r}")
        return out


class ProfilerStepper:
    """Call ``step(global_step)`` once per train step; traces the configured
    window exactly once."""

    def __init__(self, options: ProfilerOptions, tracer=TRACER):
        self.options = options
        self.tracer = tracer
        self._active = False
        self._done = False
        self._window_t0: Optional[float] = None

    def step(self, global_step: int):
        import jax

        start, end = self.options.batch_range
        if self._done:
            return
        if not self._active and global_step >= start and global_step < end:
            jax.profiler.start_trace(self.options.profile_path)
            self._active = True
            # anchored-timeline cursor (snapshot since_ts compares span.ts,
            # which is perf-anchored — a wall-clock step must not empty the window)
            self._window_t0 = self.tracer.now()
            self.tracer.instant("profiler_window_start", cat="profiler",
                                trace="train", step=global_step)
            logger.info(f"profiler: tracing steps [{global_step}, {end}) -> {self.options.profile_path}")
        elif self._active and global_step >= end:
            self._stop(global_step)

    def _stop(self, global_step: Optional[int] = None):
        import jax

        self.tracer.instant("profiler_window_stop", cat="profiler",
                            trace="train", step=global_step)
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        self._dump_spans()
        logger.info(f"profiler: trace written to {self.options.profile_path}")

    def _dump_spans(self):
        """Write the window's host-side span timeline next to the device trace
        (same step range — filtered by the window's start timestamp)."""
        try:
            os.makedirs(self.options.profile_path, exist_ok=True)
            spans = self.tracer.snapshot(since_ts=self._window_t0)
            path = os.path.join(self.options.profile_path, "span_timeline.json")
            self.tracer.write_chrome_trace(path, spans)
            with open(os.path.join(self.options.profile_path, "spans.jsonl"), "w") as f:
                f.write(self.tracer.to_jsonl(spans) + "\n")
            logger.info(f"profiler: {len(spans)} host spans -> {path}")
        except Exception as e:  # span dump must never fail the run
            logger.warning(f"profiler: span timeline dump failed: {e!r}")

    def close(self):
        if self._active:
            self._stop()


_GLOBAL: Optional[ProfilerStepper] = None


def add_profiler_step(options: Optional[str], global_step: int):
    """Stateless entry mirroring the reference's add_profiler_step: feed the
    step counter; start/stop happen at the window edges."""
    global _GLOBAL
    if not options:
        return
    if _GLOBAL is None:
        _GLOBAL = ProfilerStepper(ProfilerOptions.parse(options))
    _GLOBAL.step(global_step)
