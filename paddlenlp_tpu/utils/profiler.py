"""Step-windowed device profiling.

Counterpart of ``paddlenlp/utils/profiler.py`` (``ProfilerOptions`` :28,
``add_profiler_step`` :88 — timeline export controlled by the
``--profiler_options`` launch flag). TPU-native: the window drives
``jax.profiler.start_trace``/``stop_trace``, producing an XPlane/TensorBoard
trace of the XLA device timeline.

Options string: ``key=value`` pairs separated by ``;``, e.g.
``batch_range=[10,20];profile_path=./profile_out`` — the trace covers steps
[start, end) of ``batch_range``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from .log import logger

__all__ = ["ProfilerOptions", "ProfilerStepper", "add_profiler_step"]


@dataclasses.dataclass
class ProfilerOptions:
    batch_range: Tuple[int, int] = (10, 12)
    profile_path: str = "profile_out"

    @classmethod
    def parse(cls, options: str) -> "ProfilerOptions":
        out = cls()
        for item in (options or "").split(";"):
            item = item.strip()
            if not item:
                continue
            if "=" not in item:
                raise ValueError(f"profiler option {item!r} is not key=value")
            k, v = (s.strip() for s in item.split("=", 1))
            if k == "batch_range":
                nums = [int(x) for x in v.strip("[]() ").replace(",", " ").split()]
                if len(nums) != 2 or nums[0] < 0 or nums[1] <= nums[0]:
                    raise ValueError(f"batch_range must be [start, end) with end>start>=0, got {v!r}")
                out.batch_range = (nums[0], nums[1])
            elif k == "profile_path":
                out.profile_path = v
            else:
                logger.warning(f"ignoring unknown profiler option {k!r}")
        return out


class ProfilerStepper:
    """Call ``step(global_step)`` once per train step; traces the configured
    window exactly once."""

    def __init__(self, options: ProfilerOptions):
        self.options = options
        self._active = False
        self._done = False

    def step(self, global_step: int):
        import jax

        start, end = self.options.batch_range
        if self._done:
            return
        if not self._active and global_step >= start and global_step < end:
            jax.profiler.start_trace(self.options.profile_path)
            self._active = True
            logger.info(f"profiler: tracing steps [{global_step}, {end}) -> {self.options.profile_path}")
        elif self._active and global_step >= end:
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            logger.info(f"profiler: trace written to {self.options.profile_path}")

    def close(self):
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True


_GLOBAL: Optional[ProfilerStepper] = None


def add_profiler_step(options: Optional[str], global_step: int):
    """Stateless entry mirroring the reference's add_profiler_step: feed the
    step counter; start/stop happen at the window edges."""
    global _GLOBAL
    if not options:
        return
    if _GLOBAL is None:
        _GLOBAL = ProfilerStepper(ProfilerOptions.parse(options))
    _GLOBAL.step(global_step)
