"""Colored, rank-aware logging.

TPU-native counterpart of the reference's ``paddlenlp/utils/log.py`` (colorlog-based
singleton logger). Here rank-awareness comes from ``jax.process_index()`` instead of
``paddle.distributed`` env vars; only process 0 logs at INFO by default.

Structured mode: ``PDNLP_TPU_LOG_JSON=1`` switches the formatter to one JSON
object per line (``ts``/``level``/``logger``/``msg``/``file``/``line`` [+
``exc``, + ``trace`` when a span-tracer trace id is ambient]) so serving and
trainer logs are machine-parseable — the shape log shippers (fluentbit/vector)
and ``jq`` expect, and the ``trace`` key grep-joins fleet logs to stitched
``/debug/trace`` timelines. ``logger.set_json(True)`` toggles it at runtime.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import sys
import threading
import time

__all__ = ["logger"]

_COLORS = {
    "DEBUG": "\033[35m",  # purple
    "INFO": "\033[32m",  # green
    "WARNING": "\033[33m",  # yellow
    "ERROR": "\033[31m",  # red
    "CRITICAL": "\033[31;1m",
}
_RESET = "\033[0m"


def _process_index() -> int:
    # Avoid importing jax at module import time (jax init is expensive and
    # ordering-sensitive wrt XLA_FLAGS); fall back to env contract.
    try:
        import jax

        # jax.process_index() initializes the backend; only call if initialized.
        if jax._src.xla_bridge._backends:  # noqa: SLF001
            return jax.process_index()
    except Exception:
        pass
    return int(os.environ.get("JAX_PROCESS_INDEX", os.environ.get("RANK", "0")))


class _ColorFormatter(logging.Formatter):
    def format(self, record):  # noqa: A003
        color = _COLORS.get(record.levelname, "")
        timestamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(record.created))
        msg = record.getMessage()
        if record.exc_info:
            msg = msg + "\n" + self.formatException(record.exc_info)
        return f"{color}[{timestamp}] [{record.levelname:>8}]{_RESET} {record.pathname.split('/')[-1]}:{record.lineno} - {msg}"


def _ambient_trace():
    """Active span-tracer trace id (None outside a traced request). Imported
    lazily: observability pulls this module in at import time, so a top-level
    import here would be circular."""
    try:
        from ..observability.tracer import current_trace

        return current_trace()
    except Exception:
        return None


class _JsonFormatter(logging.Formatter):
    """One JSON object per line; keys stable for log shippers."""

    def format(self, record):  # noqa: A003
        out = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "file": record.pathname.split("/")[-1],
            "line": record.lineno,
        }
        trace = _ambient_trace()
        if trace is not None:
            out["trace"] = trace
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class Logger:
    """Singleton logger with level context manager, mirroring reference semantics."""

    _instance = None
    _lock = threading.Lock()

    def __new__(cls, *args, **kwargs):
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = super().__new__(cls)
        return cls._instance

    def __init__(self, name: str = "paddlenlp_tpu"):
        if getattr(self, "_initialized", False):
            return
        self._initialized = True
        self.logger = logging.getLogger(name)
        self.logger.propagate = False
        self._handler = logging.StreamHandler(sys.stderr)
        json_mode = os.environ.get("PDNLP_TPU_LOG_JSON", "").lower() in ("1", "true", "yes")
        self._handler.setFormatter(_JsonFormatter() if json_mode else _ColorFormatter())
        self.logger.addHandler(self._handler)
        level = os.environ.get("PDNLP_TPU_LOG_LEVEL", "INFO").upper()
        self.logger.setLevel(level)

    def set_json(self, enabled: bool = True):
        """Switch between JSON-lines and colored human formatting."""
        self._handler.setFormatter(_JsonFormatter() if enabled else _ColorFormatter())

    def _log(self, level: int, msg, *args):
        if _process_index() != 0 and level < logging.WARNING:
            return
        self.logger.log(level, msg, *args, stacklevel=3)

    def debug(self, msg, *args):
        self._log(logging.DEBUG, msg, *args)

    def info(self, msg, *args):
        self._log(logging.INFO, msg, *args)

    def warning(self, msg, *args):
        self._log(logging.WARNING, msg, *args)

    def error(self, msg, *args):
        self._log(logging.ERROR, msg, *args)

    def critical(self, msg, *args):
        self._log(logging.CRITICAL, msg, *args)

    @functools.lru_cache(maxsize=None)  # noqa: B019
    def warning_once(self, msg):
        self.warning(msg)

    def set_level(self, level: str):
        self.logger.setLevel(level.upper())

    class _LevelContext:
        def __init__(self, logger: "Logger", level: str):
            self._logger = logger
            self._level = level.upper()
            self._old = None

        def __enter__(self):
            self._old = self._logger.logger.level
            self._logger.logger.setLevel(self._level)
            return self._logger

        def __exit__(self, *exc):
            self._logger.logger.setLevel(self._old)

    def processing(self, level: str = "DEBUG"):
        return Logger._LevelContext(self, level)


logger = Logger()
