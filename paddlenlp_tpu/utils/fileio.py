"""Crash-safe file helpers: tmp+rename atomic writes and fsync plumbing.

A process can die at any byte of a ``write()`` — after a preemption, the only
states a reader may observe for a file are "old content" or "new content in
full". ``atomic_write`` gives that contract to every small metadata file the
stack persists (``trainer_state.json``, the checkpoint ``commit.json``): the
payload is written to a same-directory temp file, flushed, fsync'd, and
``os.replace``'d over the target (atomic on POSIX within one filesystem).
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import IO, Iterator

__all__ = ["atomic_write", "fsync_file", "fsync_dir"]


def fsync_file(path: str):
    """fsync an already-written file's contents to stable storage."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str):
    """fsync a directory entry (makes a rename/creation durable). Best-effort:
    some filesystems refuse O_RDONLY on dirs — crash-consistency degrades to
    the filesystem's journal guarantee there, which is still rename-atomic."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "w", encoding: str = None,
                 fsync: bool = True) -> Iterator[IO]:
    """``with atomic_write(p) as f: f.write(...)`` — all-or-nothing replace.

    The temp file lives in the target's directory (rename must not cross
    filesystems). On any exception the temp file is removed and the target is
    untouched; on success the replace is atomic and (with ``fsync=True``) the
    rename itself is made durable by fsyncing the parent directory."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".", suffix=".tmp")
    try:
        if "b" in mode:
            f = os.fdopen(fd, mode)
        else:
            f = os.fdopen(fd, mode, encoding=encoding)
        with f:
            yield f
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        if fsync:
            fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
