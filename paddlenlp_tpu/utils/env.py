"""Cache-dir / environment contract.

Counterpart of ``paddlenlp/utils/env.py`` (MODEL_HOME etc.) and
``paddlenlp/utils/tools.py::get_env_device``, re-targeted at JAX platforms.
"""

from __future__ import annotations

import os

__all__ = [
    "MODEL_HOME",
    "DATA_HOME",
    "PDNLP_TPU_HOME",
    "get_env_device",
    "device_peak_flops",
    "CONFIG_NAME",
    "GENERATION_CONFIG_NAME",
    "MODEL_WEIGHTS_NAME",
    "SAFE_WEIGHTS_NAME",
    "SAFE_WEIGHTS_INDEX_NAME",
    "TOKENIZER_CONFIG_NAME",
    "CHAT_TEMPLATE_NAME",
]


def _get_home() -> str:
    home = os.environ.get("PDNLP_TPU_HOME")
    if home is None:
        home = os.path.join(os.path.expanduser("~"), ".paddlenlp_tpu")
    return home


PDNLP_TPU_HOME = _get_home()
MODEL_HOME = os.path.join(PDNLP_TPU_HOME, "models")
DATA_HOME = os.path.join(PDNLP_TPU_HOME, "datasets")

# Canonical artifact filenames (reference: paddlenlp/utils/env.py:55-86).
CONFIG_NAME = "config.json"
GENERATION_CONFIG_NAME = "generation_config.json"
MODEL_WEIGHTS_NAME = "model_weights.msgpack"
SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
TOKENIZER_CONFIG_NAME = "tokenizer_config.json"
CHAT_TEMPLATE_NAME = "chat_template.json"


def get_env_device() -> str:
    """Return the active JAX platform name ("tpu", "cpu", "gpu")."""
    try:
        import jax

        platform = jax.devices()[0].platform
        # axon tunnels expose TPU devices under a custom platform name.
        if platform in ("axon",):
            return "tpu"
        return platform
    except Exception:
        return "cpu"


# Peak dense bf16 FLOP/s per chip, for MFU / hardware-TFLOPS metrics
# (reference computes hardware TFLOPS in trainer_utils.py:351-380 from model flops).
_PEAK_FLOPS = {
    "tpu v2": 22.5e12,
    "tpu v3": 61.25e12,  # per chip (2 cores)
    "tpu v4": 137.5e12 * 2,
    "tpu v5 lite": 197e12,
    "tpu v5e": 197e12,
    "tpu v5": 229.5e12 * 2,  # v5p per chip
    "tpu v6 lite": 918e12,
    "a100": 312e12,
    "h100": 989e12,
}


def device_peak_flops(device=None) -> float:
    """Best-effort peak bf16 FLOP/s of the attached accelerator."""
    try:
        import jax

        if device is None:
            device = jax.devices()[0]
        kind = getattr(device, "device_kind", "").lower()
        for key, val in _PEAK_FLOPS.items():
            if key in kind:
                return val
        if device.platform in ("tpu", "axon"):
            return 197e12  # conservative default: v5e
    except Exception:
        pass
    return 0.0
