"""Optional-dependency gating (reference: paddlenlp/utils/import_utils.py)."""

from __future__ import annotations

import importlib
import importlib.util
from functools import lru_cache

__all__ = [
    "is_package_available",
    "is_tokenizers_available",
    "is_sentencepiece_available",
    "is_datasets_available",
    "is_transformers_available",
    "is_torch_available",
]


@lru_cache(maxsize=None)
def is_package_available(name: str) -> bool:
    try:
        return importlib.util.find_spec(name) is not None
    except ModuleNotFoundError:  # dotted name whose parent isn't installed
        return False


def is_tokenizers_available() -> bool:
    return is_package_available("tokenizers")


def is_sentencepiece_available() -> bool:
    return is_package_available("sentencepiece")


def is_datasets_available() -> bool:
    return is_package_available("datasets")


def is_transformers_available() -> bool:
    return is_package_available("transformers")


def is_torch_available() -> bool:
    return is_package_available("torch")


def require(name: str, hint: str = ""):
    if not is_package_available(name):
        raise ImportError(f"`{name}` is required for this feature. {hint}")
    return importlib.import_module(name)
