from .env import (  # noqa: F401
    CONFIG_NAME,
    DATA_HOME,
    GENERATION_CONFIG_NAME,
    MODEL_HOME,
    SAFE_WEIGHTS_INDEX_NAME,
    SAFE_WEIGHTS_NAME,
    device_peak_flops,
    get_env_device,
)
from .faults import FAULTS, FaultPoint, InjectedFault  # noqa: F401
from .fileio import atomic_write, fsync_dir, fsync_file  # noqa: F401
from .import_utils import is_package_available  # noqa: F401
from .log import logger  # noqa: F401
