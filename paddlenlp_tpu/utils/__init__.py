from .env import (  # noqa: F401
    CONFIG_NAME,
    DATA_HOME,
    GENERATION_CONFIG_NAME,
    MODEL_HOME,
    SAFE_WEIGHTS_INDEX_NAME,
    SAFE_WEIGHTS_NAME,
    device_peak_flops,
    get_env_device,
)
from .import_utils import is_package_available  # noqa: F401
from .log import logger  # noqa: F401
