"""Prefix tuning (reference: paddlenlp/peft/prefix/ — ``PrefixModelForCausalLM``
with per-model past-KV reshape fns).

TPU-native: the learned prefix IS a pre-filled slice of the static KV cache —
no per-model reshape functions needed. Forward: build a cache of size
``num_prefix_tokens + T``, write the (batch-broadcast) prefix K/V, run the base
module with that cache. Only the prefix tensor trains.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...transformers.cache_utils import KVCache
from ...transformers.conversion_utils import flatten_params, unflatten_params
from ...utils.log import logger
from ...utils.safetensors_io import SafeFile, save_file

__all__ = ["PrefixConfig", "PrefixModelForCausalLM"]

PREFIX_WEIGHTS_NAME = "prefix_model.safetensors"
PREFIX_CONFIG_NAME = "prefix_config.json"


@dataclasses.dataclass
class PrefixConfig:
    num_prefix_tokens: int = 64
    init_std: float = 0.02

    def save_pretrained(self, d: str):
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, PREFIX_CONFIG_NAME), "w") as f:
            json.dump(dataclasses.asdict(self), f)

    @classmethod
    def from_pretrained(cls, d: str):
        with open(os.path.join(d, PREFIX_CONFIG_NAME)) as f:
            return cls(**json.load(f))


class PrefixModelForCausalLM:
    def __init__(self, model, prefix_config: Optional[PrefixConfig] = None, params: Optional[dict] = None):
        self.model = model
        self.prefix_config = prefix_config or PrefixConfig()
        self.config = model.config
        self.dtype = model.dtype
        cfg = model.config
        P = self.prefix_config.num_prefix_tokens
        n_kv = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        head_dim = getattr(cfg, "head_dim", cfg.hidden_size // cfg.num_attention_heads)
        if params is not None:
            self.params = params
        else:
            rng = np.random.default_rng(0)
            prefix_kv = rng.standard_normal(
                (cfg.num_hidden_layers, 2, P, n_kv, head_dim)
            ).astype(np.float32) * self.prefix_config.init_std
            self.params = dict(model.params)
            self.params["prefix_kv"] = jnp.asarray(prefix_kv)
        self.module = _PrefixModule(model.module, cfg, self.prefix_config)
        self.mesh = model.mesh
        self.generation_config = model.generation_config
        self._jit_cache: Dict[Any, Any] = {}

    def get_partition_rules_instance(self):
        """Base model rules + replicated prefix (it's tiny)."""
        from ...parallel.partition import P

        return list(type(self.model).get_partition_rules(self.config)) + [(r"^prefix_kv$", P())]

    def get_model_flops(self, *a, **kw):
        return self.model.get_model_flops(*a, **kw)

    def trainable_mask(self) -> dict:
        flat = flatten_params(self.params)
        return unflatten_params({p: p.startswith("prefix_kv") for p in flat})

    def print_trainable_parameters(self):
        n = int(np.prod(self.params["prefix_kv"].shape))
        total = self.model.num_parameters() + n
        logger.info(f"trainable params: {n:,} / {total:,} ({100 * n / total:.3f}%)")

    def __call__(self, *args, **kwargs):
        params = kwargs.pop("params", self.params)
        rngs_kwargs = {}
        out = self.module.apply({"params": params}, *args, **kwargs)
        return out

    def apply(self, params, *args, **kwargs):
        return self.module.apply({"params": params}, *args, **kwargs)

    def num_parameters(self, params=None):
        return self.model.num_parameters(self.model.params) + int(np.prod(self.params["prefix_kv"].shape))

    def save_pretrained(self, d: str, **kw):
        os.makedirs(d, exist_ok=True)
        self.prefix_config.save_pretrained(d)
        save_file({"prefix_kv": np.asarray(jax.device_get(self.params["prefix_kv"]))},
                  os.path.join(d, PREFIX_WEIGHTS_NAME), metadata={"format": "np"})

    @classmethod
    def from_pretrained(cls, model, d: str):
        cfgp = PrefixConfig.from_pretrained(d)
        obj = cls(model, cfgp)
        with SafeFile(os.path.join(d, PREFIX_WEIGHTS_NAME)) as sf:
            obj.params = dict(obj.params)
            obj.params["prefix_kv"] = jnp.asarray(sf.get_tensor("prefix_kv"))
        return obj


class _PrefixModule:
    """Shim module: prepends the learned prefix to a fresh KV cache, then applies
    the base module; logits are returned for the input tokens only."""

    def __init__(self, base_module, config, prefix_config: PrefixConfig):
        self._base = base_module
        self._config = config
        self._prefix_config = prefix_config
        self.dtype = getattr(base_module, "dtype", jnp.float32)

    def apply(self, variables, input_ids=None, attention_mask=None, position_ids=None, **kwargs):
        params = dict(variables["params"] if "params" in variables else variables)
        prefix_kv = params.pop("prefix_kv")
        P = self._prefix_config.num_prefix_tokens
        B, T = input_ids.shape
        L = self._config.num_hidden_layers
        cache_dtype = jnp.bfloat16 if self.dtype == jnp.bfloat16 else jnp.float32
        keys = jnp.zeros((L, B, P + T) + prefix_kv.shape[3:], cache_dtype)
        values = jnp.zeros_like(keys)
        pk = jnp.broadcast_to(prefix_kv[:, 0][:, None], (L, B, P) + prefix_kv.shape[3:]).astype(cache_dtype)
        pv = jnp.broadcast_to(prefix_kv[:, 1][:, None], (L, B, P) + prefix_kv.shape[3:]).astype(cache_dtype)
        keys = keys.at[:, :, :P].set(pk)
        values = values.at[:, :, :P].set(pv)
        cache = KVCache(keys=keys, values=values, offset=jnp.asarray(P, jnp.int32))
        if attention_mask is not None:
            attention_mask = jnp.concatenate([jnp.ones((B, P), attention_mask.dtype), attention_mask,
                                              jnp.zeros((B, 0), attention_mask.dtype)], axis=1)
        if position_ids is None:
            position_ids = jnp.arange(T)[None, :]
        out = self._base.apply({"params": params}, input_ids=input_ids, attention_mask=attention_mask,
                               position_ids=position_ids, cache=cache, **kwargs)
        return out

    def __getattr__(self, item):
        return getattr(self._base, item)
