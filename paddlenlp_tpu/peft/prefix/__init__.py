from .prefix_model import PrefixConfig, PrefixModelForCausalLM  # noqa: F401
