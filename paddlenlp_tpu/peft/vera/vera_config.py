"""VeRA config (reference: paddlenlp/peft/vera/vera_config.py)."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

__all__ = ["VeRAConfig"]

DEFAULT_TARGETS = ["q_proj", "k_proj", "v_proj", "o_proj"]


@dataclasses.dataclass
class VeRAConfig:
    r: int = 64
    d_initial: float = 0.1
    target_modules: Optional[List[str]] = None
    seed: int = 0

    def save_pretrained(self, save_directory: str):
        os.makedirs(save_directory, exist_ok=True)
        with open(os.path.join(save_directory, "vera_config.json"), "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)

    @classmethod
    def from_pretrained(cls, path: str) -> "VeRAConfig":
        with open(os.path.join(path, "vera_config.json")) as f:
            return cls(**json.load(f))
