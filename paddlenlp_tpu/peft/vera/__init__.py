from .vera_config import VeRAConfig  # noqa: F401
from .vera_model import VeRAModel  # noqa: F401
