"""VeRA: Vector-based Random-matrix Adaptation, TPU-native.

Counterpart of ``paddlenlp/peft/vera/`` (``VeRAModel``). One pair of FROZEN
random low-rank bases (A [in, r], B [r, out]) is SHARED by every adapted kernel
of the same shape; only per-layer scaling vectors train:

    W' = W + (A * d) @ (B * b)      d [r] (init ``d_initial``), b [out] (init 0)

~10-100x fewer trainable params than LoRA at the same rank. Same facade design
as LoRAModel: no module surgery — the forward functionally merges the update
before the unchanged base module applies; scanned [L] stacks carry the vectors
per layer while the bases stay unstacked.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...transformers.conversion_utils import flatten_params, unflatten_params
from ...utils.log import logger
from ...utils.safetensors_io import SafeFile, save_file
from .vera_config import DEFAULT_TARGETS, VeRAConfig

__all__ = ["VeRAModel"]

VERA_WEIGHTS_NAME = "vera_model.safetensors"
SHARED_KEY = "vera_shared"


def _merge_vera(params: dict) -> dict:
    shared = params.get(SHARED_KEY, {})

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {k: walk(v) for k, v in node.items() if k != SHARED_KEY}
        if "kernel" in out and "vera_d" in out and "vera_b" in out:
            k = out["kernel"]
            in_dim, out_dim = k.shape[-2], k.shape[-1]
            base = shared[f"{in_dim}x{out_dim}"]
            a = base["A"].astype(jnp.float32)  # [in, r]
            b = base["B"].astype(jnp.float32)  # [r, out]
            d = out["vera_d"].astype(jnp.float32)  # [..., r]
            bv = out["vera_b"].astype(jnp.float32)  # [..., out]
            # per-layer leading axes broadcast against the shared bases
            delta = (a * d[..., None, :]) @ b * bv[..., None, :]
            out = dict(out)
            out["kernel"] = (k.astype(jnp.float32) + delta).astype(k.dtype)
        return out

    merged = walk(params)
    merged[SHARED_KEY] = shared  # keep tree structure stable for jit
    return merged


class _VeRAMergedModule:
    def __init__(self, base_module):
        self._base = base_module
        self.dtype = getattr(base_module, "dtype", jnp.float32)

    def apply(self, variables, *args, **kwargs):
        params = variables["params"] if "params" in variables else variables
        merged = {k: v for k, v in _merge_vera(params).items() if k != SHARED_KEY}
        return self._base.apply({"params": merged}, *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._base, item)


class VeRAModel:
    """Wraps a PretrainedModel; quacks like one (module/params/config/generate)."""

    def __init__(self, model, vera_config: Optional[VeRAConfig] = None, params: Optional[dict] = None):
        self.model = model
        self.vera_config = vera_config or VeRAConfig()
        self.config = model.config
        self.dtype = model.dtype
        self.generation_config = model.generation_config
        patterns = self.vera_config.target_modules or DEFAULT_TARGETS
        self._target_res = [re.compile(p if p.endswith("$") or "/" in p else rf"\b{p}\b") for p in patterns]
        self.params = params if params is not None else self._init_vera_params(model.params)
        self.module = _VeRAMergedModule(model.module)
        self.mesh = model.mesh
        self._jit_cache: Dict[Any, Any] = {}

    def _matches(self, kernel_path: str) -> bool:
        module_path = kernel_path.rsplit("/", 1)[0]
        return any(p.search(module_path) or p.search(kernel_path) for p in self._target_res)

    def _init_vera_params(self, base_params: dict) -> dict:
        cfg = self.vera_config
        rng = np.random.default_rng(cfg.seed)
        flat = flatten_params(base_params)
        out = dict(flat)
        shared: Dict[str, np.ndarray] = {}
        added = 0
        for path, leaf in flat.items():
            if not path.endswith("/kernel") or getattr(leaf, "ndim", 0) < 2 or not self._matches(path):
                continue
            in_dim, out_dim = leaf.shape[-2], leaf.shape[-1]
            lead = leaf.shape[:-2]
            key = f"{in_dim}x{out_dim}"
            if f"{SHARED_KEY}/{key}/A" not in shared:
                shared[f"{SHARED_KEY}/{key}/A"] = (
                    rng.standard_normal((in_dim, cfg.r)).astype(np.float32) / np.sqrt(in_dim)
                )
                shared[f"{SHARED_KEY}/{key}/B"] = (
                    rng.standard_normal((cfg.r, out_dim)).astype(np.float32) / np.sqrt(cfg.r)
                )
            prefix = path.rsplit("/", 1)[0]
            out[prefix + "/vera_d"] = jnp.full(lead + (cfg.r,), cfg.d_initial, jnp.float32)
            out[prefix + "/vera_b"] = jnp.zeros(lead + (out_dim,), jnp.float32)
            added += 1
        if added == 0:
            raise ValueError(f"no modules matched VeRA target patterns {cfg.target_modules}")
        out.update({k: jnp.asarray(v) for k, v in shared.items()})
        logger.info(f"VeRA: {added} kernels adapted (r={cfg.r}, {len(shared) // 2} shared basis pairs)")
        return unflatten_params(out)

    # ------------------------------------------------------------------ training glue
    def trainable_mask(self) -> dict:
        flat = flatten_params(self.params)
        mask = {p: ("/vera_d" in p or "/vera_b" in p) for p in flat}
        return unflatten_params(mask)

    def print_trainable_parameters(self):
        flat = flatten_params(self.params)
        total = sum(int(np.prod(v.shape)) for v in flat.values())
        trainable = sum(int(np.prod(v.shape)) for p, v in flat.items()
                        if "/vera_d" in p or "/vera_b" in p)
        logger.info(f"trainable params: {trainable:,} / {total:,} ({100 * trainable / total:.4f}%)")

    # ------------------------------------------------------------------ facade
    def __call__(self, *args, **kwargs):
        params = kwargs.pop("params", None)
        orig_params, orig_module = self.model.params, self.model.module
        self.model.params = params if params is not None else self.params
        self.model.module = self.module
        try:
            return self.model(*args, **kwargs)
        finally:
            self.model.params = orig_params
            self.model.module = orig_module

    def apply(self, params, *args, **kwargs):
        return self.module.apply({"params": params}, *args, **kwargs)

    def generate(self, *args, **kwargs):
        kwargs.setdefault("params", self.params)
        orig_module = self.model.module
        self.model.module = self.module
        try:
            return self.model.generate(*args, **kwargs)
        finally:
            self.model.module = orig_module

    def num_parameters(self, params=None):
        return self.model.num_parameters(params if params is not None else self.params)

    def get_model_flops(self, *a, **kw):
        return self.model.get_model_flops(*a, **kw)

    def get_partition_rules_instance(self):
        from ...parallel.partition import P

        base = list(type(self.model).get_partition_rules(self.config))
        # vectors are tiny: replicate; shared bases follow the kernel dims loosely
        return base + [(r"vera_(d|b)$", P()), (rf"{SHARED_KEY}/.*/(A|B)$", P())]

    # ------------------------------------------------------------------ save/load
    def save_pretrained(self, save_directory: str, **kw):
        os.makedirs(save_directory, exist_ok=True)
        self.vera_config.save_pretrained(save_directory)
        flat = flatten_params(self.params)
        tensors = {p: np.asarray(jax.device_get(v)) for p, v in flat.items()
                   if "/vera_" in p or p.startswith(SHARED_KEY + "/")}
        save_file(tensors, os.path.join(save_directory, VERA_WEIGHTS_NAME), metadata={"format": "np"})
        logger.info(f"VeRA adapters saved to {save_directory}")

    @classmethod
    def from_pretrained(cls, model, vera_path: str) -> "VeRAModel":
        config = VeRAConfig.from_pretrained(vera_path)
        obj = cls(model, config)
        flat = flatten_params(obj.params)
        with SafeFile(os.path.join(vera_path, VERA_WEIGHTS_NAME)) as sf:
            for key in sf.keys():
                if key not in flat:
                    logger.warning(f"adapter key {key} not in model; skipping")
                    continue
                flat[key] = jnp.asarray(sf.get_tensor(key))
        obj.params = unflatten_params(flat)
        return obj
