from .lora import LoRAConfig, LoRAModel  # noqa: F401
from .prefix import PrefixConfig, PrefixModelForCausalLM  # noqa: F401
from .vera import VeRAConfig, VeRAModel  # noqa: F401
