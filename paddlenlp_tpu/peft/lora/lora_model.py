"""LoRA: low-rank adapters, TPU-native.

Counterpart of ``paddlenlp/peft/lora/lora_model.py`` (``LoRAModel`` :134,
find-and-replace module surgery :427, TP-aware save/merge :320-371) and
``lora_layers.py`` (LoRALinear + Column/Row/SequenceParallel TP variants).

TPU-first redesign — NO module surgery and NO parallel layer variants:
LoRA params (A [in, r], B [r, out]) live as sibling leaves of each targeted
kernel; the forward **functionally merges** ``W' = W + scaling * A @ B`` before
the unchanged base module applies. Gradients flow only to A/B (the trainer masks
the rest), merged lazily under jit so XLA fuses the rank-r update into the layer;
TP sharding falls out of the partition rules (A inherits the kernel's input-dim
sharding, B its output-dim sharding).

With ``lora_dropout > 0`` the merged form is approximate (dropout would apply to
the adapter input only); this implementation keeps the exact merged math and
applies no adapter dropout.
"""

from __future__ import annotations

import math
import os
import re
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...transformers.conversion_utils import flatten_params, unflatten_params
from ...utils.log import logger
from ...utils.safetensors_io import SafeFile, save_file
from .lora_config import DEFAULT_TARGETS, LoRAConfig

__all__ = ["LoRAModel"]

LORA_WEIGHTS_NAME = "lora_model.safetensors"


def _merge_lora(params: dict, scaling: float) -> dict:
    """kernel + scaling * A @ B wherever adapters exist (pure; jit-fusable)."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            out[k] = walk(v)
        if "kernel" in out and "lora_A" in out and "lora_B" in out:
            a, b = out["lora_A"], out["lora_B"]
            # @ batches any leading axes: works for [in,r]@[r,out] and the scanned
            # [L,in,r]@[L,r,out] layout alike
            delta = (a.astype(jnp.float32) @ b.astype(jnp.float32)) * scaling
            out = dict(out)
            out["kernel"] = (out["kernel"].astype(jnp.float32) + delta).astype(out["kernel"].dtype)
        return out

    return walk(params)


class _LoRAMergedModule:
    """Duck-typed linen-module shim: merges adapters, then applies the base module."""

    def __init__(self, base_module, scaling: float):
        self._base = base_module
        self._scaling = scaling
        self.dtype = getattr(base_module, "dtype", jnp.float32)

    def apply(self, variables, *args, **kwargs):
        params = variables["params"] if "params" in variables else variables
        merged = _merge_lora(params, self._scaling)
        return self._base.apply({"params": merged}, *args, **kwargs)

    def __getattr__(self, item):
        return getattr(self._base, item)


class LoRAModel:
    """Wraps a PretrainedModel; quacks like one (module/params/config/generate)."""

    def __init__(self, model, lora_config: Optional[LoRAConfig] = None, params: Optional[dict] = None):
        self.model = model
        self.lora_config = lora_config or LoRAConfig()
        self.config = model.config
        self.dtype = model.dtype
        self.generation_config = model.generation_config
        patterns = self.lora_config.target_modules or [t.rsplit("/", 1)[0] for t in DEFAULT_TARGETS]
        self._target_res = [re.compile(p if p.endswith("$") or "/" in p else rf"\b{p}\b") for p in patterns]
        self.params = params if params is not None else self._init_lora_params(model.params)
        self.module = _LoRAMergedModule(model.module, self.lora_config.scaling)
        self.mesh = model.mesh
        self._jit_cache: Dict[Any, Any] = {}

    # ------------------------------------------------------------------ init
    def _matches(self, kernel_path: str) -> bool:
        module_path = kernel_path.rsplit("/", 1)[0]
        return any(p.search(module_path) or p.search(kernel_path) for p in self._target_res)

    def _init_lora_params(self, base_params: dict) -> dict:
        cfg = self.lora_config
        rng = np.random.default_rng(0)
        flat = flatten_params(base_params)
        added = 0
        out = dict(flat)
        for path, leaf in flat.items():
            if not path.endswith("/kernel") or getattr(leaf, "ndim", 0) < 2:
                continue
            if not self._matches(path):
                continue
            shape = leaf.shape
            in_dim, out_dim = shape[-2], shape[-1]
            lead = shape[:-2]  # scanned layers keep the [L] axis on the adapters too
            a = rng.standard_normal(lead + (in_dim, cfg.r)).astype(np.float32) / math.sqrt(in_dim)
            b = np.zeros(lead + (cfg.r, out_dim), dtype=np.float32)
            prefix = path.rsplit("/", 1)[0]
            out[prefix + "/lora_A"] = jnp.asarray(a)
            out[prefix + "/lora_B"] = jnp.asarray(b)
            added += 1
        if added == 0:
            raise ValueError(f"no modules matched LoRA target patterns {cfg.target_modules}")
        logger.info(f"LoRA: adapters added to {added} kernels (r={cfg.r}, scaling={cfg.scaling:.3f})")
        return unflatten_params(out)

    # ------------------------------------------------------------------ training glue
    def trainable_mask(self) -> dict:
        """pytree of bool: True = trainable (lora_A/lora_B only)."""
        flat = flatten_params(self.params)
        mask = {p: ("/lora_A" in p or "/lora_B" in p) for p in flat}
        return unflatten_params(mask)

    def print_trainable_parameters(self):
        flat = flatten_params(self.params)
        total = sum(int(np.prod(v.shape)) for v in flat.values())
        trainable = sum(int(np.prod(v.shape)) for p, v in flat.items() if "/lora_" in p)
        logger.info(f"trainable params: {trainable:,} / {total:,} ({100 * trainable / total:.3f}%)")

    def mark_only_lora_as_trainable(self):
        return self.trainable_mask()

    # ------------------------------------------------------------------ facade
    def __call__(self, *args, **kwargs):
        params = kwargs.pop("params", None)
        orig = self.model.params
        self.model.params = params if params is not None else self.params
        self.model.module, base_module = self.module, self.model.module
        try:
            return self.model(*args, **kwargs)
        finally:
            self.model.params = orig
            self.model.module = base_module

    def apply(self, params, *args, **kwargs):
        return self.module.apply({"params": params}, *args, **kwargs)

    def generate(self, *args, **kwargs):
        kwargs.setdefault("params", self.params)
        orig_module = self.model.module
        self.model.module = self.module
        try:
            return self.model.generate(*args, **kwargs)
        finally:
            self.model.module = orig_module

    def num_parameters(self, params=None):
        return self.model.num_parameters(params if params is not None else self.params)

    def get_model_flops(self, *a, **kw):
        return self.model.get_model_flops(*a, **kw)

    def get_partition_rules_instance(self):
        """Adapter specs DERIVED from each kernel rule: lora_A inherits the
        kernel's input-dim logical axis, lora_B its output-dim axis — so e.g.
        down_proj (P('mlp','embed')) gets A: P('mlp', None), B: P(None, 'embed')."""
        from ...parallel.partition import P

        base = list(type(self.model).get_partition_rules(self.config))
        derived = []
        for pattern, spec in base:
            if not pattern.endswith("/kernel$") or len(spec) < 2:
                continue
            prefix = pattern[: -len("/kernel$")]
            derived.append((prefix + "/lora_A$", P(spec[0], None)))
            derived.append((prefix + "/lora_B$", P(None, spec[-1])))
        return base + derived

    # ------------------------------------------------------------------ save/load
    def merge_and_unload(self):
        """Return the base model with adapters folded in (reference `merge` :853)."""
        merged = jax.jit(lambda p: _merge_lora(p, self.lora_config.scaling))(self.params)
        flat = {p: v for p, v in flatten_params(merged).items() if "/lora_" not in p}
        self.model.params = unflatten_params(flat)
        return self.model

    def save_pretrained(self, save_directory: str, merge_tensor_parallel: bool = False, **kw):
        """Save ONLY the adapters + config (reference TP-aware save :320; gathering
        shards is jax.device_get here)."""
        os.makedirs(save_directory, exist_ok=True)
        self.lora_config.save_pretrained(save_directory)
        flat = flatten_params(self.params)
        tensors = {
            p: np.asarray(jax.device_get(v)) for p, v in flat.items() if "/lora_" in p
        }
        save_file(tensors, os.path.join(save_directory, LORA_WEIGHTS_NAME), metadata={"format": "np"})
        logger.info(f"LoRA adapters saved to {save_directory}")

    def export_adapter(self, path: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Flatten the trained adapters into the serving exchange format:
        flat ``{proj}.lora_A`` [L, d_in, r] / ``{proj}.lora_B`` [L, r, d_out]
        arrays keyed by projection name (``q_proj`` ... ``down_proj``) — the
        scanned layout is exported as-is, per-layer trees are stacked in layer
        order. With ``path``, writes a safetensors file carrying ``scaling``
        in its metadata; either the returned dict or the file is a direct
        ``AdapterRegistry.add`` source, so a trained adapter drops into the
        multi-tenant serving pool without a conversion step."""
        flat = flatten_params(self.params)
        layer_re = re.compile(r"/layers_(\d+)/")
        by_key: Dict[str, Dict[Optional[int], np.ndarray]] = {}
        for p, v in flat.items():
            part = p.rsplit("/", 1)[-1]
            if part not in ("lora_A", "lora_B"):
                continue
            proj = p.rsplit("/", 2)[-2]
            m = layer_re.search(p)
            layer = int(m.group(1)) if m else None
            arr = np.asarray(jax.device_get(v), dtype=np.float32)
            by_key.setdefault(f"{proj}.{part}", {})[layer] = arr
        if not by_key:
            raise ValueError("no LoRA adapters to export")
        L = int(self.config.num_hidden_layers)
        out: Dict[str, np.ndarray] = {}
        for key in sorted(by_key):
            layers = by_key[key]
            if None in layers:  # scanned: already [L, d, r]
                out[key] = layers[None]
            else:
                if sorted(layers) != list(range(L)):
                    raise ValueError(
                        f"adapter {key} covers layers {sorted(layers)}; "
                        f"want all of 0..{L - 1}")
                out[key] = np.stack([layers[i] for i in range(L)])
        if path is not None:
            save_file(out, path, metadata={"format": "np",
                                           "scaling": str(self.lora_config.scaling)})
            logger.info(f"LoRA adapter exported to {path} "
                        f"({len(out)} tensors, scaling {self.lora_config.scaling:.3f})")
        return out

    @classmethod
    def from_pretrained(cls, model, lora_path: str) -> "LoRAModel":
        config = LoRAConfig.from_pretrained(lora_path)
        obj = cls(model, config)
        flat = flatten_params(obj.params)
        with SafeFile(os.path.join(lora_path, LORA_WEIGHTS_NAME)) as sf:
            for key in sf.keys():
                if key not in flat:
                    logger.warning(f"adapter key {key} not in model; skipping")
                    continue
                flat[key] = jnp.asarray(sf.get_tensor(key))
        obj.params = unflatten_params(flat)
        return obj
