"""LoRA configuration (reference: paddlenlp/peft/lora/lora_config.py)."""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional

__all__ = ["LoRAConfig"]

LORA_CONFIG_NAME = "lora_config.json"


@dataclasses.dataclass
class LoRAConfig:
    r: int = 8
    lora_alpha: int = 16
    lora_dropout: float = 0.0
    target_modules: Optional[List[str]] = None  # regexes over param paths; None -> arch default
    rslora: bool = False  # scale alpha/sqrt(r) (reference lora_config rslora)
    lora_plus_scale: float = 1.0  # LoRA+ lr ratio for B matrices
    trainable_bias: bool = False
    merge_weights: bool = False

    @property
    def scaling(self) -> float:
        import math

        return self.lora_alpha / (math.sqrt(self.r) if self.rslora else self.r)

    def save_pretrained(self, save_directory: str):
        os.makedirs(save_directory, exist_ok=True)
        with open(os.path.join(save_directory, LORA_CONFIG_NAME), "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)

    @classmethod
    def from_pretrained(cls, directory: str) -> "LoRAConfig":
        with open(os.path.join(directory, LORA_CONFIG_NAME)) as f:
            data = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


DEFAULT_TARGETS = [r"self_attn/(q_proj|k_proj|v_proj|o_proj)/kernel$", r"mlp/(gate_proj|up_proj|down_proj)/kernel$"]
