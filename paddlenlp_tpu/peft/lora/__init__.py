from .lora_config import LoRAConfig  # noqa: F401
from .lora_model import LoRAModel  # noqa: F401
