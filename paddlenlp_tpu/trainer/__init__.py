from .argparser import PdArgumentParser  # noqa: F401
from .trainer import Trainer, TrainState  # noqa: F401
from .trainer_callback import (  # noqa: F401
    DefaultFlowCallback,
    EarlyStoppingCallback,
    PrinterCallback,
    ProgressCallback,
    TrainerCallback,
    TrainerControl,
    TrainerState,
)
from .trainer_utils import (  # noqa: F401
    EvalPrediction,
    IntervalStrategy,
    SchedulerType,
    get_last_checkpoint,
    get_scheduler,
    set_seed,
    speed_metrics,
)
from .training_args import TrainingArguments  # noqa: F401
from .unified_checkpoint import (  # noqa: F401
    CorruptCheckpointError,
    get_last_committed_checkpoint,
    is_committed,
    join_pending_saves,
    rotate_checkpoints,
    validate_checkpoint,
)
from .timer import RuntimeTimer, Timers  # noqa: F401
from .trainer_seq2seq import Seq2SeqTrainer  # noqa: F401
from .integrations import JsonlLoggerCallback, TensorBoardCallback  # noqa: F401
