"""Seq2seq trainer: teacher-forced encoder-decoder loss + generation-based eval.

Counterpart of ``paddlenlp/trainer/trainer_seq2seq.py`` (predict/evaluate through
``model.generate`` instead of teacher-forced logits). For encoder-decoder models
(t5/bart) ``compute_loss`` builds ``decoder_input_ids`` by shifting labels right
and computes UNSHIFTED cross-entropy (labels already align 1:1 with decoder
positions) — the causal-LM shift in the base Trainer would be off by one.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..ops.cross_entropy import cross_entropy_with_ignore
from .trainer import Trainer
from .trainer_utils import PredictionOutput, speed_metrics

__all__ = ["Seq2SeqTrainer"]


def _left_repack(ids: np.ndarray, mask: np.ndarray):
    """Move each row's valid tokens to the right edge (left padding)."""
    out_ids = np.zeros_like(ids)
    out_mask = np.zeros_like(mask)
    for i in range(len(ids)):
        valid = ids[i][mask[i] == 1]
        if len(valid):
            out_ids[i, -len(valid):] = valid
            out_mask[i, -len(valid):] = 1
    return out_ids, out_mask


class Seq2SeqTrainer(Trainer):
    def __init__(self, *args, gen_kwargs: Optional[dict] = None, predict_with_generate: bool = True, **kwargs):
        super().__init__(*args, **kwargs)
        self.gen_kwargs = gen_kwargs or {"max_new_tokens": 64, "do_sample": False}
        self.predict_with_generate = predict_with_generate

    def compute_loss(self, params, inputs, dropout_rng=None):
        if not getattr(self.model.config, "is_encoder_decoder", False):
            return super().compute_loss(params, inputs, dropout_rng)
        return self.model.compute_seq2seq_loss(params, inputs, dropout_rng=dropout_rng,
                                               criterion=self.criterion)

    def _build_eval_step(self):
        """Teacher-forced eval for encoder-decoder models: decoder_input_ids from
        shifted labels + UNSHIFTED CE (the base Trainer's causal shift would be
        off by one); still returns logits for compute_metrics."""
        if not getattr(self.model.config, "is_encoder_decoder", False):
            return super()._build_eval_step()
        import jax

        def eval_step(params, batch):
            inputs = dict(batch)
            labels = inputs.pop("labels", None)
            if labels is not None and "decoder_input_ids" not in inputs:
                inputs["decoder_input_ids"] = self.model.prepare_decoder_input_ids_from_labels(labels)
            out = self.model.module.apply({"params": params}, **inputs, deterministic=True)
            if labels is None:
                return {"logits": out.logits}
            if self.criterion is not None:
                loss = self.criterion(out.logits, labels)
            else:
                loss, _ = cross_entropy_with_ignore(out.logits, labels)
            return {"loss": loss, "logits": out.logits}

        return jax.jit(eval_step)

    def generate_and_score(self, test_dataset, metric_key_prefix: str = "test") -> PredictionOutput:
        """Batch generate over the dataset; compute_metrics sees token sequences."""
        import time

        start = time.time()
        dataloader = self.get_eval_dataloader(test_dataset)
        params = self.train_state.params if self.train_state is not None else self.model.params
        preds: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        encdec = getattr(self.model.config, "is_encoder_decoder", False)
        for host_batch in dataloader:
            ids = np.asarray(host_batch["input_ids"])
            mask = np.asarray(host_batch.get("attention_mask", np.ones_like(ids)))
            if not encdec:
                # batched DECODER prompts need LEFT padding; eval collators
                # right-pad, so repack (encoder inputs keep right padding)
                ids, mask = _left_repack(ids, mask)
            out, _ = self.model.generate(jnp.asarray(ids), attention_mask=jnp.asarray(mask),
                                         params=params, **self.gen_kwargs)
            preds.extend(np.asarray(out))
            if "labels" in host_batch:
                labels.extend(np.asarray(host_batch["labels"]))
        metrics: Dict[str, float] = {}
        if self.compute_metrics is not None:
            from .trainer_utils import EvalPrediction

            metrics = {
                f"{metric_key_prefix}_{k}": v
                for k, v in self.compute_metrics(
                    EvalPrediction(predictions=preds, label_ids=labels or None)
                ).items()
            }
        metrics.update(speed_metrics(metric_key_prefix, start, num_samples=len(preds)))
        return PredictionOutput(predictions=preds, label_ids=labels or None, metrics=metrics)

    def evaluate(self, eval_dataset=None, ignore_keys=None, metric_key_prefix: str = "eval"):
        if self.predict_with_generate:
            dataset = eval_dataset if eval_dataset is not None else self.eval_dataset
            out = self.generate_and_score(dataset, metric_key_prefix)
            self.state.log_history.append(dict(out.metrics))
            return out.metrics
        return super().evaluate(eval_dataset, ignore_keys, metric_key_prefix)
