"""Seq2seq trainer: generation-based evaluation.

Counterpart of ``paddlenlp/trainer/trainer_seq2seq.py`` (predict/evaluate through
``model.generate`` instead of teacher-forced logits).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from .trainer import Trainer
from .trainer_utils import PredictionOutput, speed_metrics

__all__ = ["Seq2SeqTrainer"]


def _left_repack(ids: np.ndarray, mask: np.ndarray):
    """Move each row's valid tokens to the right edge (left padding)."""
    out_ids = np.zeros_like(ids)
    out_mask = np.zeros_like(mask)
    for i in range(len(ids)):
        valid = ids[i][mask[i] == 1]
        if len(valid):
            out_ids[i, -len(valid):] = valid
            out_mask[i, -len(valid):] = 1
    return out_ids, out_mask


class Seq2SeqTrainer(Trainer):
    def __init__(self, *args, gen_kwargs: Optional[dict] = None, predict_with_generate: bool = True, **kwargs):
        super().__init__(*args, **kwargs)
        self.gen_kwargs = gen_kwargs or {"max_new_tokens": 64, "do_sample": False}
        self.predict_with_generate = predict_with_generate

    def generate_and_score(self, test_dataset, metric_key_prefix: str = "test") -> PredictionOutput:
        """Batch generate over the dataset; compute_metrics sees token sequences."""
        import time

        start = time.time()
        dataloader = self.get_eval_dataloader(test_dataset)
        params = self.train_state.params if self.train_state is not None else self.model.params
        preds: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        for host_batch in dataloader:
            ids = np.asarray(host_batch["input_ids"])
            mask = np.asarray(host_batch.get("attention_mask", np.ones_like(ids)))
            # batched decode needs LEFT padding; eval collators right-pad, so repack
            ids, mask = _left_repack(ids, mask)
            out, _ = self.model.generate(jnp.asarray(ids), attention_mask=jnp.asarray(mask),
                                         params=params, **self.gen_kwargs)
            preds.extend(np.asarray(out))
            if "labels" in host_batch:
                labels.extend(np.asarray(host_batch["labels"]))
        metrics: Dict[str, float] = {}
        if self.compute_metrics is not None:
            from .trainer_utils import EvalPrediction

            metrics = {
                f"{metric_key_prefix}_{k}": v
                for k, v in self.compute_metrics(
                    EvalPrediction(predictions=preds, label_ids=labels or None)
                ).items()
            }
        metrics.update(speed_metrics(metric_key_prefix, start, num_samples=len(preds)))
        return PredictionOutput(predictions=preds, label_ids=labels or None, metrics=metrics)

    def evaluate(self, eval_dataset=None, ignore_keys=None, metric_key_prefix: str = "eval"):
        if self.predict_with_generate:
            dataset = eval_dataset if eval_dataset is not None else self.eval_dataset
            out = self.generate_and_score(dataset, metric_key_prefix)
            self.state.log_history.append(dict(out.metrics))
            return out.metrics
        return super().evaluate(eval_dataset, ignore_keys, metric_key_prefix)
