"""Logging integrations (reference: paddlenlp/trainer/integrations.py —
``VisualDLCallback`` :78, ``TensorBoardCallback`` :162, ``WandbCallback``;
selected via ``report_to``). Zero-dependency core: a JSONL metrics writer that
any dashboard can tail; TensorBoard/W&B writers attach when their packages exist.

``MetricsCallback`` is the training half of the shared observability plane: it
publishes step time / tokens-per-sec / MFU / loss / lr / JIT-compile series
into the same ``MetricsRegistry`` the serving runtime exposes, and (opt-in via
``TrainingArguments.metrics_port``) starts a background HTTP ``/metrics`` +
``/health`` + ``/debug/trace`` exporter so training jobs are scrapeable like
serving replicas.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..serving.metrics import REGISTRY, MetricsRegistry
from ..utils.import_utils import is_package_available
from ..utils.log import logger
from .trainer_callback import TrainerCallback

__all__ = [
    "JsonlLoggerCallback",
    "MetricsCallback",
    "TensorBoardCallback",
    "WandbCallback",
    "get_reporting_callbacks",
    "note_checkpoint_commit",
    "register_training_metrics",
]

# epoch time of the last committed checkpoint in this process — stamped by
# unified_checkpoint._commit_checkpoint (on the writer thread for async
# saves, i.e. at the actual rename, not at save *submission*)
_LAST_COMMIT_T: Optional[float] = None


def note_checkpoint_commit(step: Optional[int] = None, t: Optional[float] = None):
    """Record that a checkpoint commit landed (feeds
    ``ckpt_last_commit_age_seconds``). Stdlib-only so the checkpoint writer
    can call it without the metrics plane being up."""
    global _LAST_COMMIT_T
    _LAST_COMMIT_T = time.time() if t is None else float(t)


def _ckpt_commit_age_seconds() -> float:
    """NaN before the first commit — a scraper alerting on this gauge must
    distinguish 'never saved' from 'saved just now', and 0 would lie."""
    if _LAST_COMMIT_T is None:
        return float("nan")
    return max(0.0, time.time() - _LAST_COMMIT_T)


def register_training_metrics(registry: MetricsRegistry) -> dict:
    """Create (idempotently) the training metric catalog in ``registry``.

    Shared by :class:`MetricsCallback` and ``tools/check_metrics.py`` so the
    lint covers exactly what training jobs expose. Names are stable API."""
    return {
        "step_seconds": registry.histogram(
            "train_step_seconds", "Wall time per optimizer step"),
        "tokens_per_second": registry.gauge(
            "train_tokens_per_second", "Token throughput of the last step"),
        "steps": registry.counter(
            "train_steps_total", "Optimizer steps completed"),
        "tokens": registry.counter(
            "train_tokens_total", "Tokens consumed by training"),
        "loss": registry.gauge(
            "train_loss", "Last logged training loss (interval mean)"),
        "learning_rate": registry.gauge(
            "train_learning_rate", "Current learning rate"),
        "grad_norm": registry.gauge(
            "train_grad_norm", "Last logged global gradient norm"),
        "mfu": registry.gauge(
            "train_mfu", "Estimated model FLOPs utilization of the last step (0-1)"),
        "compiles": registry.counter(
            "jax_jit_compile_total", "XLA backend compilations observed"),
        "compile_seconds": registry.counter(
            "jax_jit_compile_seconds_total", "Seconds spent in XLA backend compilation"),
        "epoch": registry.gauge(
            "train_epoch", "Fractional training epoch"),
        "ckpt_age": _ckpt_age_gauge(registry),
    }


def _ckpt_age_gauge(registry: MetricsRegistry):
    """Pull-mode gauge: seconds since the last committed checkpoint (the
    async-save health signal — a growing age means the writer is wedged or
    every save is dying before its rename)."""
    g = registry.gauge(
        "ckpt_last_commit_age_seconds",
        "Seconds since the last committed checkpoint (NaN before the first commit)")
    g.set_function(_ckpt_commit_age_seconds)
    return g


# jax.monitoring listeners are process-global and unremovable — register ONE
# fan-out listener lazily and let it feed the registries currently subscribed;
# sinks deregister on_train_end so dead registries neither leak nor keep
# receiving increments
_COMPILE_SINKS: list = []
_COMPILE_LISTENER_INSTALLED = False


def _install_compile_listener(metrics: dict) -> bool:
    global _COMPILE_LISTENER_INSTALLED
    if not any(m["compiles"] is metrics["compiles"] for m in _COMPILE_SINKS):
        _COMPILE_SINKS.append(metrics)
    if _COMPILE_LISTENER_INSTALLED:
        return True
    try:
        import jax

        def _on_duration(event: str, duration_secs: float, **kw):
            if "backend_compile" not in event:
                return
            for sink in list(_COMPILE_SINKS):
                sink["compiles"].inc()
                sink["compile_seconds"].inc(duration_secs)

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _COMPILE_LISTENER_INSTALLED = True
        return True
    except Exception as e:  # jax absent or monitoring API changed
        logger.warning_once(f"jit-compile metrics unavailable: {e!r}")
        return False


def _remove_compile_sink(metrics: dict):
    _COMPILE_SINKS[:] = [m for m in _COMPILE_SINKS
                         if m["compiles"] is not metrics["compiles"]]


class MetricsCallback(TrainerCallback):
    """Publish training step metrics into the shared ``MetricsRegistry``.

    Per step: ``train_step_seconds`` (histogram), ``train_tokens_per_second``,
    ``train_steps_total``/``train_tokens_total``, and ``train_mfu`` when the
    model reports FLOPs. Per log event: loss / learning rate / grad norm.
    Always on (registry writes are lock-protected dict updates — noise next to
    a train step); the HTTP exporter only starts when
    ``TrainingArguments.metrics_port`` is set (0 = ephemeral port, for tests;
    the bound port lands in ``self.port``).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry if registry is not None else REGISTRY
        self.metrics = register_training_metrics(self.registry)
        self.port: Optional[int] = None
        self._exporter = None
        self._t0: Optional[float] = None
        self._model = None
        self._flops_cache: dict = {}  # seq_len -> flops per token

    def _flops_per_token(self, seq_len: Optional[int]) -> Optional[float]:
        """Per-token model flops at the step's sequence length (the quadratic
        attention term scales with seq_len; evaluating at length 1 would drop
        it and understate MFU for long sequences)."""
        if self._model is None or not hasattr(self._model, "get_model_flops"):
            return None
        key = seq_len or 1
        if key not in self._flops_cache:
            try:
                self._flops_cache[key] = float(self._model.get_model_flops(1, key)) / key
            except Exception:
                self._flops_cache[key] = None
        return self._flops_cache[key]

    # ------------------------------------------------------------- lifecycle
    def on_train_begin(self, args, state, control, model=None, **kwargs):
        _install_compile_listener(self.metrics)
        self._model = model
        self._flops_cache = {}
        port = getattr(args, "metrics_port", None)
        if port is not None and self._exporter is None:
            from ..observability.exporter import ObservabilityExporter

            try:
                self._exporter = ObservabilityExporter(registry=self.registry)
                self.port = self._exporter.start(
                    host=getattr(args, "metrics_host", "127.0.0.1"), port=port)
            except OSError as e:  # EADDRINUSE etc.: observability never kills training
                logger.warning(f"metrics exporter failed to bind port {port}: {e!r}; "
                               "continuing without the HTTP plane")
                self._exporter = None
                self.port = None

    def on_train_end(self, args, state, control, **kwargs):
        _remove_compile_sink(self.metrics)
        if self._exporter is not None:
            self._exporter.shutdown()
            self._exporter = None
            self.port = None

    # ------------------------------------------------------------- per step
    def on_step_begin(self, args, state, control, **kwargs):
        self._t0 = time.perf_counter()

    def on_step_end(self, args, state, control, step_tokens: Optional[int] = None,
                    seq_len: Optional[int] = None, **kwargs):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        m = self.metrics
        m["step_seconds"].observe(dt)
        m["steps"].inc()
        if state.epoch is not None:
            m["epoch"].set(state.epoch)
        if step_tokens:
            m["tokens"].inc(step_tokens)
            tps = step_tokens / max(dt, 1e-9)
            m["tokens_per_second"].set(tps)
            flops_per_token = self._flops_per_token(seq_len)
            if flops_per_token:
                try:
                    import jax

                    from ..utils.env import device_peak_flops

                    peak = device_peak_flops()
                    if peak > 0:
                        n_dev = max(jax.device_count(), 1)
                        m["mfu"].set(flops_per_token * tps / n_dev / peak)
                except Exception:
                    pass

    # ------------------------------------------------------------- per log
    def on_log(self, args, state, control, logs=None, **kwargs):
        if not logs:
            return
        m = self.metrics
        if "loss" in logs:
            m["loss"].set(float(logs["loss"]))
        if "learning_rate" in logs:
            m["learning_rate"].set(float(logs["learning_rate"]))
        if "grad_norm" in logs:
            m["grad_norm"].set(float(logs["grad_norm"]))


class JsonlLoggerCallback(TrainerCallback):
    """Appends one JSON object per log event to <output_dir>/metrics.jsonl."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._fh = None

    def _ensure(self, args):
        if self._fh is None:
            path = self._path or os.path.join(args.output_dir, "metrics.jsonl")
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a")
        return self._fh

    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is None or not state.is_world_process_zero:
            return
        fh = self._ensure(args)
        fh.write(json.dumps({"ts": time.time(), "step": state.global_step, **logs}, default=str) + "\n")
        fh.flush()

    def on_train_end(self, args, state, control, **kwargs):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class TensorBoardCallback(TrainerCallback):
    """Scalar writer over tensorboardX/torch.utils.tensorboard when available."""

    def __init__(self, log_dir: Optional[str] = None):
        self._log_dir = log_dir
        self._writer = None

    def _ensure(self, args):
        if self._writer is None:
            writer_cls = None
            if is_package_available("tensorboardX"):
                from tensorboardX import SummaryWriter as writer_cls  # noqa: N813
            elif is_package_available("torch.utils.tensorboard"):
                from torch.utils.tensorboard import SummaryWriter as writer_cls  # noqa: N813
            if writer_cls is None:
                logger.warning_once("tensorboard writer unavailable; install tensorboardX")
                return None
            self._writer = writer_cls(self._log_dir or os.path.join(args.output_dir, "runs"))
        return self._writer

    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is None or not state.is_world_process_zero:
            return
        writer = self._ensure(args)
        if writer is None:
            return
        for k, v in logs.items():
            if isinstance(v, (int, float)):
                writer.add_scalar(k, v, state.global_step)
        writer.flush()

    def on_train_end(self, args, state, control, **kwargs):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class WandbCallback(TrainerCallback):
    """Weights & Biases reporter (reference integrations.py WandbCallback).
    Run config from TrainingArguments; project/name via WANDB_PROJECT/WANDB_NAME
    env vars (the wandb convention). No-op with a one-time warning when the
    wandb package is absent."""

    def __init__(self):
        self._run = None
        self._unavailable = False

    def _ensure(self, args):
        if self._run is not None or self._unavailable:
            return self._run
        if not is_package_available("wandb"):
            logger.warning_once("report_to=wandb but the wandb package is not installed; skipping")
            self._unavailable = True
            return None
        import wandb

        self._run = wandb.init(
            project=os.environ.get("WANDB_PROJECT", "paddlenlp_tpu"),
            name=os.environ.get("WANDB_NAME") or None,
            dir=args.output_dir,
            config={k: v for k, v in vars(args).items()
                    if isinstance(v, (int, float, str, bool, type(None)))},
            resume="allow",
        )
        return self._run

    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is None or not state.is_world_process_zero:
            return
        run = self._ensure(args)
        if run is None:
            return
        run.log({k: v for k, v in logs.items() if isinstance(v, (int, float))},
                step=state.global_step)

    def on_train_end(self, args, state, control, **kwargs):
        if self._run is not None:
            self._run.finish()
            self._run = None


def get_reporting_callbacks(report_to) -> list:
    """Map TrainingArguments.report_to names to callback instances."""
    if not report_to:
        return []
    if isinstance(report_to, str):
        report_to = [report_to]
    out = []
    for name in report_to:
        if name in ("jsonl", "json", "all"):
            out.append(JsonlLoggerCallback())
        if name in ("tensorboard", "visualdl", "all"):
            out.append(TensorBoardCallback())
        if name in ("wandb", "all"):
            out.append(WandbCallback())
        if name == "none":
            continue
    return out
