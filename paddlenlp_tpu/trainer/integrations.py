"""Logging integrations (reference: paddlenlp/trainer/integrations.py —
``VisualDLCallback`` :78, ``TensorBoardCallback`` :162, ``WandbCallback``;
selected via ``report_to``). Zero-dependency core: a JSONL metrics writer that
any dashboard can tail; TensorBoard/W&B writers attach when their packages exist.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from ..utils.import_utils import is_package_available
from ..utils.log import logger
from .trainer_callback import TrainerCallback

__all__ = ["JsonlLoggerCallback", "TensorBoardCallback", "WandbCallback", "get_reporting_callbacks"]


class JsonlLoggerCallback(TrainerCallback):
    """Appends one JSON object per log event to <output_dir>/metrics.jsonl."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._fh = None

    def _ensure(self, args):
        if self._fh is None:
            path = self._path or os.path.join(args.output_dir, "metrics.jsonl")
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, "a")
        return self._fh

    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is None or not state.is_world_process_zero:
            return
        fh = self._ensure(args)
        fh.write(json.dumps({"ts": time.time(), "step": state.global_step, **logs}, default=str) + "\n")
        fh.flush()

    def on_train_end(self, args, state, control, **kwargs):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class TensorBoardCallback(TrainerCallback):
    """Scalar writer over tensorboardX/torch.utils.tensorboard when available."""

    def __init__(self, log_dir: Optional[str] = None):
        self._log_dir = log_dir
        self._writer = None

    def _ensure(self, args):
        if self._writer is None:
            writer_cls = None
            if is_package_available("tensorboardX"):
                from tensorboardX import SummaryWriter as writer_cls  # noqa: N813
            elif is_package_available("torch.utils.tensorboard"):
                from torch.utils.tensorboard import SummaryWriter as writer_cls  # noqa: N813
            if writer_cls is None:
                logger.warning_once("tensorboard writer unavailable; install tensorboardX")
                return None
            self._writer = writer_cls(self._log_dir or os.path.join(args.output_dir, "runs"))
        return self._writer

    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is None or not state.is_world_process_zero:
            return
        writer = self._ensure(args)
        if writer is None:
            return
        for k, v in logs.items():
            if isinstance(v, (int, float)):
                writer.add_scalar(k, v, state.global_step)
        writer.flush()

    def on_train_end(self, args, state, control, **kwargs):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class WandbCallback(TrainerCallback):
    """Weights & Biases reporter (reference integrations.py WandbCallback).
    Run config from TrainingArguments; project/name via WANDB_PROJECT/WANDB_NAME
    env vars (the wandb convention). No-op with a one-time warning when the
    wandb package is absent."""

    def __init__(self):
        self._run = None
        self._unavailable = False

    def _ensure(self, args):
        if self._run is not None or self._unavailable:
            return self._run
        if not is_package_available("wandb"):
            logger.warning_once("report_to=wandb but the wandb package is not installed; skipping")
            self._unavailable = True
            return None
        import wandb

        self._run = wandb.init(
            project=os.environ.get("WANDB_PROJECT", "paddlenlp_tpu"),
            name=os.environ.get("WANDB_NAME") or None,
            dir=args.output_dir,
            config={k: v for k, v in vars(args).items()
                    if isinstance(v, (int, float, str, bool, type(None)))},
            resume="allow",
        )
        return self._run

    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is None or not state.is_world_process_zero:
            return
        run = self._ensure(args)
        if run is None:
            return
        run.log({k: v for k, v in logs.items() if isinstance(v, (int, float))},
                step=state.global_step)

    def on_train_end(self, args, state, control, **kwargs):
        if self._run is not None:
            self._run.finish()
            self._run = None


def get_reporting_callbacks(report_to) -> list:
    """Map TrainingArguments.report_to names to callback instances."""
    if not report_to:
        return []
    if isinstance(report_to, str):
        report_to = [report_to]
    out = []
    for name in report_to:
        if name in ("jsonl", "json", "all"):
            out.append(JsonlLoggerCallback())
        if name in ("tensorboard", "visualdl", "all"):
            out.append(TensorBoardCallback())
        if name in ("wandb", "all"):
            out.append(WandbCallback())
        if name == "none":
            continue
    return out
