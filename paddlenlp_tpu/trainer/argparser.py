"""Dataclass argument parser (reference: paddlenlp/trainer/argparser.py —
``PdArgumentParser``: dataclass->argparse with JSON config-file support, the
``llm/config/<model>/*.json`` launch format)."""

from __future__ import annotations

import dataclasses
import json
import sys
from argparse import ArgumentDefaultsHelpFormatter, ArgumentParser
from enum import Enum
from pathlib import Path
from typing import Any, Dict, Iterable, List, NewType, Optional, Tuple, Union, get_args, get_origin, get_type_hints

DataClass = NewType("DataClass", Any)

__all__ = ["PdArgumentParser"]


def _string_to_bool(v):
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise ValueError(f"can't parse {v!r} as bool")


class PdArgumentParser(ArgumentParser):
    def __init__(self, dataclass_types, **kwargs):
        kwargs.setdefault("formatter_class", ArgumentDefaultsHelpFormatter)
        super().__init__(**kwargs)
        if dataclasses.is_dataclass(dataclass_types):
            dataclass_types = [dataclass_types]
        self.dataclass_types = list(dataclass_types)
        for dtype in self.dataclass_types:
            self._add_dataclass_arguments(dtype)

    def _add_dataclass_arguments(self, dtype):
        hints = get_type_hints(dtype)
        for f in dataclasses.fields(dtype):
            if not f.init:
                continue
            self._parse_dataclass_field(f, hints[f.name])

    def _parse_dataclass_field(self, f: dataclasses.Field, field_type):
        field_name = f"--{f.name}"
        kwargs: Dict[str, Any] = dict(f.metadata)
        origin = get_origin(field_type)
        args_t = get_args(field_type)
        if origin is Union:
            non_none = [a for a in args_t if a is not type(None)]
            field_type = non_none[0] if non_none else str
            origin = get_origin(field_type)
            args_t = get_args(field_type)
        if isinstance(field_type, type) and issubclass(field_type, Enum):
            kwargs["type"] = type(list(field_type)[0].value)
            kwargs["choices"] = [e.value for e in field_type]
            kwargs["default"] = f.default.value if isinstance(f.default, Enum) else f.default
        elif field_type is bool:
            kwargs["type"] = _string_to_bool
            kwargs["nargs"] = "?"
            kwargs["const"] = True
            if f.default is not dataclasses.MISSING:
                kwargs["default"] = f.default
        elif origin in (list, List):
            kwargs["type"] = args_t[0] if args_t else str
            kwargs["nargs"] = "+"
            if f.default_factory is not dataclasses.MISSING:
                kwargs["default"] = f.default_factory()
            elif f.default is not dataclasses.MISSING:
                kwargs["default"] = f.default
        else:
            kwargs["type"] = field_type
            if f.default is not dataclasses.MISSING:
                kwargs["default"] = f.default
            elif f.default_factory is not dataclasses.MISSING:
                kwargs["default"] = f.default_factory()
            else:
                kwargs["required"] = True
        self.add_argument(field_name, **kwargs)

    def parse_args_into_dataclasses(
        self, args=None, return_remaining_strings=False, look_for_args_file=True
    ) -> Tuple[DataClass, ...]:
        if args is None:
            args = sys.argv[1:]
        # the launch convention: a single .json positional is the whole config
        if len(args) == 1 and args[0].endswith(".json"):
            return self.parse_json_file(args[0])
        namespace, remaining = self.parse_known_args(args)
        outputs = []
        for dtype in self.dataclass_types:
            keys = {f.name for f in dataclasses.fields(dtype) if f.init}
            inputs = {k: v for k, v in vars(namespace).items() if k in keys}
            outputs.append(dtype(**inputs))
        if return_remaining_strings:
            return (*outputs, remaining)
        if remaining:
            raise ValueError(f"unparsed arguments: {remaining}")
        return tuple(outputs)

    def parse_json_file(self, json_file: str, return_remaining=False) -> Tuple[DataClass, ...]:
        data = json.loads(Path(json_file).read_text())
        return self.parse_dict(data, return_remaining=return_remaining)

    def parse_dict(self, data: Dict[str, Any], return_remaining=False) -> Tuple[DataClass, ...]:
        unused = dict(data)
        outputs = []
        for dtype in self.dataclass_types:
            keys = {f.name for f in dataclasses.fields(dtype) if f.init}
            inputs = {k: v for k, v in data.items() if k in keys}
            for k in inputs:
                unused.pop(k, None)
            outputs.append(dtype(**inputs))
        if return_remaining:
            return (*outputs, unused)
        if unused:
            raise ValueError(f"unused config keys: {sorted(unused)}")
        return tuple(outputs)
