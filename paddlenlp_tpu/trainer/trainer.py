"""``Trainer`` — HF-style training loop, TPU-native execution.

Counterpart of ``paddlenlp/trainer/trainer.py`` (~3.5k LoC: ``train`` :687,
``_inner_training_loop`` :855, ``training_step`` :2211, ``_wrap_model`` :1895,
``evaluate`` :2846, ``_save_checkpoint`` :2363). The structural translation:

==============================  =================================================
reference mechanism              TPU-native mechanism
==============================  =================================================
``_wrap_model`` (fleet wrappers  nothing to wrap: params/opt-state live as sharded
 DataParallel/TP/sharding/PP)    arrays on the mesh; one jitted train_step carries
                                 every strategy, GSPMD inserts the collectives
``fused_allreduce_gradients``    grads inherit batch sharding -> psum inserted by
                                 XLA at the jit boundary
AMP O2 + master weights          params fp32, compute bf16 via model dtype
grad-accum microbatch loop       ``lax.scan`` over a leading accum dim inside jit
``paddle.amp.GradScaler``        not needed (bf16 has fp32 range)
==============================  =================================================

The train_step donates its input state: params and optimizer state are updated
in-place in HBM — no per-step host sync, loss fetched asynchronously.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability.tracer import TRACER
from ..ops.cross_entropy import causal_lm_loss
from ..parallel.mesh import use_mesh
from ..parallel.partition import P, sharding_tree
from ..utils.log import logger
from .trainer_callback import (
    CallbackHandler,
    DefaultFlowCallback,
    ProgressCallback,
    TrainerControl,
    TrainerState,
)
from .timer import Timers
from .trainer_utils import (
    PREFIX_CHECKPOINT_DIR,
    IntervalStrategy,
    TrainOutput,
    get_scheduler,
    has_length,
    set_seed,
    speed_metrics,
)
from .training_args import TrainingArguments

__all__ = ["Trainer", "TrainState"]

DEFAULT_CALLBACKS = [DefaultFlowCallback, ProgressCallback]


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


jax.tree_util.register_dataclass(TrainState, data_fields=["params", "opt_state", "step"], meta_fields=[])


class Trainer:
    def __init__(
        self,
        model=None,
        criterion: Optional[Callable] = None,
        args: Optional[TrainingArguments] = None,
        data_collator: Optional[Callable] = None,
        train_dataset=None,
        eval_dataset=None,
        tokenizer=None,
        compute_metrics: Optional[Callable] = None,
        callbacks: Optional[List] = None,
        optimizers: Tuple = (None, None),
        preprocess_logits_for_metrics: Optional[Callable] = None,
    ):
        if args is None:
            args = TrainingArguments(output_dir="tmp_trainer")
        self.args = args
        self.model = model
        self.criterion = criterion
        self.data_collator = data_collator if data_collator is not None else _default_collator
        self.train_dataset = train_dataset
        self.eval_dataset = eval_dataset
        self.tokenizer = tokenizer
        self.compute_metrics = compute_metrics
        self.preprocess_logits_for_metrics = preprocess_logits_for_metrics
        self.optimizer, self.lr_scheduler = optimizers
        self.state = TrainerState()
        self.control = TrainerControl()
        self.train_state: Optional[TrainState] = None
        self._profiler = None
        self._train_step_fn = None
        self._eval_step_fn = None
        self.mesh = args.mesh()
        # cp>1 + built-in loss: labels are pre-shifted host-side before the zigzag
        # reorder (a post-permutation causal shift would be wrong); both the train
        # and eval steps then compute the loss with shift=False.
        self._labels_preshifted = self.mesh.shape.get("cp", 1) > 1 and criterion is None
        from .integrations import MetricsCallback, get_reporting_callbacks

        # MetricsCallback feeds the shared metrics plane (serving.metrics.REGISTRY)
        # on every run; its HTTP exporter only starts when args.metrics_port is set
        callbacks = DEFAULT_CALLBACKS + [MetricsCallback] \
            + get_reporting_callbacks(args.report_to) + (callbacks or [])
        self.callback_handler = CallbackHandler(callbacks, self.model, self.tokenizer)
        self.timers = Timers()  # reference trainer/plugins/timer.py phase buckets
        set_seed(args.seed)
        self.control = self.callback_handler.on_init_end(self.args, self.state, self.control)

    # ------------------------------------------------------------------ setup
    def create_optimizer_and_scheduler(self, num_training_steps: int):
        import optax

        args = self.args
        if self.lr_scheduler is None:
            self.lr_scheduler = get_scheduler(
                args.lr_scheduler_type,
                args.learning_rate,
                args.get_warmup_steps(num_training_steps),
                num_training_steps,
                min_lr=args.min_learning_rate,
            )
        if self.optimizer is None:
            def _no_decay_mask(params):
                flat = jax.tree_util.tree_flatten_with_path(params)[0]

                def decay(path):
                    name = "/".join(str(getattr(k, "key", k)) for k in path)
                    return not (name.endswith("bias") or "norm" in name.lower() or name.endswith("scale"))

                tree = jax.tree_util.tree_unflatten(
                    jax.tree_util.tree_structure(params), [decay(p) for p, _ in flat]
                )
                return tree

            chain = []
            if args.max_grad_norm and args.max_grad_norm > 0:
                chain.append(optax.clip_by_global_norm(args.max_grad_norm))
            chain.append(
                optax.adamw(
                    learning_rate=self.lr_scheduler,
                    b1=args.adam_beta1,
                    b2=args.adam_beta2,
                    eps=args.adam_epsilon,
                    weight_decay=args.weight_decay,
                    mask=_no_decay_mask if args.weight_decay > 0 else None,
                )
            )
            tx = optax.chain(*chain)
            # PEFT: frozen params get set_to_zero (no optimizer state allocated)
            if hasattr(self.model, "trainable_mask"):
                mask = self.model.trainable_mask()
                labels = jax.tree.map(lambda t: "train" if t else "freeze", mask)
                tx = optax.multi_transform({"train": tx, "freeze": optax.set_to_zero()}, labels)
            self.optimizer = tx
        return self.optimizer

    def _logical_overrides(self) -> dict:
        """Mesh-dependent logical->physical rule overrides, applied BOTH to
        initial param placement and (via ``_with_rules``) to the jitted
        train/eval traces so activation constraints agree with placement."""
        overrides = {}
        if self.mesh.shape.get("pp", 1) > 1:
            overrides["layers"] = "pp"  # stacked [L] decoder params split across stages
            # embedding + lm_head would otherwise be REPLICATED per stage (at
            # 7B/32k-vocab that's ~260M params each): ride the vocab dim on pp
            # too — the one-hot embed contraction and the fused CE are
            # vocab-sharding-agnostic, GSPMD adds the psum over (tp, pp)
            overrides["vocab"] = ("tp", "pp")
            overrides["act_vocab"] = ("tp", "pp")
        if getattr(self.args, "sequence_parallel", False) and self.mesh.shape.get("tp", 1) > 1:
            # Megatron-SP: residual-stream activations also shard over tp
            overrides["act_seq"] = ("sep", "cp", "tp")
        return overrides

    def _with_rules(self, fn):
        """Wrap a jitted step so its (lazy, first-call) trace runs under this
        trainer's logical-rule overrides — shard_constraint/logical_axis_size
        inside the model then resolve against the same mapping the params were
        placed with."""
        overrides = self._logical_overrides()
        if not overrides:
            return fn
        from ..parallel.partition import logical_axis_rules

        def wrapped(*args, **kwargs):
            with logical_axis_rules(overrides):
                return fn(*args, **kwargs)

        return wrapped

    def _shard_params(self, params, logical_overrides=None):
        """Place params on the mesh per the model's partition rules."""
        from ..parallel.partition import logical_axis_rules

        if hasattr(self.model, "get_partition_rules_instance"):
            rules = self.model.get_partition_rules_instance()
        else:
            rules = type(self.model).get_partition_rules(self.model.config)
        with logical_axis_rules(logical_overrides or {}):
            shardings = sharding_tree(params, rules, self.mesh)
        return jax.device_put(params, shardings)

    def _zero1_opt_shardings(self, params):
        """Optimizer-state shardings for sharding stage1/2: moments sharded over the
        fsdp axis (first divisible dim), params replicated (reference
        DygraphShardingOptimizer semantics, trainer.py:2016-2022)."""
        from jax.sharding import NamedSharding

        fsdp = self.mesh.shape.get("fsdp", 1)
        opt_shapes = jax.eval_shape(self.optimizer.init, params)

        def leaf_sharding(leaf):
            for axis, dim in enumerate(getattr(leaf, "shape", ())):
                if dim % fsdp == 0 and dim >= fsdp:
                    spec = [None] * len(leaf.shape)
                    spec[axis] = "fsdp"
                    return NamedSharding(self.mesh, P(*spec))
            return NamedSharding(self.mesh, P())

        return jax.tree.map(leaf_sharding, opt_shapes)

    def _make_train_state(self) -> TrainState:
        """Params + optimizer state onto the mesh.

        - stage3 (or no sharding config): params sharded per the model's partition
          rules (ZeRO-3 + TP); optimizer state inherits param placement via jit.
        - stage1/stage2: params REPLICATED over fsdp (only tp etc. applies),
          optimizer moments explicitly sharded over fsdp (ZeRO-1; XLA chooses
          reduce-scatter for the grad consumer, the moral stage2).
        """
        params = self.model.params
        fsdp = self.mesh.shape.get("fsdp", 1)
        stage = self.args.sharding_stage
        overrides = dict(self._logical_overrides())
        if stage in (1, 2) and fsdp > 1:
            params = self._shard_params(params, logical_overrides={"embed": None, **overrides})
            opt_shardings = self._zero1_opt_shardings(params)
            with use_mesh(self.mesh):
                opt_state = jax.jit(self.optimizer.init, out_shardings=opt_shardings)(params)
        else:
            params = self._shard_params(params, logical_overrides=overrides)
            with use_mesh(self.mesh):
                opt_state = jax.jit(self.optimizer.init)(params)  # shardings follow params
        return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    # ------------------------------------------------------------------ loss
    def compute_loss(self, params, inputs: Dict[str, Any], dropout_rng=None):
        """Override point (reference trainer.py compute_loss). ``labels`` follow the
        HF convention (unshifted; shift happens here for causal LM)."""
        inputs = dict(inputs)
        labels = inputs.pop("labels", None)
        rngs = {"dropout": dropout_rng} if dropout_rng is not None else {}
        outputs = self.model.module.apply({"params": params}, **inputs, deterministic=False, rngs=rngs)
        if labels is None:
            raise ValueError("training requires `labels` in inputs (or override compute_loss)")
        logits = outputs.logits if hasattr(outputs, "logits") else outputs[0]
        shift = not getattr(self, "_labels_preshifted", False)
        if self.criterion is not None:
            loss = self.criterion(logits, labels)
        else:
            loss = causal_lm_loss(logits, labels, shift=shift)
        aux = getattr(outputs, "aux_loss", None)
        if aux is not None:  # MoE router load-balancing (pre-weighted by its coef)
            loss = loss + aux
        return loss

    # ------------------------------------------------------------------ train step
    def _use_pipeline(self) -> bool:
        """Pipelined train step: pp>1, model exposes ``pipelined_loss``, and
        ``compute_loss`` is not overridden (subclass losses fall back to the
        plain GSPMD path, which remains correct under a pp-sharded layer stack)."""
        if self.mesh.shape.get("pp", 1) <= 1:
            return False
        if not hasattr(self.model, "pipelined_loss"):
            logger.warning_once(
                "pp>1 but the model has no pipelined_loss; running the un-pipelined "
                "GSPMD path (layer params gathered stage-by-stage)"
            )
            return False
        cfg = getattr(self.model, "config", None)
        if not getattr(cfg, "use_scan_layers", False):
            logger.warning_once(
                "pp>1 requires use_scan_layers=True (stacked [L] params); running "
                "the un-pipelined GSPMD path"
            )
            return False
        if type(self).compute_loss is not Trainer.compute_loss:
            logger.warning_once(
                "pp>1 with an overridden compute_loss: the microbatch pipeline only "
                "drives the built-in causal-LM loss; running the un-pipelined path"
            )
            return False
        return True

    def _model_has_dropout(self) -> bool:
        cfg = self.model.config
        return any(getattr(cfg, attr, 0.0) for attr in
                   ("attention_dropout", "hidden_dropout", "resid_pdrop", "embd_pdrop",
                    "attn_pdrop", "hidden_dropout_prob", "attention_probs_dropout_prob"))

    def _build_train_step(self):
        optimizer = self.optimizer
        accum = self.args.gradient_accumulation_steps
        if self._use_pipeline():
            pp = self.mesh.shape["pp"]
            shift = not self._labels_preshifted
            has_dropout = self._model_has_dropout()

            def pipeline_train_step(state: TrainState, batch, dropout_rng):
                import optax

                # dropout rng threaded per (step, microbatch, layer) through the
                # pipeline state; None keeps the deterministic path bit-stable
                rng = jax.random.fold_in(dropout_rng, state.step) if has_dropout else None

                def loss_fn(params):
                    return self.model.pipelined_loss(
                        params, batch, n_stages=pp, criterion=self.criterion, shift=shift,
                        dropout_rng=rng,
                    )

                loss, grads = jax.value_and_grad(loss_fn)(state.params)
                grad_norm = optax.global_norm(grads)
                updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
                params = optax.apply_updates(state.params, updates)
                new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
                return new_state, {"loss": loss, "grad_norm": grad_norm}

            return self._with_rules(jax.jit(pipeline_train_step, donate_argnums=(0,)))

        def loss_for_micro(params, micro, rng):
            return self.compute_loss(params, micro, dropout_rng=rng)

        def train_step(state: TrainState, batch, dropout_rng):
            import optax

            rng = jax.random.fold_in(dropout_rng, state.step)
            if accum > 1:
                def micro_step(carry, micro):
                    grads_acc, loss_acc, i = carry
                    loss, grads = jax.value_and_grad(loss_for_micro)(
                        state.params, micro, jax.random.fold_in(rng, i)
                    )
                    grads_acc = jax.tree.map(jnp.add, grads_acc, grads)
                    return (grads_acc, loss_acc + loss, i + 1), None

                zero_grads = jax.tree.map(jnp.zeros_like, state.params)
                (grads, loss, _), _ = jax.lax.scan(
                    micro_step, (zero_grads, jnp.zeros((), jnp.float32), 0), batch
                )
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = loss / accum
            else:
                loss, grads = jax.value_and_grad(loss_for_micro)(state.params, batch, rng)
            grad_norm = optax.global_norm(grads)
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(params=params, opt_state=opt_state, step=state.step + 1)
            metrics = {"loss": loss, "grad_norm": grad_norm}
            return new_state, metrics

        return self._with_rules(jax.jit(train_step, donate_argnums=(0,)))

    def _build_eval_step(self):
        shift = not self._labels_preshifted

        def eval_step(params, batch):
            inputs = dict(batch)
            labels = inputs.pop("labels", None)
            outputs = self.model.module.apply({"params": params}, **inputs, deterministic=True)
            logits = outputs.logits if hasattr(outputs, "logits") else outputs[0]
            if labels is None:
                return {"logits": logits}
            if self.criterion is not None:
                loss = self.criterion(logits, labels)
            else:
                loss = causal_lm_loss(logits, labels, shift=shift)
            return {"loss": loss, "logits": logits}

        return self._with_rules(jax.jit(eval_step))

    # ------------------------------------------------------------------ data
    def _data_shard_geometry(self):
        """(num_groups, first_group, span): which of the D = dp x fsdp data-shard
        row groups THIS process's addressable devices cover. Devices are
        data-shard-major in the mesh axis order, so a process owns a contiguous
        group range; processes sharing one group (tp/pp spanning hosts) feed
        identical rows — the single-controller equivalent of the reference's
        broadcast over mp/pp groups (dist_dataloader.py:135-205)."""
        D = self.args.dataset_world_size
        if jax.process_count() <= 1:
            return 1, 0, 1
        # Derive ownership from the ACTUAL batch sharding: mesh_utils may permute
        # devices for ICI topology, so index arithmetic over process-contiguous
        # devices would mis-assign rows. devices_indices_map on a [D]-aval tells
        # us exactly which row groups this process's devices hold.
        from jax.sharding import NamedSharding

        sharding = NamedSharding(self.mesh, P(("dp", "fsdp")))
        imap = sharding.devices_indices_map((D,))
        p = jax.process_index()
        groups = sorted(
            {(idx[0].start or 0) for dev, idx in imap.items() if dev.process_index == p}
        )
        g0, g1 = groups[0], groups[-1]
        if groups != list(range(g0, g1 + 1)):
            raise RuntimeError(
                f"process {p} owns non-contiguous data-shard groups {groups} under the "
                "mesh's device permutation; contiguous per-process batch rows cannot be "
                "assembled — reorder the mesh axes or use a replicated dataloader"
            )
        return D, g0, g1 - g0 + 1

    def get_train_dataloader(self):
        from ..data.dataloader import DataLoader

        args = self.args
        num_shards, shard_id, span = self._data_shard_geometry()
        return DataLoader(
            self.train_dataset,
            batch_size=args.global_train_batch_size,
            collate_fn=self.data_collator,
            shuffle=True,
            drop_last=args.dataloader_drop_last,
            seed=args.data_seed,
            num_shards=num_shards,
            shard_id=shard_id,
            shard_span=span,
        )

    def get_eval_dataloader(self, eval_dataset=None):
        from ..data.dataloader import DataLoader

        dataset = eval_dataset if eval_dataset is not None else self.eval_dataset
        num_shards, shard_id, span = self._data_shard_geometry()
        return DataLoader(
            dataset,
            batch_size=self.args.per_device_eval_batch_size * self.args.dataset_world_size,
            collate_fn=self.data_collator,
            shuffle=False,
            drop_last=False,  # final partial batch wraps (pad-by-duplicate) on multihost
            num_shards=num_shards,
            shard_id=shard_id,
            shard_span=span,
        )

    def _device_put_batch(self, batch: Dict[str, np.ndarray], accum: int, micro_axis: bool = False):
        """Shard the host batch onto the mesh: [global_B, ...] -> batch axes (dp,fsdp);
        with accumulation, reshape to [accum, global_B/accum, ...] first.

        Context parallel (cp>1): the sequence axis is reordered into the zigzag
        load-balanced layout (reference context_parallel_utils.py:32) with explicit
        position_ids, and labels are pre-shifted on the host (a post-reorder causal
        shift would be wrong).
        """
        from jax.sharding import NamedSharding

        cp = self.mesh.shape.get("cp", 1)
        if cp > 1:
            from ..ops.ring_attention import zigzag_positions

            batch = dict(batch)
            ref_key = next((k for k in ("input_ids", "labels", "inputs_embeds") if k in batch), None)
            if ref_key is None:
                raise ValueError("context parallel needs input_ids/labels in the batch")
            seq_len = np.asarray(batch[ref_key]).shape[1]
            # pre-shift labels on the host ONLY for the built-in loss; a user
            # criterion keeps its own contract (labels already dataset-aligned)
            if "labels" in batch and self.criterion is None:
                labels = np.asarray(batch["labels"]).copy()
                labels[..., :-1] = labels[..., 1:]
                labels[..., -1] = -100
                batch["labels"] = labels
            order = np.asarray(zigzag_positions(seq_len, cp))
            if "position_ids" not in batch:
                shape = np.asarray(batch[ref_key]).shape[:2]
                batch["position_ids"] = np.broadcast_to(order, shape)
            else:
                batch["position_ids"] = np.asarray(batch["position_ids"])[..., order]
            for key in ("input_ids", "labels", "attention_mask", "segment_ids"):
                if key in batch:
                    batch[key] = np.asarray(batch[key])[..., order]
            if "inputs_embeds" in batch:
                batch["inputs_embeds"] = np.asarray(batch["inputs_embeds"])[:, order]

        multihost = jax.process_count() > 1

        def put(x):
            x = np.asarray(x)
            if accum > 1 or micro_axis:
                x = x.reshape((accum, x.shape[0] // accum) + x.shape[1:])
                spec = P(None, ("dp", "fsdp"))
            else:
                spec = P(("dp", "fsdp"))
            if multihost:
                # each process holds only its shard of the global batch; assemble
                # the global array from per-process rows (reference solves this
                # with the broadcast dataloader, dist_dataloader.py:41)
                from ..parallel.launch import local_batch_to_global

                return local_batch_to_global(x, self.mesh, spec)
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        return {k: put(v) for k, v in batch.items()}

    def _maybe_unsplit_seq(self, logits):
        """Undo the cp zigzag permutation on eval logits (device-side) so every
        downstream consumer — preprocess_logits_for_metrics, compute_metrics,
        predict — sees dataset sequence order aligned with the host labels."""
        cp = self.mesh.shape.get("cp", 1)
        if cp <= 1 or getattr(logits, "ndim", 0) < 2:
            return logits
        from ..ops.ring_attention import zigzag_unsplit

        return zigzag_unsplit(logits, cp, axis=1)

    def _pad_batch_to_shards(self, batch: Dict[str, np.ndarray]):
        """Pad a partial (last) eval batch to a multiple of the data shards by
        repeating row 0 with labels=-100: the masked token-mean loss ignores the
        filler, and callers slice the filler rows off logits. Returns (batch, n_pad)."""
        if jax.process_count() > 1:
            # the sharded sampler already yields consistent full-size local
            # slices (final partial batch wrap-padded identically on all
            # processes); per-process padding here would desynchronize shards
            return batch, 0
        n_shards = self.args.dataset_world_size
        any_val = next(iter(batch.values()))
        bsz = np.asarray(any_val).shape[0]
        n_pad = (-bsz) % n_shards
        if n_pad == 0:
            return batch, 0
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            filler = np.repeat(v[:1], n_pad, axis=0)
            if k == "labels":
                filler = np.full_like(filler, -100)
            out[k] = np.concatenate([v, filler], axis=0)
        return out, n_pad

    # ------------------------------------------------------------------ main loop
    def train(self, resume_from_checkpoint: Optional[str] = None, **kwargs):
        args = self.args
        train_dataloader = self.get_train_dataloader()
        if has_length(train_dataloader):
            steps_per_epoch = len(train_dataloader)
            if steps_per_epoch == 0:
                raise ValueError(
                    f"dataset yields 0 batches: {len(self.train_dataset)} samples < global batch "
                    f"{args.global_train_batch_size} with drop_last; reduce batch size/data shards"
                )
            if args.max_steps > 0:
                max_steps = args.max_steps
                num_train_epochs = math.ceil(max_steps / steps_per_epoch)
            else:
                max_steps = int(steps_per_epoch * args.num_train_epochs)
                num_train_epochs = math.ceil(args.num_train_epochs)
        else:
            if args.max_steps <= 0:
                raise ValueError("max_steps must be set for sized-less datasets")
            max_steps = args.max_steps
            steps_per_epoch = max_steps
            num_train_epochs = 1

        self.create_optimizer_and_scheduler(max_steps)
        if self.train_state is None:
            self.train_state = self._make_train_state()
        self._train_step_fn = self._build_train_step()

        # ---- resume ----
        if resume_from_checkpoint is None:
            resume_from_checkpoint = args.resume_from_checkpoint
        if resume_from_checkpoint is True:
            # auto-discovery goes through the commit protocol: the newest
            # *committed* checkpoint wins, torn dirs from a crashed save are
            # skipped (get_last_checkpoint would happily hand one back)
            from .unified_checkpoint import (
                get_last_committed_checkpoint,
                get_last_legacy_checkpoint,
            )

            resume_from_checkpoint = get_last_committed_checkpoint(args.output_dir)
            if resume_from_checkpoint is None:
                # no committed checkpoint: fall back to the newest MANIFEST-LESS
                # dir (written by a pre-protocol trainer, loadable via the
                # legacy path) — losing the run to a protocol upgrade would be
                # worse than trusting it. Dirs whose manifest fails validation
                # are torn saves and are never resumed from.
                resume_from_checkpoint = get_last_legacy_checkpoint(args.output_dir)
                if resume_from_checkpoint:
                    logger.warning(
                        f"resume: no committed checkpoint under {args.output_dir}; "
                        f"falling back to legacy (pre-commit-protocol) {resume_from_checkpoint}")
        if resume_from_checkpoint:
            self._load_checkpoint(resume_from_checkpoint)

        self.state.max_steps = max_steps
        self.state.num_train_epochs = num_train_epochs
        self.state.is_world_process_zero = args.process_index == 0
        self.callback_handler.train_dataloader = train_dataloader
        self.callback_handler.optimizer = self.optimizer
        self.callback_handler.lr_scheduler = self.lr_scheduler

        n_params = self.model.num_parameters()
        logger.info("***** Running training *****")
        logger.info(f"  Num examples = {len(self.train_dataset) if has_length(self.train_dataset) else 'unknown'}")
        logger.info(f"  Num epochs = {num_train_epochs}, total steps = {max_steps}")
        logger.info(f"  Global batch size = {args.global_train_batch_size} "
                    f"(per-shard {args.per_device_train_batch_size} x accum {args.gradient_accumulation_steps} "
                    f"x data shards {args.dataset_world_size})")
        logger.info(f"  Model parameters = {n_params:,}")
        logger.info(f"  Mesh = {dict(self.mesh.shape)}")

        self.control = self.callback_handler.on_train_begin(args, self.state, self.control)
        dropout_rng = jax.random.key(args.seed)
        accum = args.gradient_accumulation_steps
        self._interval_losses = []  # device arrays; only sync'd at logging time
        last_metrics = None
        train_start = time.time()
        tokens_seen = 0
        epoch = self.state.global_step // max(steps_per_epoch, 1)

        with use_mesh(self.mesh):
            while self.state.global_step < max_steps and not self.control.should_training_stop:
                self.control = self.callback_handler.on_epoch_begin(args, self.state, self.control)
                steps_to_skip = 0
                if self.state.global_step > 0 and not args.ignore_data_skip:
                    steps_to_skip = self.state.global_step % steps_per_epoch
                train_dataloader.set_epoch(epoch)
                self.timers("read-data").start()
                for step_in_epoch, host_batch in enumerate(train_dataloader):
                    if steps_to_skip > 0:
                        steps_to_skip -= 1
                        continue
                    self.state.data_step += 1
                    if args.skip_data_intervals and any(
                        lo <= self.state.data_step <= hi for lo, hi in args.skip_data_intervals
                    ):
                        # hop over loss-spiking data regions (reference
                        # skip_data_intervals, training_args.py:882): the interval
                        # is in DATA steps — those batches are consumed untrained
                        self.state.consumed_samples += args.global_train_batch_size
                        continue
                    step_t0 = time.perf_counter()
                    self.control = self.callback_handler.on_step_begin(args, self.state, self.control)
                    batch = self._device_put_batch(host_batch, accum, micro_axis=self._use_pipeline())
                    self.timers("read-data").stop()
                    self.timers("forward-backward-optimizer").start()
                    self.train_state, metrics = self._train_step_fn(self.train_state, batch, dropout_rng)
                    # block only when THIS step will log (should_log is set later, in
                    # on_step_end) so the phase breakdown reflects device time
                    will_log = (
                        args.logging_strategy == IntervalStrategy.STEPS
                        and (self.state.global_step + 1) % args.logging_steps == 0
                    )
                    self.timers("forward-backward-optimizer").stop(
                        block_on=metrics["loss"] if will_log else None
                    )
                    last_metrics = metrics
                    self._interval_losses.append(metrics["loss"])
                    self.state.global_step += 1
                    self.state.epoch = self.state.global_step / steps_per_epoch
                    self.state.consumed_samples += args.global_train_batch_size
                    if args.profiler_options:
                        # jax.profiler trace over the configured step window
                        # (reference utils/profiler.py:88 add_profiler_step)
                        if self._profiler is None:
                            from ..utils.profiler import ProfilerOptions, ProfilerStepper

                            self._profiler = ProfilerStepper(
                                ProfilerOptions.parse(args.profiler_options))
                        self._profiler.step(self.state.global_step)
                    step_tokens, seq_len = 0, None
                    if "input_ids" in host_batch:
                        shape = np.asarray(host_batch["input_ids"]).shape
                        step_tokens = int(np.prod(shape))
                        seq_len = int(shape[-1])
                        tokens_seen += step_tokens
                    self.control = self.callback_handler.on_step_end(
                        args, self.state, self.control, step_tokens=step_tokens,
                        seq_len=seq_len)
                    TRACER.add_span("train_step", TRACER.epoch_time(step_t0),
                                    time.perf_counter() - step_t0, cat="trainer",
                                    trace="train", step=self.state.global_step,
                                    tokens=step_tokens)
                    self._maybe_log_save_evaluate(last_metrics, train_start, tokens_seen)
                    if self.control.should_training_stop or self.state.global_step >= max_steps:
                        break
                    self.timers("read-data").start()
                t_rd = self.timers("read-data")
                if t_rd._started is not None:
                    t_rd.stop()
                epoch += 1
                self.control = self.callback_handler.on_epoch_end(args, self.state, self.control)
                self._maybe_log_save_evaluate(last_metrics, train_start, tokens_seen)
                if not has_length(train_dataloader):
                    break

        final_loss = float(last_metrics["loss"]) if last_metrics is not None else float("nan")
        metrics = speed_metrics(
            "train",
            train_start,
            num_samples=self.state.consumed_samples,
            num_steps=self.state.global_step,
            num_tokens=tokens_seen,
            model_flops=self._total_flops(tokens_seen),
        )
        metrics["train_loss"] = final_loss
        if self._profiler is not None:
            # flush an open trace even when training ended inside the window
            self._profiler.close()
            self._profiler = None
        # trainer exit: a live async-save thread must land (and be reaped)
        # before train() returns — callers may rotate, rsync, or exit the
        # process the moment this function hands back control
        from .unified_checkpoint import join_pending_saves

        join_pending_saves(timeout=None)
        self.control = self.callback_handler.on_train_end(args, self.state, self.control)
        self.model.params = self.train_state.params
        return TrainOutput(self.state.global_step, final_loss, metrics)

    def _total_flops(self, tokens_seen: int) -> Optional[float]:
        try:
            if tokens_seen and hasattr(self.model, "get_model_flops"):
                per_token = self.model.get_model_flops(1, 1)  # 6N approx per token
                return per_token * tokens_seen
        except Exception:
            pass
        return None

    def _maybe_log_save_evaluate(self, metrics, train_start, tokens_seen):
        args = self.args
        if self.control.should_log and metrics is not None:
            # interval-mean loss (reference logs the mean over logging_steps); the
            # device->host sync happens only here, once per logging interval
            interval = [float(x) for x in self._interval_losses] or [float(metrics["loss"])]
            self._interval_losses = []
            logs = {
                "loss": round(float(np.mean(interval)), 6),
                "grad_norm": round(float(metrics["grad_norm"]), 6),
                "learning_rate": float(self.lr_scheduler(max(self.state.global_step - 1, 0)))
                if callable(self.lr_scheduler)
                else args.learning_rate,
                "global_step": self.state.global_step,
            }
            logs.update(
                speed_metrics(
                    "interval",
                    train_start,
                    num_steps=self.state.global_step,
                    num_tokens=tokens_seen,
                    model_flops=self._total_flops(tokens_seen),
                )
            )
            self.state.log_history.append(logs)
            self.timers.log(["read-data", "forward-backward-optimizer"], normalizer=max(len(interval), 1))
            self.control = self.callback_handler.on_log(args, self.state, self.control, logs=logs)
        if self.control.should_evaluate:
            with TRACER.span("evaluate", cat="trainer", trace="train",
                             step=self.state.global_step):
                metrics_out = self.evaluate()
            self.control = self.callback_handler.on_evaluate(args, self.state, self.control, metrics=metrics_out)
        if self.control.should_save:
            with TRACER.span("checkpoint", cat="trainer", trace="train",
                             step=self.state.global_step):
                self._save_checkpoint()
            self.control = self.callback_handler.on_save(args, self.state, self.control)

    # ------------------------------------------------------------------ eval
    def evaluate(self, eval_dataset=None, ignore_keys=None, metric_key_prefix: str = "eval") -> Dict[str, float]:
        dataloader = self.get_eval_dataloader(eval_dataset)
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        params = self.train_state.params if self.train_state is not None else self.model.params
        start = time.time()
        losses, n_batches = [], 0
        all_logits, all_labels = [], []
        run_metrics = self.compute_metrics is not None
        multihost = jax.process_count() > 1
        with use_mesh(self.mesh):
            for host_batch in dataloader:
                host_batch, n_pad = self._pad_batch_to_shards(host_batch)
                batch = self._device_put_batch(host_batch, accum=1)
                out = self._eval_step_fn(params, batch)
                if "loss" in out:
                    losses.append(float(out["loss"]))
                if run_metrics:
                    logits = self._maybe_unsplit_seq(out["logits"])  # BEFORE any positional preprocessing
                    logits = self._reduce_eval_logits(logits, batch, host_batch, len(dataloader))
                    if multihost:
                        # gather the device-sharded global batch to every host
                        # (reference trainer.py:2911 evaluation_loop gathers
                        # across ranks); the gathered labels come from the
                        # device batch — the sampler already masked any
                        # wrap-padded filler rows to -100
                        arr, lab = self._allgather_eval(logits, batch)
                    else:
                        arr = np.asarray(jax.device_get(logits))
                        lab = np.asarray(host_batch["labels"]) if "labels" in host_batch else None
                    all_logits.append(arr[: arr.shape[0] - n_pad] if n_pad else arr)
                    if lab is not None:
                        all_labels.append(lab[: lab.shape[0] - n_pad] if n_pad else lab)
                n_batches += 1
        metrics = {}
        if losses:
            metrics[f"{metric_key_prefix}_loss"] = float(np.mean(losses))
            try:
                metrics[f"{metric_key_prefix}_ppl"] = float(np.exp(np.mean(losses)))
            except OverflowError:
                pass
        if run_metrics and all_logits:
            from .trainer_utils import EvalPrediction

            preds = np.concatenate(all_logits, axis=0)
            labels = np.concatenate(all_labels, axis=0) if all_labels else None
            extra = self.compute_metrics(EvalPrediction(predictions=preds, label_ids=labels))
            metrics.update({f"{metric_key_prefix}_{k}" if not k.startswith(metric_key_prefix) else k: v
                            for k, v in extra.items()})
        metrics.update(speed_metrics(metric_key_prefix, start, num_steps=n_batches))
        # best_metric bookkeeping belongs to callbacks (EarlyStoppingCallback) /
        # checkpoint logic, NOT here — updating before on_evaluate would make every
        # improvement invisible to patience counters.
        self.state.log_history.append(dict(metrics))
        return metrics

    def _reduce_eval_logits(self, logits, batch, host_batch, n_batches: int = 1):
        """preprocess_logits_for_metrics if given; otherwise, when accumulating
        the full eval's logits would exceed ``eval_logits_host_bytes_limit`` of
        host RAM, refuse loudly (the reference's eval_accumulation pressure
        valve). Silent argmax substitution changed the meaning of
        compute_metrics inputs depending only on dataset size (ADVICE r3), so
        the reduction now requires the explicit ``eval_reduce_logits_to_argmax``
        opt-in."""
        if self.preprocess_logits_for_metrics is not None:
            labels = batch.get("labels") if jax.process_count() > 1 else host_batch.get("labels")
            return self.preprocess_logits_for_metrics(logits, labels)
        limit = getattr(self.args, "eval_logits_host_bytes_limit", 2 << 30)
        if getattr(logits, "ndim", 0) == 3 and limit and logits.size * 4 * n_batches > limit:
            need_gb = logits.size * 4 * n_batches / 1e9
            if getattr(self.args, "eval_reduce_logits_to_argmax", False):
                logger.warning_once(
                    f"accumulating eval logits would need ~{need_gb:.1f} GB host RAM "
                    f"(> eval_logits_host_bytes_limit={limit}); reducing to argmax token ids "
                    "on device (eval_reduce_logits_to_argmax=True)"
                )
                return jnp.argmax(logits, axis=-1)
            raise ValueError(
                f"accumulating eval logits would need ~{need_gb:.1f} GB host RAM "
                f"(> eval_logits_host_bytes_limit={limit}). Pass preprocess_logits_for_metrics "
                "to reduce them yourself, raise eval_logits_host_bytes_limit, or set "
                "eval_reduce_logits_to_argmax=True to accept [B, T] argmax ids."
            )
        return logits

    def _allgather_eval(self, logits, batch):
        """Multihost: replicate the global (sharded) eval outputs onto every host."""
        from jax.experimental import multihost_utils

        arr = np.asarray(multihost_utils.process_allgather(logits, tiled=True))
        lab = None
        if "labels" in batch:
            lab = np.asarray(multihost_utils.process_allgather(batch["labels"], tiled=True))
        return arr, lab

    def predict(self, test_dataset, ignore_keys=None, metric_key_prefix: str = "test"):
        from .trainer_utils import PredictionOutput

        multihost = jax.process_count() > 1
        dataloader = self.get_eval_dataloader(test_dataset)
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        params = self.train_state.params if self.train_state is not None else self.model.params
        logits_all, labels_all = [], []
        with use_mesh(self.mesh):
            for host_batch in dataloader:
                host_batch, n_pad = self._pad_batch_to_shards(host_batch)
                batch = self._device_put_batch(host_batch, accum=1)
                out = self._eval_step_fn(params, batch)
                logits = self._reduce_eval_logits(self._maybe_unsplit_seq(out["logits"]), batch,
                                                  host_batch, len(dataloader))
                if multihost:
                    arr, lab = self._allgather_eval(logits, batch)
                else:
                    arr = np.asarray(jax.device_get(logits))
                    lab = np.asarray(host_batch["labels"]) if "labels" in host_batch else None
                logits_all.append(arr[: arr.shape[0] - n_pad] if n_pad else arr)
                if lab is not None:
                    labels_all.append(lab[: lab.shape[0] - n_pad] if n_pad else lab)
        preds = np.concatenate(logits_all, axis=0) if logits_all else None
        labels = np.concatenate(labels_all, axis=0) if labels_all else None
        metrics = {}
        if self.compute_metrics is not None and preds is not None and labels is not None:
            from .trainer_utils import EvalPrediction

            metrics = {f"{metric_key_prefix}_{k}": v for k, v in
                       self.compute_metrics(EvalPrediction(predictions=preds, label_ids=labels)).items()}
        return PredictionOutput(predictions=preds, label_ids=labels, metrics=metrics)

    # ------------------------------------------------------------------ checkpoint
    def _save_checkpoint(self):
        from .unified_checkpoint import (
            join_pending_saves,
            rotate_checkpoints,
            save_unified_checkpoint,
        )

        args = self.args
        # one async writer at a time: joining here reaps finished threads (the
        # module list is otherwise unbounded) and keeps the new save from
        # racing a previous in-flight one
        join_pending_saves(timeout=None)
        ckpt_dir = os.path.join(args.output_dir, f"{PREFIX_CHECKPOINT_DIR}-{self.state.global_step}")
        # rotation runs on the writer thread right after the commit rename
        # lands — an async save stays async (no join-just-to-rotate) and
        # rotation always sees the new checkpoint as committed
        best = self.state.best_model_checkpoint
        save_unified_checkpoint(
            ckpt_dir,
            model=self.model,
            train_state=self.train_state,
            trainer_state=self.state,
            tokenizer=self.tokenizer,
            async_save=args.async_save,
            after_commit=lambda: rotate_checkpoints(
                args.output_dir, args.save_total_limit, best_model_checkpoint=best),
        )

    def save_model(self, output_dir: Optional[str] = None):
        output_dir = output_dir or self.args.output_dir
        params = self.train_state.params if self.train_state is not None else self.model.params
        self.model.save_pretrained(output_dir, params=params)
        if self.tokenizer is not None and hasattr(self.tokenizer, "save_pretrained"):
            self.tokenizer.save_pretrained(output_dir)

    def _load_checkpoint(self, ckpt_dir: str):
        from .unified_checkpoint import load_unified_checkpoint

        logger.info(f"resuming from checkpoint {ckpt_dir}")
        self.train_state, trainer_state = load_unified_checkpoint(
            ckpt_dir, model=self.model, train_state=self.train_state, mesh=self.mesh
        )
        if trainer_state is not None:
            self.state = trainer_state
        self.model.params = self.train_state.params

    def _rotate_checkpoints(self):
        """Manual rotation entry point (saves rotate themselves post-commit)."""
        from .unified_checkpoint import join_pending_saves, rotate_checkpoints

        # an in-flight async save must land before we decide what is stale:
        # with async_save the newest checkpoint may still be a staging dir
        join_pending_saves(timeout=None)
        rotate_checkpoints(
            self.args.output_dir,
            self.args.save_total_limit,
            best_model_checkpoint=self.state.best_model_checkpoint,
        )

    def compress(self, strategy: str = "ptq", output_dir: Optional[str] = None, **kwargs):
        """Post-training compression (reference Trainer.compress,
        trainer_compress.py): PTQ weight-only (optionally GPTQ-calibrated) or
        dynabert-style ffn width pruning; exports to ``output_dir``."""
        from .trainer_compress import compress as _compress

        return _compress(self, strategy=strategy, output_dir=output_dir, **kwargs)

    def log(self, logs: Dict[str, float]):
        self.state.log_history.append(logs)
        self.control = self.callback_handler.on_log(self.args, self.state, self.control, logs=logs)

    def add_callback(self, callback):
        self.callback_handler.add_callback(callback)

    def pop_callback(self, callback):
        return self.callback_handler.pop_callback(callback)

    def remove_callback(self, callback):
        self.callback_handler.remove_callback(callback)


def _default_collator(features: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    keys = features[0].keys()
    return {k: np.stack([np.asarray(f[k]) for f in features]) for k in keys}
