"""Trainer utilities: enums, seeding, LR schedules, speed metrics, checkpoint discovery.

Counterpart of ``paddlenlp/trainer/trainer_utils.py`` (seed control :73/:1095,
``speed_metrics`` incl. tokens/sec/device + hardware TFLOPS :351-380, LR schedulers
:391-613, checkpoint discovery :259, ``IterableDatasetShard`` :943).
"""

from __future__ import annotations

import math
import os
import random
import re
import time
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

__all__ = [
    "IntervalStrategy",
    "SchedulerType",
    "EvalPrediction",
    "PredictionOutput",
    "TrainOutput",
    "set_seed",
    "get_scheduler",
    "speed_metrics",
    "get_last_checkpoint",
    "has_length",
    "seed_worker",
    "PREFIX_CHECKPOINT_DIR",
]

PREFIX_CHECKPOINT_DIR = "checkpoint"
_re_checkpoint = re.compile(r"^" + PREFIX_CHECKPOINT_DIR + r"-(\d+)$")


class ExplicitEnum(str, Enum):
    @classmethod
    def _missing_(cls, value):
        raise ValueError(f"{value} is not a valid {cls.__name__}: pick one of {list(cls._value2member_map_)}")


class IntervalStrategy(ExplicitEnum):
    NO = "no"
    STEPS = "steps"
    EPOCH = "epoch"


class SchedulerType(ExplicitEnum):
    LINEAR = "linear"
    COSINE = "cosine"
    CONSTANT = "constant"
    CONSTANT_WITH_WARMUP = "constant_with_warmup"
    POLYNOMIAL = "polynomial"


class EvalPrediction:
    def __init__(self, predictions, label_ids):
        self.predictions = predictions
        self.label_ids = label_ids


class PredictionOutput:
    def __init__(self, predictions, label_ids, metrics):
        self.predictions = predictions
        self.label_ids = label_ids
        self.metrics = metrics


class TrainOutput:
    def __init__(self, global_step: int, training_loss: float, metrics: Dict[str, float]):
        self.global_step = global_step
        self.training_loss = training_loss
        self.metrics = metrics


def set_seed(seed: int):
    """Python/numpy seeding; JAX keys derive from fold_in trees (no global jax seed).

    The reference builds per-axis seed trees (``_get_distributed_seeds``,
    trainer_utils.py:73) so tp ranks share init seeds while dp ranks differ; under
    GSPMD init runs as ONE logical program, so a single key suffices and per-rank
    divergence (dropout on dp shards) comes from `jax_threefry_partitionable`
    splitting the key across the sharded batch.
    """
    random.seed(seed)
    np.random.seed(seed)


def seed_worker(worker_id: int, rank: int, seed: int):
    worker_seed = (seed + rank * 1009 + worker_id) % 2**32
    np.random.seed(worker_seed)
    random.seed(worker_seed)


def get_scheduler(
    name,
    learning_rate: float,
    num_warmup_steps: int,
    num_training_steps: int,
    min_lr: float = 0.0,
    power: float = 1.0,
):
    """Return an optax schedule fn (reference LR zoo trainer_utils.py:391-613)."""
    import optax

    name = SchedulerType(name) if not isinstance(name, SchedulerType) else name
    warmup = optax.linear_schedule(0.0, learning_rate, max(num_warmup_steps, 1))
    decay_steps = max(num_training_steps - num_warmup_steps, 1)
    if name == SchedulerType.LINEAR:
        decay = optax.linear_schedule(learning_rate, min_lr, decay_steps)
    elif name == SchedulerType.COSINE:
        decay = optax.cosine_decay_schedule(learning_rate, decay_steps, alpha=min_lr / max(learning_rate, 1e-12))
    elif name == SchedulerType.POLYNOMIAL:
        decay = optax.polynomial_schedule(learning_rate, min_lr, power, decay_steps)
    elif name in (SchedulerType.CONSTANT, SchedulerType.CONSTANT_WITH_WARMUP):
        decay = optax.constant_schedule(learning_rate)
    else:
        raise ValueError(f"unknown scheduler {name}")
    if num_warmup_steps > 0:
        return optax.join_schedules([warmup, decay], [num_warmup_steps])
    return decay


def speed_metrics(
    split: str,
    start_time: float,
    num_samples: Optional[int] = None,
    num_steps: Optional[int] = None,
    num_tokens: Optional[int] = None,
    model_flops: Optional[float] = None,
) -> Dict[str, float]:
    """Throughput metrics incl. the reference's ``*_tokens_per_second_per_device``
    and ``*_hardware_tflops_per_device`` (trainer_utils.py:351-380)."""
    import jax

    from ..utils.env import device_peak_flops

    runtime = time.time() - start_time
    result = {f"{split}_runtime": round(runtime, 4)}
    if runtime == 0:
        return result
    n_dev = max(jax.device_count(), 1)
    if num_samples is not None:
        result[f"{split}_samples_per_second"] = round(num_samples / runtime, 3)
    if num_steps is not None:
        result[f"{split}_steps_per_second"] = round(num_steps / runtime, 3)
    if num_tokens is not None:
        result[f"{split}_tokens_per_second"] = round(num_tokens / runtime, 2)
        result[f"{split}_tokens_per_second_per_device"] = round(num_tokens / runtime / n_dev, 2)
    if model_flops is not None:
        tflops = model_flops / runtime / n_dev / 1e12
        result[f"{split}_hardware_tflops_per_device"] = round(tflops, 2)
        peak = device_peak_flops()
        if peak > 0:
            result[f"{split}_model_flops_utilization"] = round(model_flops / runtime / n_dev / peak, 4)
    return result


def get_last_checkpoint(folder: str) -> Optional[str]:
    """Newest ``checkpoint-<step>`` subdir (reference trainer_utils.py:259)."""
    if not os.path.isdir(folder):
        return None
    checkpoints = [d for d in os.listdir(folder) if _re_checkpoint.match(d) and os.path.isdir(os.path.join(folder, d))]
    if not checkpoints:
        return None
    return os.path.join(folder, max(checkpoints, key=lambda d: int(_re_checkpoint.match(d).group(1))))


def has_length(dataset) -> bool:
    try:
        return len(dataset) is not None
    except TypeError:
        return False


def copy_aliased_params(params, policy_params):
    """jnp.copy only the leaves of ``params`` that alias ``policy_params`` buffers.

    Donation safety for frozen reference copies (DPO/PPO): the jitted train step
    donates the policy buffers; any leaf shared with them must be a real copy,
    while distinct buffers are kept as-is (no HBM doubling).
    """
    import jax
    import jax.numpy as jnp

    policy_ids = {id(x) for x in jax.tree.leaves(policy_params)}
    return jax.tree.map(lambda x: jnp.copy(x) if id(x) in policy_ids else x, params)
