"""Post-training compression.

Counterpart of ``paddlenlp/trainer/trainer_compress.py`` (42k chars:
dynabert width/depth pruning + PTQ/QAT + embedding quant behind
``Trainer.compress()``). TPU-native scope:

- ``compress(strategy="ptq")``: weight-only int8/int4 PTQ, optionally
  GPTQ-error-compensated against calibration batches from the eval dataset,
  exported as a quantized checkpoint directory (qweight/scales leaves).
- ``compress(strategy="prune")``: magnitude-based structured WIDTH pruning of
  the ffn intermediate dimension (the dynabert axis) by ``width_mult``,
  rewriting gate/up/down kernels to the kept columns and exporting a smaller
  model + patched config.

Both are offline transforms over the unsharded logical checkpoint — no
training-loop integration needed for the PTQ path (QAT = finetune the
dequantized result with the normal Trainer).
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..transformers.conversion_utils import flatten_params, unflatten_params
from ..utils.log import logger

__all__ = ["compress"]


def compress(trainer, strategy: str = "ptq", output_dir: Optional[str] = None, **kwargs):
    """Entry point mirroring ``Trainer.compress()``; see module docstring."""
    output_dir = output_dir or os.path.join(trainer.args.output_dir, f"compress_{strategy}")
    if strategy == "ptq":
        return _ptq(trainer, output_dir, **kwargs)
    if strategy == "prune":
        return _prune_width(trainer, output_dir, **kwargs)
    if strategy == "prune_depth":
        return _prune_depth(trainer, output_dir, **kwargs)
    if strategy == "a8w8":
        return _a8w8(trainer, output_dir, **kwargs)
    if strategy == "qat":
        return _qat(trainer, output_dir, **kwargs)
    if strategy == "embedding_quant":
        return _embedding_quant(trainer, output_dir, **kwargs)
    raise ValueError(f"unknown compression strategy {strategy!r} "
                     "(ptq | prune | prune_depth | a8w8 | qat | embedding_quant)")


def _ptq(trainer, output_dir: str, bits: int = 8, use_gptq: bool = False,
         n_calib_batches: int = 4, match=None):
    from ..quantization import QuantizationConfig, quantize_params

    model = trainer.model
    params = trainer.train_state.params if trainer.train_state is not None else model.params
    if use_gptq:
        from ..quantization.gptq import apply_gptq

        dataset = trainer.eval_dataset or trainer.train_dataset
        if dataset is None:
            raise ValueError("GPTQ calibration needs an eval or train dataset")
        batches = []
        for i in range(min(n_calib_batches, len(dataset))):
            row = dataset[i]
            batches.append({"input_ids": jnp.asarray(np.asarray(row["input_ids"])[None], jnp.int32)})
        orig = model.params
        model.params = params
        try:
            params = apply_gptq(model, batches, bits=bits, match=match)
        finally:
            model.params = orig
    algo = "weight_only_int8" if bits == 8 else "weight_only_int4"
    qparams = quantize_params(params, QuantizationConfig(weight_quantize_algo=algo))
    model.save_pretrained(output_dir, params=params)  # fp reference
    _save_q(qparams, output_dir)
    logger.info(f"PTQ({'gptq+' if use_gptq else ''}wint{bits}) exported to {output_dir}")
    return output_dir


def _a8w8(trainer, output_dir: str, n_calib_batches: int = 4, match=None,
          static_act_scales: bool = True):
    """Activation+weight int8 export (reference llm/utils/quant.py a8w8 PTQ):
    calibrate per-tensor activation absmax observers, quantize weights int8,
    save both plus the scale table. Serving loads them into QuantizedModel."""
    import json

    from ..quantization import QuantizationConfig, quantize_params
    from ..quantization.a8w8 import collect_act_scales

    model = trainer.model
    params = trainer.train_state.params if trainer.train_state is not None else model.params
    act_scales = None
    if static_act_scales:
        dataset = trainer.eval_dataset or trainer.train_dataset
        if dataset is None:
            raise ValueError("a8w8 calibration needs an eval or train dataset")
        batches = []
        for i in range(min(n_calib_batches, len(dataset))):
            row = dataset[i]
            batches.append({"input_ids": jnp.asarray(np.asarray(row["input_ids"])[None], jnp.int32)})
        orig = model.params
        model.params = params
        try:
            act_scales = collect_act_scales(model, batches, match=match)
        finally:
            model.params = orig
    qparams = quantize_params(params, QuantizationConfig(weight_quantize_algo="a8w8"))
    model.save_pretrained(output_dir, params=params)  # fp reference
    _save_q(qparams, output_dir)
    if act_scales is not None:
        with open(os.path.join(output_dir, "act_scales.json"), "w") as f:
            json.dump(act_scales, f)
    logger.info(f"a8w8 exported to {output_dir} "
                f"({'static' if act_scales else 'dynamic'} activation scales)")
    return output_dir


def _save_q(qparams: dict, output_dir: str):
    from ..utils.safetensors_io import save_file

    flat = flatten_params(qparams)
    tensors = {k: np.asarray(jax.device_get(v)) for k, v in flat.items()}
    save_file(tensors, os.path.join(output_dir, "model_quant.safetensors"), metadata={"format": "np"})


def _qat(trainer, output_dir: str, bits: int = 8, n_qat_steps: int = 32,
         learning_rate: float = 1e-5, match=None):
    """Quantization-aware finetune (reference trainer_compress.py QAT stage):
    targeted kernels pass through fake-quant (quantize -> dequantize) in the
    forward with a straight-through estimator — ``w + sg(qdq(w) - w)`` — so
    gradients flow to the fp weights while the loss sees int8/int4 rounding.
    After ``n_qat_steps`` the adapted weights are PTQ-exported."""
    import re as _re

    import optax

    from ..quantization.quantization_utils import DEFAULT_SKIP

    model = trainer.model
    params = trainer.train_state.params if trainer.train_state is not None else model.params
    dataset = trainer.train_dataset
    if dataset is None:
        raise ValueError("QAT needs a train dataset")
    skip_res = [_re.compile(p) for p in DEFAULT_SKIP]
    target_res = [_re.compile(p) for p in match] if match else None
    qmax = 127 if bits == 8 else 7

    def wanted(path, leaf):
        is_kernel = path.endswith("/kernel") and getattr(leaf, "ndim", 0) >= 2
        if target_res is not None:
            return is_kernel and any(p.search(path) for p in target_res)
        return is_kernel and not any(p.search(path) for p in skip_res)

    def fake_quant_tree(p):
        flat = flatten_params(p)
        out = {}
        for path, leaf in flat.items():
            if wanted(path, leaf):
                absmax = jnp.max(jnp.abs(leaf), axis=-2, keepdims=True)
                scales = jnp.maximum(absmax / qmax, 1e-12)
                qdq = jnp.clip(jnp.round(leaf / scales), -qmax - 1, qmax) * scales
                leaf = leaf + jax.lax.stop_gradient(qdq - leaf)  # STE
            out[path] = leaf
        return unflatten_params(out)

    def loss_fn(p, batch):
        return trainer.compute_loss(fake_quant_tree(p), batch)

    tx = optax.adamw(learning_rate)
    opt_state = tx.init(params)

    @jax.jit
    def step(p, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(p, batch)
        updates, opt_state = tx.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    first = last = None
    for i in range(n_qat_steps):
        row = dataset[i % len(dataset)]
        batch = {k: jnp.asarray(np.asarray(v)[None]) for k, v in row.items()
                 if k in ("input_ids", "labels", "attention_mask")}
        params, opt_state, loss = step(params, opt_state, batch)
        first = float(loss) if first is None else first
        last = float(loss)
    logger.info(f"QAT: {n_qat_steps} fake-quant steps, loss {first:.4f} -> {last:.4f}")
    return _ptq_export(trainer, params, output_dir, bits)


def _ptq_export(trainer, params, output_dir: str, bits: int):
    from ..quantization import QuantizationConfig, quantize_params

    algo = "weight_only_int8" if bits == 8 else "weight_only_int4"
    qparams = quantize_params(params, QuantizationConfig(weight_quantize_algo=algo))
    trainer.model.save_pretrained(output_dir, params=params)
    _save_q(qparams, output_dir)
    return output_dir


def _embedding_quant(trainer, output_dir: str):
    """int8 per-row (per-token) quantization of embedding tables (reference
    trainer_compress.py embedding quantization stage): rows are what a lookup
    reads, so per-row scales keep dequantization a cheap fused multiply."""
    model = trainer.model
    params = trainer.train_state.params if trainer.train_state is not None else model.params
    flat = dict(flatten_params(params))
    n = 0
    for path in list(flat):
        if not path.endswith("/embedding"):
            continue
        w = np.asarray(jax.device_get(flat[path]), np.float32)
        absmax = np.abs(w).max(axis=-1, keepdims=True)  # per row
        scales = np.maximum(absmax / 127.0, 1e-12).astype(np.float32)
        q = np.clip(np.round(w / scales), -128, 127).astype(np.int8)
        prefix = path.rsplit("/", 1)[0]
        del flat[path]
        flat[prefix + "/qembedding"] = jnp.asarray(q)
        flat[prefix + "/embed_scales"] = jnp.asarray(scales[..., 0])
        n += 1
    if n == 0:
        raise ValueError("no embedding tables found")
    model.save_pretrained(output_dir, params=params)  # loadable fp reference
    _save_q(unflatten_params(flat), output_dir)
    logger.info(f"embedding-quantized {n} tables to int8 per-row; exported {output_dir}")
    return output_dir


def dequantize_embedding(qembedding, embed_scales, dtype=jnp.float32):
    """Inverse of ``_embedding_quant`` (load-side helper)."""
    return (qembedding.astype(jnp.float32) * embed_scales[..., None]).astype(dtype)


def _prune_depth(trainer, output_dir: str, depth_mult: float = 0.5):
    """Keep ``int(L * depth_mult)`` layers EVENLY SPACED across depth (the
    dynabert depth schedule: uniform strided selection preserves the network's
    coarse feature hierarchy better than dropping a contiguous block)."""
    model = trainer.model
    params = trainer.train_state.params if trainer.train_state is not None else model.params
    cfg = model.config
    L = cfg.num_hidden_layers
    new_l = max(int(round(L * depth_mult)), 1)
    keep = np.linspace(0, L - 1, new_l).round().astype(int)
    keep = np.unique(keep)
    new_l = len(keep)
    flat = dict(flatten_params(params))
    out = {}
    import re as _re

    # NOT \blayers?_ : underscore-joined module names (bert's encoder_layer_0,
    # ernie's encoder_layers_0) have no word boundary before "layer", so \b
    # never fires and BERT-family depth pruning found no per-layer params
    layer_pat = _re.compile(r"(.*?layers?_)(\d+)(?=[/_]|$)")
    renumber = {int(old): i for i, old in enumerate(keep)}
    scanned = getattr(cfg, "use_scan_layers", False)
    n_sliced = n_dropped = 0
    for path, leaf in flat.items():
        m = layer_pat.match(path)
        if m is not None:  # unrolled per-layer param
            old = int(m.group(2))
            if old not in renumber:
                n_dropped += 1
                continue
            out[f"{m.group(1)}{renumber[old]}{path[m.end():]}"] = leaf
            continue
        # scan-stacked layer params live under the index-less "layers" module
        # (model/layers/...): match by PATH, not by a shape[0]==L coincidence
        if scanned and "/layers/" in path and getattr(leaf, "ndim", 0) >= 1 \
                and leaf.shape[0] == L:
            out[path] = jnp.asarray(np.asarray(jax.device_get(leaf))[keep])
            n_sliced += 1
            continue
        out[path] = leaf
    if n_sliced == 0 and n_dropped == 0:
        raise ValueError(f"no per-layer params found to prune (L={L})")
    import copy

    pruned_cfg = copy.deepcopy(cfg)
    pruned_cfg.num_hidden_layers = new_l
    orig_cfg = model.config
    model.config = pruned_cfg
    try:
        model.save_pretrained(output_dir, params=unflatten_params(out))
    finally:
        model.config = orig_cfg
    logger.info(f"depth-pruned {L} -> {new_l} layers (kept {list(keep)}); exported {output_dir}")
    return output_dir


def _prune_width(trainer, output_dir: str, width_mult: float = 0.75):
    """Keep the top-|width_mult| ffn columns by L2 magnitude of the down
    projection rows (the dynabert importance proxy), per layer."""
    model = trainer.model
    params = trainer.train_state.params if trainer.train_state is not None else model.params
    flat = dict(flatten_params(params))
    cfg = model.config
    new_f = int(cfg.intermediate_size * width_mult)
    pruned = 0
    prefixes = sorted({p.rsplit("/", 1)[0].rsplit("/", 1)[0] for p in flat
                       if p.endswith("down_proj/kernel")})
    for prefix in prefixes:
        down = np.asarray(flat[f"{prefix}/down_proj/kernel"])
        imp = np.linalg.norm(down, axis=-1)  # [..., F]
        if down.ndim == 3:  # scanned [L, F, D]: per-layer top-k
            keep = np.argsort(-imp, axis=-1)[:, :new_f]
            keep = np.sort(keep, axis=-1)
            take_f = lambda a, ax: np.take_along_axis(
                a, keep[..., None] if ax == -2 else keep[:, None, :], axis=ax)
            flat[f"{prefix}/down_proj/kernel"] = jnp.asarray(take_f(down, -2))
            for name in ("gate_proj", "up_proj"):
                k = np.asarray(flat[f"{prefix}/{name}/kernel"])  # [L, D, F]
                flat[f"{prefix}/{name}/kernel"] = jnp.asarray(take_f(k, -1))
        else:
            keep = np.sort(np.argsort(-imp)[:new_f])
            flat[f"{prefix}/down_proj/kernel"] = jnp.asarray(down[keep, :])
            for name in ("gate_proj", "up_proj"):
                k = np.asarray(flat[f"{prefix}/{name}/kernel"])
                flat[f"{prefix}/{name}/kernel"] = jnp.asarray(k[:, keep])
        pruned += 1
    # bert/ernie-style encoders: intermediate_dense [D,F] -> output_dense [F,D]
    # (the architectures dynabert actually targets in the reference)
    enc_prefixes = sorted({p.rsplit("/", 2)[0] for p in flat
                           if p.endswith("output_dense/kernel")
                           and f"{p.rsplit('/', 2)[0]}/intermediate_dense/kernel" in flat})
    for prefix in enc_prefixes:
        out_k = np.asarray(flat[f"{prefix}/output_dense/kernel"])  # [F, D]
        imp = np.linalg.norm(out_k, axis=-1)
        keep = np.sort(np.argsort(-imp)[:new_f])
        flat[f"{prefix}/output_dense/kernel"] = jnp.asarray(out_k[keep, :])
        flat[f"{prefix}/intermediate_dense/kernel"] = jnp.asarray(
            np.asarray(flat[f"{prefix}/intermediate_dense/kernel"])[:, keep])
        bias_key = f"{prefix}/intermediate_dense/bias"
        if bias_key in flat:
            flat[bias_key] = jnp.asarray(np.asarray(flat[bias_key])[keep])
        pruned += 1
    if pruned == 0:
        raise ValueError("no prunable ffn kernels found (expected llama-style "
                         "gate/up/down or bert-style intermediate/output dense)")
    # export with a patched config COPY; the live trainer model keeps its
    # full-width params + config consistent
    import copy

    pruned_cfg = copy.deepcopy(cfg)
    pruned_cfg.intermediate_size = new_f
    orig_cfg = model.config
    model.config = pruned_cfg
    try:
        model.save_pretrained(output_dir, params=unflatten_params(flat))
    finally:
        model.config = orig_cfg
    logger.info(f"width-pruned {pruned} ffn stacks to {new_f} ({width_mult:.0%}); exported {output_dir}")
    return output_dir
