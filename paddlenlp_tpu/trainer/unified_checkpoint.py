"""Unified checkpoint: topology-elastic safetensors save/resume with an
atomic commit protocol.

Counterpart of ``paddlenlp/trainer/plugins/unified_checkpoint.py`` (112k chars).
The reference needs TP-merge actions, send/recv dispatch tables, and resharding
converters because every rank holds opaque shards. TPU-native, the design inverts:

- checkpoints ALWAYS hold the unsharded logical tensors (model weights under HF
  keys via ``model.save_pretrained``; optimizer moments under ``<param-path>.<leaf>``
  keys) — "merge tensor parallel" is just ``jax.device_get`` of a sharded array;
- loading under ANY new topology is ``jax.device_put`` against the new mesh's
  NamedShardings — the dynamic re-dispatch machinery (:1382-1569) disappears;
- async save (reference :159-261, shm + writer process) becomes device_get into
  host RAM + a writer thread.

**Commit protocol.** A crash mid-save must never leave a directory that
resume will mistake for a checkpoint. Every save therefore writes into a
``<ckpt_dir>.tmp`` staging directory, fsyncs the payload, writes a
``commit.json`` manifest (file list + sizes + step) and only then
``os.replace``'s the staging dir into place — rename is the commit point.
The observable states are: no dir, a ``*.tmp`` staging dir (ignored by the
``checkpoint-<step>`` regex), or a fully-committed dir. ``load`` validates
the manifest; :func:`get_last_committed_checkpoint` is the resume
auto-discovery that skips torn dirs; :func:`rotate_checkpoints` never deletes
an uncommitted dir or the newest committed one (the resume fallback).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..transformers.conversion_utils import flatten_params, unflatten_params
from ..utils.faults import FaultPoint
from ..utils.fileio import atomic_write, fsync_dir, fsync_file
from ..utils.log import logger
from ..utils.safetensors_io import SafeFile, save_file, shard_checkpoint
from .trainer_utils import _re_checkpoint

__all__ = [
    "save_unified_checkpoint",
    "load_unified_checkpoint",
    "validate_checkpoint",
    "is_committed",
    "get_last_committed_checkpoint",
    "get_last_legacy_checkpoint",
    "rotate_checkpoints",
    "join_pending_saves",
    "wait_for_pending_saves",
    "CorruptCheckpointError",
    "COMMIT_MANIFEST",
]

OPTIMIZER_NAME = "optimizer.safetensors"
TRAINER_STATE_NAME = "trainer_state.json"
COMMIT_MANIFEST = "commit.json"
STAGING_SUFFIX = ".tmp"

_F_WRITE_SHARD = FaultPoint("ckpt.write_shard")
_F_COMMIT = FaultPoint("ckpt.commit")

_pending_saves: List[threading.Thread] = []
_pending_lock = threading.Lock()


class CorruptCheckpointError(RuntimeError):
    """A checkpoint directory failed commit-manifest validation (torn write)."""


def _flatten_opt_state(opt_state) -> Dict[str, np.ndarray]:
    """Flatten an optax state pytree into string-keyed leaves (stable paths)."""
    flat = {}
    leaves = jax.tree_util.tree_flatten_with_path(opt_state)[0]
    for path, leaf in leaves:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in path)
        flat[key] = leaf
    return flat


# --------------------------------------------------------------------- commit
def _manifest_files(ckpt_dir: str) -> Dict[str, int]:
    """Relative path → size for every payload file under ``ckpt_dir``."""
    files: Dict[str, int] = {}
    for root, _dirs, names in os.walk(ckpt_dir):
        for name in names:
            if name == COMMIT_MANIFEST:
                continue
            p = os.path.join(root, name)
            files[os.path.relpath(p, ckpt_dir)] = os.path.getsize(p)
    return files


def _sha256_file(path: str, chunk_bytes: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(chunk_bytes)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _commit_checkpoint(staging: str, final: str, step: Optional[int]):
    """Manifest + fsync + rename: the all-or-nothing commit point.

    Everything before the ``os.replace`` can crash with zero effect on
    ``final``; everything after it is durable (parent dir fsync'd). The
    manifest carries per-file sha256 alongside sizes: sizes catch truncation,
    hashes catch bit rot / partial rsync that preserves length."""
    files = _manifest_files(staging)
    hashes = {}
    for rel in files:
        fsync_file(os.path.join(staging, rel))
        hashes[rel] = _sha256_file(os.path.join(staging, rel))
    _F_COMMIT.fire(step=step)
    commit_t = time.time()
    with atomic_write(os.path.join(staging, COMMIT_MANIFEST)) as f:
        json.dump({"version": 2, "step": step, "time": commit_t, "files": files,
                   "sha256": hashes}, f, indent=2, sort_keys=True)
    if os.path.isdir(final):
        # re-saving the same step: drop the old dir so rename can land. The
        # vulnerable window (old gone, new not yet renamed) only affects the
        # step being overwritten, never other checkpoints.
        shutil.rmtree(final)
    os.replace(staging, final)
    fsync_dir(os.path.dirname(final) or ".")
    # stamp the training metrics plane (ckpt_last_commit_age_seconds) — lazy
    # import, and never let an observability hiccup fail a landed commit
    try:
        from .integrations import note_checkpoint_commit

        note_checkpoint_commit(step=step, t=commit_t)
    except Exception as e:  # pragma: no cover - defensive
        logger.warning(f"checkpoint commit-time stamp failed: {e!r}")


def validate_checkpoint(ckpt_dir: str, verify_hashes: bool = True) -> Optional[str]:
    """None when ``ckpt_dir`` holds a committed, consistent checkpoint;
    otherwise a human-readable reason it must not be trusted.

    Size validation always runs (cheap; catches truncation). Content-hash
    validation runs when the manifest carries ``sha256`` entries and
    ``verify_hashes`` is true (full re-read; catches bit rot). Manifests
    written before the hash field (version 1) still validate — with a warning
    that integrity is size-only."""
    manifest_path = os.path.join(ckpt_dir, COMMIT_MANIFEST)
    if not os.path.isfile(manifest_path):
        return f"no {COMMIT_MANIFEST} (save never committed)"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        return f"unreadable {COMMIT_MANIFEST}: {e}"
    hashes = manifest.get("sha256") or {}
    if verify_hashes and not hashes:
        # only worth saying when the caller ASKED for hash validation —
        # is_committed() (rotation, per dir per save) explicitly opts out
        logger.warning(
            f"checkpoint {ckpt_dir}: manifest has no content hashes (written by a "
            "pre-hash trainer); validating sizes only — truncation is caught, bit rot is not")
    for rel, size in manifest.get("files", {}).items():
        p = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(p):
            return f"manifest file missing: {rel}"
        actual = os.path.getsize(p)
        if actual != size:
            return f"size mismatch for {rel}: manifest {size}, on disk {actual}"
        if verify_hashes and rel in hashes:
            digest = _sha256_file(p)
            if digest != hashes[rel]:
                return (f"content hash mismatch for {rel}: manifest sha256 "
                        f"{hashes[rel][:12]}…, on disk {digest[:12]}… (bit rot?)")
    return None


def is_committed(ckpt_dir: str) -> bool:
    """Commit-status check (manifest present + sizes match). Skips the full
    content-hash re-read: rotation calls this per dir on every save, and
    bit-rot detection belongs to the load/resume path, not the reaper."""
    return os.path.isdir(ckpt_dir) and validate_checkpoint(ckpt_dir, verify_hashes=False) is None


def get_last_committed_checkpoint(folder: str) -> Optional[str]:
    """Resume auto-discovery: newest ``checkpoint-<step>`` dir that passes
    manifest validation. Torn/uncommitted dirs are skipped with a warning —
    the fallback order is strictly newest-committed-first."""
    if not os.path.isdir(folder):
        return None
    steps = sorted(
        (int(m.group(1)), d)
        for d in os.listdir(folder)
        if (m := _re_checkpoint.match(d)) and os.path.isdir(os.path.join(folder, d))
    )
    for _step, d in reversed(steps):
        path = os.path.join(folder, d)
        reason = validate_checkpoint(path)
        if reason is None:
            return path
        logger.warning(f"resume: skipping torn checkpoint {path}: {reason}")
    return None


def get_last_legacy_checkpoint(folder: str) -> Optional[str]:
    """Newest checkpoint dir with NO commit manifest at all — written by a
    pre-protocol trainer, loadable via the legacy path. A dir that HAS a
    manifest which fails validation is a torn post-protocol save and is never
    returned (loading it would raise CorruptCheckpointError)."""
    if not os.path.isdir(folder):
        return None
    steps = sorted(
        (int(m.group(1)), d)
        for d in os.listdir(folder)
        if (m := _re_checkpoint.match(d)) and os.path.isdir(os.path.join(folder, d))
    )
    for _step, d in reversed(steps):
        path = os.path.join(folder, d)
        if not os.path.isfile(os.path.join(path, COMMIT_MANIFEST)):
            return path
    return None


def rotate_checkpoints(folder: str, limit: Optional[int],
                       best_model_checkpoint: Optional[str] = None) -> List[str]:
    """Delete stale ``checkpoint-*`` dirs beyond ``limit``, never touching:

    - the best-model checkpoint (paths realpath-normalized — a relative
      ``best_model_checkpoint`` must still protect the absolute dir);
    - uncommitted dirs (an in-progress async save or a torn dir a human may
      want for diagnosis — either way not ours to reap);
    - the newest committed checkpoint (the resume fallback target).

    Returns the deleted paths. Pending async saves must be joined by the
    caller first (``Trainer._rotate_checkpoints`` does) so an in-flight save's
    staging dir has landed before we decide what is stale."""
    if limit is None or limit <= 0 or not os.path.isdir(folder):
        return []
    ckpts = sorted(
        (d for d in os.listdir(folder)
         if _re_checkpoint.match(d) and os.path.isdir(os.path.join(folder, d))),
        key=lambda d: int(d.split("-")[-1]),
    )
    if len(ckpts) <= limit:
        return []
    best = os.path.realpath(best_model_checkpoint) if best_model_checkpoint else None
    fallback = get_last_committed_checkpoint(folder)
    fallback = os.path.realpath(fallback) if fallback else None
    deleted: List[str] = []
    for stale in ckpts[:-limit]:
        path = os.path.join(folder, stale)
        real = os.path.realpath(path)
        if best is not None and real == best:
            continue
        if fallback is not None and real == fallback:
            logger.info(f"rotation: keeping {path} (newest committed checkpoint; resume fallback)")
            continue
        if not is_committed(path):
            logger.warning(f"rotation: keeping uncommitted dir {path} (in-progress or torn save)")
            continue
        logger.info(f"rotating old checkpoint {path}")
        shutil.rmtree(path, ignore_errors=True)
        deleted.append(path)
    return deleted


# --------------------------------------------------------------------- save
def save_unified_checkpoint(
    ckpt_dir: str,
    model,
    train_state,
    trainer_state=None,
    tokenizer=None,
    async_save: bool = False,
    after_commit=None,
):
    """``after_commit`` (no-arg callable) runs on the writer thread right
    after the rename lands — rotation hooks in here so an async save stays
    async instead of being joined just to rotate."""
    ckpt_dir = os.path.abspath(ckpt_dir)
    os.makedirs(os.path.dirname(ckpt_dir) or ".", exist_ok=True)
    params = train_state.params if train_state is not None else model.params

    opt_tensors: Dict[str, np.ndarray] = {}
    if train_state is not None:
        for key, leaf in _flatten_opt_state(train_state.opt_state).items():
            opt_tensors[key] = leaf
        opt_tensors["__step__"] = train_state.step

    if trainer_state is not None:
        step = int(trainer_state.global_step)
    elif train_state is not None:
        step = int(np.asarray(jax.device_get(train_state.step)))
    else:
        step = None

    staging = ckpt_dir + STAGING_SUFFIX

    def _write(host_params, host_opt):
        # stale staging from an earlier crashed save: ours to reclaim (the
        # committed dir, if any, is untouched by anything below until commit)
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)
        model.save_pretrained(staging, params=host_params)
        if host_opt:
            shards, index = shard_checkpoint(host_opt, weights_name=OPTIMIZER_NAME)
            for fname, shard in shards:
                path = os.path.join(staging, fname)
                save_file(shard, path, metadata={"format": "np"})
                _F_WRITE_SHARD.fire(file=path, shard=fname)
            if index is not None:
                with atomic_write(os.path.join(staging, OPTIMIZER_NAME + ".index.json")) as f:
                    json.dump(index, f)
        if trainer_state is not None:
            trainer_state.save_to_json(os.path.join(staging, TRAINER_STATE_NAME))
        if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
            tokenizer.save_pretrained(staging)
        _commit_checkpoint(staging, ckpt_dir, step)
        logger.info(f"unified checkpoint saved to {ckpt_dir} (step {step}, committed)")
        if after_commit is not None:
            after_commit()

    # gather to host (the TP-merge of the reference, for free)
    host_params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
    host_opt = {k: np.asarray(jax.device_get(v)) for k, v in opt_tensors.items()}
    if async_save:
        t = threading.Thread(target=_writer_main, args=(_write, host_params, host_opt, ckpt_dir),
                             name=f"ckpt-save-{step}", daemon=False)
        t.start()
        with _pending_lock:
            _pending_saves.append(t)
    else:
        _write(host_params, host_opt)


def _writer_main(write_fn, host_params, host_opt, ckpt_dir):
    """Async-writer thread body: record the exception for join_pending_saves
    to surface — a save that died must not fail silently."""
    try:
        write_fn(host_params, host_opt)
    except BaseException as e:  # noqa: BLE001 - re-surfaced at join
        threading.current_thread()._ckpt_exc = e
        logger.error(f"async checkpoint save to {ckpt_dir} failed: {e!r} "
                     f"(staging dir left uncommitted; previous checkpoint still valid)")


def join_pending_saves(timeout: Optional[float] = None) -> int:
    """Join async writer threads and prune finished ones from the module list
    (they were previously never reaped — an unbounded leak over a long run).

    Returns the number of saves still running after ``timeout`` (0 = drained).
    Exceptions raised inside writer threads are logged here; the checkpoint
    they belonged to is simply absent/uncommitted on disk."""
    deadline = None if timeout is None else time.monotonic() + timeout
    with _pending_lock:
        threads = list(_pending_saves)
    for t in threads:
        remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
        t.join(timeout=remaining)
        exc = getattr(t, "_ckpt_exc", None)
        if exc is not None and not t.is_alive():
            logger.error(f"pending checkpoint save {t.name} failed: {exc!r}")
            t._ckpt_exc = None
    with _pending_lock:
        _pending_saves[:] = [t for t in _pending_saves if t.is_alive()]
        return len(_pending_saves)


def wait_for_pending_saves():
    """Back-compat alias: block until every async save finishes."""
    join_pending_saves(timeout=None)


# --------------------------------------------------------------------- load
def _resolve_optimizer_files(ckpt_dir: str):
    """Single optimizer.safetensors OR sharded optimizer-XXXXX-of-NNNNN via index."""
    index_path = os.path.join(ckpt_dir, OPTIMIZER_NAME + ".index.json")
    if os.path.isfile(index_path):
        with open(index_path) as f:
            index = json.load(f)
        return [os.path.join(ckpt_dir, f) for f in sorted(set(index["weight_map"].values()))]
    single = os.path.join(ckpt_dir, OPTIMIZER_NAME)
    return [single] if os.path.isfile(single) else []


def load_unified_checkpoint(
    ckpt_dir: str,
    model,
    train_state=None,
    mesh=None,
) -> Tuple[Any, Optional[Any]]:
    """Restore (TrainState, TrainerState) from ``ckpt_dir`` under the CURRENT mesh —
    works across topology changes (the reference's `check_dynamic_load` path).

    The commit manifest is validated first: a dir with a manifest that does not
    match the bytes on disk raises :class:`CorruptCheckpointError` (use
    :func:`get_last_committed_checkpoint` to auto-skip such dirs). A dir with
    no manifest at all is accepted as a legacy pre-protocol checkpoint, with a
    warning — it predates crash-safety, so its integrity is on the operator."""
    from ..trainer.trainer_callback import TrainerState
    from .trainer import TrainState

    manifest_path = os.path.join(ckpt_dir, COMMIT_MANIFEST)
    if os.path.isfile(manifest_path):
        reason = validate_checkpoint(ckpt_dir)
        if reason is not None:
            raise CorruptCheckpointError(f"checkpoint {ckpt_dir} failed validation: {reason}")
    else:
        logger.warning(f"checkpoint {ckpt_dir} has no {COMMIT_MANIFEST}; loading as legacy "
                       "(pre-commit-protocol) checkpoint without integrity validation")

    # model params through the standard sharding-aware loader
    reloaded = type(model).from_pretrained(
        ckpt_dir, config=model.config, dtype=model.dtype, param_dtype=model.param_dtype, mesh=mesh
    )
    params = reloaded.params

    opt_state = None
    opt_files = _resolve_optimizer_files(ckpt_dir)
    if train_state is not None and opt_files:
        target = train_state.opt_state
        flat_target = _flatten_opt_state(target)
        open_files = [SafeFile(f) for f in opt_files]
        key_to_file = {}
        for sf in open_files:
            for k in sf.keys():
                key_to_file[k] = sf
        try:
            loaded: Dict[str, np.ndarray] = {}
            for key, leaf in flat_target.items():
                if key in key_to_file:
                    arr = key_to_file[key].get_tensor(key)
                    sharding = getattr(leaf, "sharding", None)
                    loaded[key] = jax.device_put(arr, sharding) if sharding is not None else arr
                else:
                    logger.warning(f"optimizer leaf {key} missing in checkpoint; keeping fresh init")
                    loaded[key] = leaf
            step = key_to_file["__step__"].get_tensor("__step__") if "__step__" in key_to_file else np.zeros((), np.int32)
        finally:
            for sf in open_files:
                sf.close()
        # rebuild the optax pytree with loaded leaves in structure order
        leaves_with_path = jax.tree_util.tree_flatten_with_path(target)
        treedef = leaves_with_path[1]
        ordered = []
        for path, leaf in leaves_with_path[0]:
            key = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in path)
            ordered.append(loaded[key])
        opt_state = jax.tree_util.tree_unflatten(treedef, ordered)
        new_train_state = TrainState(params=params, opt_state=opt_state, step=jax.numpy.asarray(step))
    else:
        new_train_state = TrainState(
            params=params,
            opt_state=train_state.opt_state if train_state is not None else None,
            step=train_state.step if train_state is not None else jax.numpy.zeros((), jax.numpy.int32),
        )

    trainer_state = None
    ts_path = os.path.join(ckpt_dir, TRAINER_STATE_NAME)
    if os.path.isfile(ts_path):
        trainer_state = TrainerState.load_from_json(ts_path)
    return new_train_state, trainer_state
