"""Unified checkpoint: topology-elastic safetensors save/resume.

Counterpart of ``paddlenlp/trainer/plugins/unified_checkpoint.py`` (112k chars).
The reference needs TP-merge actions, send/recv dispatch tables, and resharding
converters because every rank holds opaque shards. TPU-native, the design inverts:

- checkpoints ALWAYS hold the unsharded logical tensors (model weights under HF
  keys via ``model.save_pretrained``; optimizer moments under ``<param-path>.<leaf>``
  keys) — "merge tensor parallel" is just ``jax.device_get`` of a sharded array;
- loading under ANY new topology is ``jax.device_put`` against the new mesh's
  NamedShardings — the dynamic re-dispatch machinery (:1382-1569) disappears;
- async save (reference :159-261, shm + writer process) becomes device_get into
  host RAM + a writer thread.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..transformers.conversion_utils import flatten_params, unflatten_params
from ..utils.log import logger
from ..utils.safetensors_io import SafeFile, save_file, shard_checkpoint

__all__ = ["save_unified_checkpoint", "load_unified_checkpoint"]

OPTIMIZER_NAME = "optimizer.safetensors"
TRAINER_STATE_NAME = "trainer_state.json"
_pending_saves: list = []


def _flatten_opt_state(opt_state) -> Dict[str, np.ndarray]:
    """Flatten an optax state pytree into string-keyed leaves (stable paths)."""
    flat = {}
    leaves = jax.tree_util.tree_flatten_with_path(opt_state)[0]
    for path, leaf in leaves:
        key = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in path)
        flat[key] = leaf
    return flat


def save_unified_checkpoint(
    ckpt_dir: str,
    model,
    train_state,
    trainer_state=None,
    tokenizer=None,
    async_save: bool = False,
):
    os.makedirs(ckpt_dir, exist_ok=True)
    params = train_state.params if train_state is not None else model.params

    opt_tensors: Dict[str, np.ndarray] = {}
    if train_state is not None:
        for key, leaf in _flatten_opt_state(train_state.opt_state).items():
            opt_tensors[key] = leaf
        opt_tensors["__step__"] = train_state.step

    def _write(host_params, host_opt):
        model.save_pretrained(ckpt_dir, params=host_params)
        if host_opt:
            shards, index = shard_checkpoint(host_opt, weights_name=OPTIMIZER_NAME)
            for fname, shard in shards:
                save_file(shard, os.path.join(ckpt_dir, fname), metadata={"format": "np"})
            if index is not None:
                with open(os.path.join(ckpt_dir, OPTIMIZER_NAME + ".index.json"), "w") as f:
                    json.dump(index, f)
        if trainer_state is not None:
            trainer_state.save_to_json(os.path.join(ckpt_dir, TRAINER_STATE_NAME))
        if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
            tokenizer.save_pretrained(ckpt_dir)
        logger.info(f"unified checkpoint saved to {ckpt_dir}")

    # gather to host (the TP-merge of the reference, for free)
    host_params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
    host_opt = {k: np.asarray(jax.device_get(v)) for k, v in opt_tensors.items()}
    if async_save:
        t = threading.Thread(target=_write, args=(host_params, host_opt), daemon=False)
        t.start()
        _pending_saves.append(t)
    else:
        _write(host_params, host_opt)


def wait_for_pending_saves():
    while _pending_saves:
        _pending_saves.pop().join()


def _resolve_optimizer_files(ckpt_dir: str):
    """Single optimizer.safetensors OR sharded optimizer-XXXXX-of-NNNNN via index."""
    index_path = os.path.join(ckpt_dir, OPTIMIZER_NAME + ".index.json")
    if os.path.isfile(index_path):
        with open(index_path) as f:
            index = json.load(f)
        return [os.path.join(ckpt_dir, f) for f in sorted(set(index["weight_map"].values()))]
    single = os.path.join(ckpt_dir, OPTIMIZER_NAME)
    return [single] if os.path.isfile(single) else []


def load_unified_checkpoint(
    ckpt_dir: str,
    model,
    train_state=None,
    mesh=None,
) -> Tuple[Any, Optional[Any]]:
    """Restore (TrainState, TrainerState) from ``ckpt_dir`` under the CURRENT mesh —
    works across topology changes (the reference's `check_dynamic_load` path)."""
    from ..trainer.trainer_callback import TrainerState
    from .trainer import TrainState

    # model params through the standard sharding-aware loader
    reloaded = type(model).from_pretrained(
        ckpt_dir, config=model.config, dtype=model.dtype, param_dtype=model.param_dtype, mesh=mesh
    )
    params = reloaded.params

    opt_state = None
    opt_files = _resolve_optimizer_files(ckpt_dir)
    if train_state is not None and opt_files:
        target = train_state.opt_state
        flat_target = _flatten_opt_state(target)
        open_files = [SafeFile(f) for f in opt_files]
        key_to_file = {}
        for sf in open_files:
            for k in sf.keys():
                key_to_file[k] = sf
        try:
            loaded: Dict[str, np.ndarray] = {}
            for key, leaf in flat_target.items():
                if key in key_to_file:
                    arr = key_to_file[key].get_tensor(key)
                    sharding = getattr(leaf, "sharding", None)
                    loaded[key] = jax.device_put(arr, sharding) if sharding is not None else arr
                else:
                    logger.warning(f"optimizer leaf {key} missing in checkpoint; keeping fresh init")
                    loaded[key] = leaf
            step = key_to_file["__step__"].get_tensor("__step__") if "__step__" in key_to_file else np.zeros((), np.int32)
        finally:
            for sf in open_files:
                sf.close()
        # rebuild the optax pytree with loaded leaves in structure order
        leaves_with_path = jax.tree_util.tree_flatten_with_path(target)
        treedef = leaves_with_path[1]
        ordered = []
        for path, leaf in leaves_with_path[0]:
            key = "/".join(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k)))) for k in path)
            ordered.append(loaded[key])
        opt_state = jax.tree_util.tree_unflatten(treedef, ordered)
        new_train_state = TrainState(params=params, opt_state=opt_state, step=jax.numpy.asarray(step))
    else:
        new_train_state = TrainState(
            params=params,
            opt_state=train_state.opt_state if train_state is not None else None,
            step=train_state.step if train_state is not None else jax.numpy.zeros((), jax.numpy.int32),
        )

    trainer_state = None
    ts_path = os.path.join(ckpt_dir, TRAINER_STATE_NAME)
    if os.path.isfile(ts_path):
        trainer_state = TrainerState.load_from_json(ts_path)
    return new_train_state, trainer_state
