"""Step-phase timers (reference: paddlenlp/trainer/plugins/timer.py —
Megatron-style ``Timers`` :96, ``RuntimeTimer`` :70; wired as
``self.timers("forward-backward")`` around trainer phases).

On TPU the device runs async: a timer stop optionally blocks on a marker array so
phases measure device work, not dispatch. Every stop also lands as a span in the
observability tracer (trace id ``train``), so the trainer's phase breakdown —
including the ``jax.block_until_ready`` sync portion, recorded as its own nested
span — shows up in ``/debug/trace`` Chrome timelines next to serving spans.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..observability.tracer import TRACER

__all__ = ["Timers", "RuntimeTimer"]


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._elapsed = 0.0
        self._started: Optional[float] = None
        self.count = 0

    def start(self):
        if self._started is not None:
            raise RuntimeError(f"timer {self.name} already started")
        self._started = time.perf_counter()

    def stop(self, block_on=None):
        if self._started is None:
            raise RuntimeError(f"timer {self.name} not started")
        if block_on is not None:
            t_sync = time.perf_counter()
            import jax

            jax.block_until_ready(block_on)
            TRACER.add_span("block_until_ready", TRACER.epoch_time(t_sync),
                            time.perf_counter() - t_sync, cat="trainer",
                            trace="train", phase=self.name)
        t_end = time.perf_counter()
        TRACER.add_span(self.name, TRACER.epoch_time(self._started),  # span-dynamic: spans are named by the caller's timer name (open phase vocabulary, e.g. "forward-backward")
                        t_end - self._started, cat="trainer", trace="train")
        self._elapsed += t_end - self._started
        self._started = None
        self.count += 1

    def elapsed(self, reset: bool = True) -> float:
        out = self._elapsed
        if reset:
            self._elapsed = 0.0
            self.count = 0
        return out


class Timers:
    """timers("name").start()/.stop(); log(names) prints per-interval ms."""

    def __init__(self):
        self._timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self._timers:
            self._timers[name] = _Timer(name)
        return self._timers[name]

    def log(self, names=None, normalizer: float = 1.0, reset: bool = True) -> str:
        names = names or list(self._timers)
        parts = []
        for n in names:
            if n in self._timers:
                t = self._timers[n]
                parts.append(f"{n}: {1000.0 * t.elapsed(reset) / max(normalizer, 1e-9):.2f}ms")
        line = " | ".join(parts)
        if line:
            from ..utils.log import logger

            logger.info(f"[timers] {line}")
        return line


class RuntimeTimer:
    """Single wall-clock phase timer with a label (reference :70)."""

    def __init__(self, name: str):
        self._timer = _Timer(name)
        self._timer.start()

    def start(self, name: str):
        self._timer = _Timer(name)
        self._timer.start()

    def get_runtime(self) -> str:
        elapsed = time.perf_counter() - self._timer._started
        return f"{self._timer.name}: {elapsed:.2f}s"
