"""``TrainingArguments`` — the run-configuration surface.

Counterpart of ``paddlenlp/trainer/training_args.py`` (~130 dataclass fields whose
``__post_init__`` builds a ``fleet.DistributedStrategy`` and calls ``fleet.init``).
TPU-native: ``__post_init__`` validates and derives a **MeshConfig**; there is no
process-group plumbing to initialize — the mesh IS the strategy. Sharding stages map:

- stage1/stage2 (optimizer/grad sharding)  -> optimizer state sharded over ``fsdp``,
  params replicated (``sharding_stage<=2``)
- stage3 (param sharding / ZeRO-3)         -> params also sharded over ``fsdp``

Field names keep the reference's spelling so the ``llm/config/*.json`` launch
artifacts translate 1:1.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..utils.log import logger
from .trainer_utils import IntervalStrategy, SchedulerType

__all__ = ["TrainingArguments"]


@dataclass
class TrainingArguments:
    output_dir: str = field(default="output", metadata={"help": "output directory for checkpoints/logs"})
    overwrite_output_dir: bool = False

    do_train: bool = False
    do_eval: bool = False
    do_predict: bool = False

    per_device_train_batch_size: int = field(default=8, metadata={"help": "per data-parallel-shard batch size"})
    per_device_eval_batch_size: int = 8
    gradient_accumulation_steps: int = 1

    learning_rate: float = 5e-5
    min_learning_rate: float = 0.0
    weight_decay: float = 0.0
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    max_grad_norm: float = 1.0

    num_train_epochs: float = 3.0
    max_steps: int = -1
    lr_scheduler_type: str = "linear"
    warmup_ratio: float = 0.0
    warmup_steps: int = 0

    logging_first_step: bool = False
    logging_strategy: str = "steps"
    logging_steps: int = 500
    evaluation_strategy: str = "no"
    eval_steps: int = 500
    save_strategy: str = "steps"
    save_steps: int = 500
    save_total_limit: Optional[int] = None
    resume_from_checkpoint: Optional[str] = None

    seed: int = 42
    data_seed: Optional[int] = None

    bf16: bool = False
    fp16: bool = False  # accepted for config compat; mapped to bf16 on TPU
    amp_master_grad: bool = True  # fp32 params + grads ("O2 + master weights")

    dataloader_drop_last: bool = True
    dataloader_num_workers: int = 0
    remove_unused_columns: bool = True
    label_names: Optional[List[str]] = None

    load_best_model_at_end: bool = False
    metric_for_best_model: Optional[str] = None
    greater_is_better: Optional[bool] = None
    ignore_data_skip: bool = False
    skip_data_intervals: Optional[List[List[int]]] = None

    run_name: Optional[str] = None
    report_to: Optional[List[str]] = None
    eval_logits_host_bytes_limit: int = field(
        default=2 << 30,
        metadata={"help": "evaluate()/predict() refuse to accumulate full logits past this many "
                          "host bytes (0 disables the check); pass preprocess_logits_for_metrics, "
                          "raise the limit, or set eval_reduce_logits_to_argmax"})
    eval_reduce_logits_to_argmax: bool = field(
        default=False,
        metadata={"help": "over the host-bytes limit, reduce eval logits to device-side argmax "
                          "token ids instead of raising (compute_metrics then receives [B, T] ids "
                          "rather than [B, T, V] logits)"})
    profiler_options: Optional[str] = field(
        default=None,
        metadata={"help": 'jax.profiler trace window, e.g. "batch_range=[10,20];profile_path=./prof" '
                          "(reference utils/profiler.py ProfilerOptions). The same window also "
                          "dumps the host-side span timeline (Chrome trace JSON) next to the "
                          "device trace."})
    metrics_port: Optional[int] = field(
        default=None,
        metadata={"help": "start a background HTTP observability exporter for this training job "
                          "(GET /metrics Prometheus text, /health, /debug/trace) on this port "
                          "(0 = ephemeral). None (default) disables it; metrics still populate "
                          "the in-process registry either way."})
    metrics_host: str = field(
        default="127.0.0.1",
        metadata={"help": "bind host for the metrics exporter (0.0.0.0 to expose off-host)"})
    disable_tqdm: bool = False

    # ---- parallelism (reference degrees, training_args.py:539-705) ----
    tensor_parallel_degree: int = 1
    pipeline_parallel_degree: int = 1
    sharding_parallel_degree: int = -1
    sep_parallel_degree: int = 1
    context_parallel_degree: int = 1
    sharding: str = field(default="", metadata={"help": '"" | stage1 | stage2 | stage3'})
    data_parallel_degree: int = -1  # derived
    use_expert_parallel: bool = False
    sequence_parallel: bool = False
    tensor_parallel_output: bool = True

    # ---- model runtime knobs bridged via LlmMetaConfig ----
    use_flash_attention: bool = True
    recompute: bool = False
    recompute_granularity: str = "full"
    use_scan_layers: bool = True

    # ---- reference per-axis config strings (training_args.py:645-705). The
    # fleet comm-overlap toggles they carry are obsolete under GSPMD (XLA
    # schedules collective overlap); recognized options warn, unknown ones
    # raise instead of silently dropping a requested behavior. ----
    tensor_parallel_config: str = ""
    pipeline_parallel_config: str = ""
    sharding_parallel_config: str = ""
    sequence_parallel_config: str = ""
    hybrid_parallel_topo_order: str = ""

    # ---- checkpointing ----
    unified_checkpoint: bool = True
    async_save: bool = False

    def __post_init__(self):
        self.logging_strategy = IntervalStrategy(self.logging_strategy)
        self.evaluation_strategy = IntervalStrategy(self.evaluation_strategy)
        self.save_strategy = IntervalStrategy(self.save_strategy)
        self.lr_scheduler_type = SchedulerType(self.lr_scheduler_type)
        if self.fp16:
            logger.warning_once("fp16 requested: TPU MXU native dtype is bf16; using bf16")
            self.bf16, self.fp16 = True, False
        if self.load_best_model_at_end and self.metric_for_best_model is None:
            self.metric_for_best_model = "loss"
        if self.greater_is_better is None and self.metric_for_best_model is not None:
            self.greater_is_better = not self.metric_for_best_model.endswith("loss")
        if self.data_seed is None:
            self.data_seed = self.seed
        sharding = (self.sharding or "").replace(",", " ").split()
        self.sharding_stage = 0
        for s in sharding:
            if s.startswith("stage"):
                self.sharding_stage = int(s[5:])
        if self.sharding_parallel_degree == -1 and self.sharding_stage > 0:
            self.sharding_parallel_degree = 0  # resolved against device count in mesh()
        _KNOWN_OBSOLETE = {
            # fleet comm/overlap scheduling knobs: GSPMD/XLA owns these decisions
            "enable_mp_async_allreduce", "enable_mp_skip_c_identity",
            "enable_mp_fused_linear_param_grad_add", "enable_delay_scale_loss",
            "enable_dp_comm_overlap", "enable_sharding_comm_overlap",
            "enable_release_grads", "enable_overlap_p2p_comm", "enable_clear_every_step_cache",
            "disable_partial_send_recv", "enable_timer", "enable_stage1_tensor_fusion",
            "enable_stage1_overlap", "enable_stage2_overlap", "split_param",
            "disable_p2p_cache_shape", "best_unbalanced_scheduler",
            "enable_allreduce_avg_in_gradinent_scale", "gradient_sync_after_accumulate",
        }
        for fieldname in ("tensor_parallel_config", "pipeline_parallel_config",
                          "sharding_parallel_config", "sequence_parallel_config"):
            raw = getattr(self, fieldname) or ""
            opts = raw.replace(",", " ").split()
            for o in opts:
                if o in _KNOWN_OBSOLETE:
                    logger.warning_once(
                        f"{fieldname}: option {o!r} is a fleet scheduling knob; obsolete "
                        "under GSPMD (XLA schedules comm overlap) — ignored"
                    )
                else:
                    raise ValueError(
                        f"{fieldname}: unsupported option {o!r} (supported-but-obsolete "
                        f"fleet options are ignored with a warning; anything else is an error)"
                    )
        if self.hybrid_parallel_topo_order:
            if self.hybrid_parallel_topo_order not in ("pp_first", "sharding_first"):
                raise ValueError(
                    f"hybrid_parallel_topo_order={self.hybrid_parallel_topo_order!r}: "
                    "expected 'pp_first' or 'sharding_first'"
                )
            logger.warning_once(
                "hybrid_parallel_topo_order is fixed by the mesh axis order "
                "(dp, fsdp, pp, sep, cp, tp — ICI-locality ordered); the knob is accepted "
                "for config compatibility and ignored"
            )
        self._mesh = None

    # ------------------------------------------------------------------ topology
    @property
    def world_size(self) -> int:
        import jax

        return jax.device_count()

    @property
    def process_index(self) -> int:
        import jax

        return jax.process_index()

    @property
    def local_process_index(self) -> int:
        return self.process_index

    @property
    def should_save(self) -> bool:
        return self.process_index == 0

    @property
    def should_log(self) -> bool:
        return self.process_index == 0

    def mesh(self):
        """Build (once) the device mesh implied by the parallel degrees."""
        if self._mesh is None:
            import jax

            from ..parallel.mesh import MeshConfig, create_mesh

            n = jax.device_count()
            fixed = self.tensor_parallel_degree * self.pipeline_parallel_degree * \
                self.sep_parallel_degree * self.context_parallel_degree
            fsdp = self.sharding_parallel_degree
            if fsdp in (-1, 0):
                # absorb everything not taken by other axes into fsdp when sharding
                # was requested, else into dp
                fsdp = (n // fixed) if self.sharding_stage > 0 else 1
            cfg = MeshConfig(
                dp=-1,
                fsdp=fsdp,
                pp=self.pipeline_parallel_degree,
                sep=self.sep_parallel_degree,
                cp=self.context_parallel_degree,
                tp=self.tensor_parallel_degree,
            ).resolve(n)
            self.data_parallel_degree = cfg.dp
            self._mesh = create_mesh(cfg)
        return self._mesh

    @property
    def dataset_world_size(self) -> int:
        """Number of batch shards (dp x fsdp), reference `dataset_world_size`."""
        m = self.mesh()
        return m.shape["dp"] * m.shape["fsdp"]

    @property
    def train_batch_size(self) -> int:
        return self.per_device_train_batch_size

    @property
    def eval_batch_size(self) -> int:
        return self.per_device_eval_batch_size

    @property
    def global_train_batch_size(self) -> int:
        return self.per_device_train_batch_size * self.gradient_accumulation_steps * self.dataset_world_size

    @property
    def global_eval_batch_size(self) -> int:
        return self.per_device_eval_batch_size * self.dataset_world_size

    def get_warmup_steps(self, num_training_steps: int) -> int:
        return self.warmup_steps if self.warmup_steps > 0 else math.ceil(num_training_steps * self.warmup_ratio)

    # ------------------------------------------------------------------ io
    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k, v in d.items():
            if isinstance(v, IntervalStrategy) or isinstance(v, SchedulerType):
                d[k] = v.value
        return d

    def to_json_string(self) -> str:
        return json.dumps(self.to_dict(), indent=2, default=str)

    def __str__(self):
        return f"TrainingArguments {self.to_json_string()}"
