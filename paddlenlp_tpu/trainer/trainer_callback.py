"""Callback system (reference: paddlenlp/trainer/trainer_callback.py —
``TrainerState`` :47, ``TrainerControl`` :118, ``TrainerCallback`` :167,
``CallbackHandler`` :301, ``DefaultFlowCallback`` :432, ``ProgressCallback``,
``EarlyStoppingCallback``)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils.fileio import atomic_write
from ..utils.log import logger
from .trainer_utils import IntervalStrategy

__all__ = [
    "TrainerState",
    "TrainerControl",
    "TrainerCallback",
    "CallbackHandler",
    "DefaultFlowCallback",
    "ProgressCallback",
    "PrinterCallback",
    "EarlyStoppingCallback",
]


@dataclasses.dataclass
class TrainerState:
    epoch: Optional[float] = None
    global_step: int = 0
    max_steps: int = 0
    num_train_epochs: int = 0
    log_history: List[Dict[str, float]] = dataclasses.field(default_factory=list)
    best_metric: Optional[float] = None
    best_model_checkpoint: Optional[str] = None
    is_world_process_zero: bool = True
    consumed_samples: int = 0
    data_step: int = 0  # yielded-batch counter (skip_data_intervals indexing; resume-safe)
    trial_params: Optional[Dict[str, Any]] = None

    def save_to_json(self, json_path: str):
        # tmp+rename: a crash mid-dump must leave the previous state file
        # intact, never a truncated JSON that load_from_json chokes on
        with atomic_write(json_path) as f:
            json.dump(dataclasses.asdict(self), f, indent=2, sort_keys=True, default=str)

    @classmethod
    def load_from_json(cls, json_path: str) -> "TrainerState":
        with open(json_path) as f:
            data = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in known})


@dataclasses.dataclass
class TrainerControl:
    should_training_stop: bool = False
    should_epoch_stop: bool = False
    should_save: bool = False
    should_evaluate: bool = False
    should_log: bool = False

    def _new_training(self):
        self.should_training_stop = False

    def _new_epoch(self):
        self.should_epoch_stop = False

    def _new_step(self):
        self.should_save = False
        self.should_evaluate = False
        self.should_log = False


class TrainerCallback:
    def on_init_end(self, args, state, control, **kwargs):
        pass

    def on_train_begin(self, args, state, control, **kwargs):
        pass

    def on_train_end(self, args, state, control, **kwargs):
        pass

    def on_epoch_begin(self, args, state, control, **kwargs):
        pass

    def on_epoch_end(self, args, state, control, **kwargs):
        pass

    def on_step_begin(self, args, state, control, **kwargs):
        pass

    def on_step_end(self, args, state, control, **kwargs):
        pass

    def on_substep_end(self, args, state, control, **kwargs):
        pass

    def on_evaluate(self, args, state, control, **kwargs):
        pass

    def on_predict(self, args, state, control, **kwargs):
        pass

    def on_save(self, args, state, control, **kwargs):
        pass

    def on_log(self, args, state, control, **kwargs):
        pass

    def on_prediction_step(self, args, state, control, **kwargs):
        pass


class CallbackHandler(TrainerCallback):
    def __init__(self, callbacks, model, tokenizer, optimizer=None, lr_scheduler=None):
        self.callbacks = []
        for cb in callbacks:
            self.add_callback(cb)
        self.model = model
        self.tokenizer = tokenizer
        self.optimizer = optimizer
        self.lr_scheduler = lr_scheduler
        self.train_dataloader = None
        self.eval_dataloader = None

    def add_callback(self, callback):
        cb = callback() if isinstance(callback, type) else callback
        if cb.__class__ in {c.__class__ for c in self.callbacks}:
            logger.warning(f"duplicate callback {cb.__class__.__name__} added")
        self.callbacks.append(cb)

    def pop_callback(self, callback):
        for cb in self.callbacks:
            if cb == callback or cb.__class__ == callback:
                self.callbacks.remove(cb)
                return cb
        return None

    def remove_callback(self, callback):
        self.pop_callback(callback)

    @property
    def callback_list(self) -> str:
        return "\n".join(cb.__class__.__name__ for cb in self.callbacks)

    def call_event(self, event: str, args, state, control, **kwargs):
        for cb in self.callbacks:
            result = getattr(cb, event)(
                args,
                state,
                control,
                model=self.model,
                tokenizer=self.tokenizer,
                optimizer=self.optimizer,
                lr_scheduler=self.lr_scheduler,
                train_dataloader=self.train_dataloader,
                eval_dataloader=self.eval_dataloader,
                **kwargs,
            )
            if result is not None:
                control = result
        return control

    def on_init_end(self, args, state, control):
        return self.call_event("on_init_end", args, state, control)

    def on_train_begin(self, args, state, control):
        control._new_training()
        return self.call_event("on_train_begin", args, state, control)

    def on_train_end(self, args, state, control):
        return self.call_event("on_train_end", args, state, control)

    def on_epoch_begin(self, args, state, control):
        control._new_epoch()
        return self.call_event("on_epoch_begin", args, state, control)

    def on_epoch_end(self, args, state, control):
        return self.call_event("on_epoch_end", args, state, control)

    def on_step_begin(self, args, state, control, **kwargs):
        control._new_step()
        return self.call_event("on_step_begin", args, state, control, **kwargs)

    def on_step_end(self, args, state, control, **kwargs):
        # kwargs carry per-step observables (e.g. ``step_tokens``) for
        # metrics/reporting callbacks
        return self.call_event("on_step_end", args, state, control, **kwargs)

    def on_substep_end(self, args, state, control):
        return self.call_event("on_substep_end", args, state, control)

    def on_evaluate(self, args, state, control, metrics=None):
        control.should_evaluate = False
        return self.call_event("on_evaluate", args, state, control, metrics=metrics)

    def on_save(self, args, state, control):
        control.should_save = False
        return self.call_event("on_save", args, state, control)

    def on_log(self, args, state, control, logs=None):
        control.should_log = False
        return self.call_event("on_log", args, state, control, logs=logs)

    def on_prediction_step(self, args, state, control):
        return self.call_event("on_prediction_step", args, state, control)


class DefaultFlowCallback(TrainerCallback):
    """Sets log/eval/save flags per the interval strategies (reference :432)."""

    def on_step_end(self, args, state, control, **kwargs):
        if state.global_step == 1 and args.logging_first_step:
            control.should_log = True
        if args.logging_strategy == IntervalStrategy.STEPS and state.global_step % args.logging_steps == 0:
            control.should_log = True
        if args.evaluation_strategy == IntervalStrategy.STEPS and state.global_step % args.eval_steps == 0:
            control.should_evaluate = True
        if (
            args.save_strategy == IntervalStrategy.STEPS
            and args.save_steps > 0
            and state.global_step % args.save_steps == 0
        ):
            control.should_save = True
        if state.global_step >= state.max_steps:
            control.should_training_stop = True
        return control

    def on_epoch_end(self, args, state, control, **kwargs):
        if args.logging_strategy == IntervalStrategy.EPOCH:
            control.should_log = True
        if args.evaluation_strategy == IntervalStrategy.EPOCH:
            control.should_evaluate = True
        if args.save_strategy == IntervalStrategy.EPOCH:
            control.should_save = True
        return control


class ProgressCallback(TrainerCallback):
    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is not None and state.is_world_process_zero:
            logs = dict(logs)
            logs.pop("total_flos", None)
            logger.info(f"step {state.global_step}/{state.max_steps} - " + json.dumps(logs, default=str))


class PrinterCallback(TrainerCallback):
    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs is not None and state.is_world_process_zero:
            print(logs, flush=True)


class EarlyStoppingCallback(TrainerCallback):
    def __init__(self, early_stopping_patience: int = 1, early_stopping_threshold: float = 0.0):
        self.early_stopping_patience = early_stopping_patience
        self.early_stopping_threshold = early_stopping_threshold
        self.early_stopping_patience_counter = 0

    def on_evaluate(self, args, state, control, metrics=None, **kwargs):
        metric_to_check = args.metric_for_best_model
        if not metric_to_check:
            return control
        if not metric_to_check.startswith("eval_"):
            metric_to_check = f"eval_{metric_to_check}"
        metric_value = (metrics or {}).get(metric_to_check)
        if metric_value is None:
            logger.warning(f"early stopping requires {metric_to_check}, not found in metrics")
            return control
        operator = np.greater if args.greater_is_better else np.less
        if state.best_metric is None or (
            operator(metric_value, state.best_metric)
            and abs(metric_value - state.best_metric) > self.early_stopping_threshold
        ):
            self.early_stopping_patience_counter = 0
            state.best_metric = metric_value  # this callback owns best-metric tracking
        else:
            self.early_stopping_patience_counter += 1
        if self.early_stopping_patience_counter >= self.early_stopping_patience:
            control.should_training_stop = True
        return control
