"""``GenerationMixin`` — autoregressive decoding, fully inside ``jit``.

Counterpart of ``paddlenlp/generation/utils.py`` (``GenerationMixin`` :319,
``generate`` :609, ``greedy_search`` :1036, ``sample`` :1137). TPU-native redesign:
the reference's per-token Python loop with dynamically growing ``past_key_values``
becomes ONE ``lax.while_loop`` over a static [B, max_length] token buffer and a
static-shape KV cache — zero host sync per token, compiled once per shape. The
reference's ``sample_d2s`` dynamic-to-static export path (:1331) is unnecessary:
the decode loop IS static.

Batched decode uses LEFT padding (tokenizer ``padding_side="left"``), matching the
HF/fast-decode convention; position ids derive from the attention mask.
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.log import logger
from .configuration_utils import GenerationConfig
from .logits_process import (
    ForcedEOSTokenLogitsProcessor,
    FrequencyPenaltyLogitsProcessor,
    LogitsProcessorList,
    MinLengthLogitsProcessor,
    NoRepeatNGramLogitsProcessor,
    PresencePenaltyLogitsProcessor,
    RepetitionPenaltyLogitsProcessor,
    TemperatureLogitsWarper,
    TopKLogitsWarper,
    TopPLogitsWarper,
)

__all__ = ["GenerationMixin"]


def _procs_sig(ps):
    """Hashable signature of a processor list (decode-fn cache key component)."""
    return tuple((type(p).__name__, tuple(sorted(p.__dict__.items()))) for p in ps)


class GenerationMixin:
    """Mixed into ``PretrainedModel``; relies on self.{module,params,config}."""

    def get_logits_processors(self, generation_config: GenerationConfig, prompt_len: int) -> LogitsProcessorList:
        g = generation_config
        procs = LogitsProcessorList()
        if g.min_new_tokens or g.min_length:
            min_new = g.min_new_tokens if g.min_new_tokens else g.min_length
            if g.eos_token_id is not None:
                procs.append(MinLengthLogitsProcessor(min_new, g.eos_token_id, prompt_len))
        if g.repetition_penalty and g.repetition_penalty != 1.0:
            procs.append(RepetitionPenaltyLogitsProcessor(g.repetition_penalty))
        if g.presence_penalty:
            procs.append(PresencePenaltyLogitsProcessor(g.presence_penalty))
        if g.frequency_penalty:
            procs.append(FrequencyPenaltyLogitsProcessor(g.frequency_penalty))
        if g.no_repeat_ngram_size:
            procs.append(NoRepeatNGramLogitsProcessor(g.no_repeat_ngram_size))
        return procs

    def get_logits_warpers(self, generation_config: GenerationConfig) -> LogitsProcessorList:
        g = generation_config
        warpers = LogitsProcessorList()
        if g.temperature is not None and g.temperature != 1.0:
            warpers.append(TemperatureLogitsWarper(g.temperature))
        if g.top_k is not None and g.top_k > 0:
            warpers.append(TopKLogitsWarper(g.top_k))
        if g.top_p is not None and g.top_p < 1.0:
            warpers.append(TopPLogitsWarper(g.top_p))
        return warpers

    def _resolve_generation_config(self, kwargs) -> GenerationConfig:
        base = self.generation_config or GenerationConfig.from_model_config(self.config)
        g = GenerationConfig(**base.to_dict())
        g.update(**kwargs)
        if g.pad_token_id is None:
            g.pad_token_id = getattr(self.config, "pad_token_id", None) or 0
        if g.eos_token_id is None:
            g.eos_token_id = getattr(self.config, "eos_token_id", None)
        if g.decode_strategy == "sampling":
            g.do_sample = True
        return g

    # ------------------------------------------------------------------
    def _gen_position_ids(self, pos, prompt_mask, *, prefill: bool):
        """Model hook: transform the loop's 1D position ids into the model's
        position scheme. ``pos`` [B,T] (prefill) or [B,1] (step: count of real
        tokens before the current one); ``prompt_mask`` [B,T0] is the ORIGINAL
        prompt attention mask. Default: identity (plain causal positions);
        chatglm overrides with the GLM (position, block_position) pair."""
        return pos

    def _init_decode_cache(self, batch_size: int, max_length: int):
        """Decode-cache factory — KVCache by default; attention-free archs
        (mamba) override with their own state pytree."""
        from ..transformers.cache_utils import init_cache

        dtype = jnp.bfloat16 if self.module.dtype == jnp.bfloat16 else jnp.float32
        return init_cache(self.config, batch_size, max_length, dtype=dtype)

    def generate(
        self,
        input_ids,
        attention_mask=None,
        generation_config: Optional[GenerationConfig] = None,
        params=None,
        seed: int = 0,
        streamer=None,
        logits_processors: Optional[LogitsProcessorList] = None,
        **kwargs,
    ):
        """Returns (sequences, scores): generated ids ([B, new_tokens] when
        ``trunc_input``, reference behavior); scores are the length-penalized
        best-beam log-probs for beam search, None for greedy/sampling."""
        if generation_config is not None:
            kwargs = {**generation_config.to_dict(), **kwargs}
        g = self._resolve_generation_config(kwargs)
        params = params if params is not None else self.params
        input_ids = jnp.asarray(input_ids, dtype=jnp.int32)
        B, T0 = input_ids.shape
        if attention_mask is None:
            attention_mask = jnp.ones((B, T0), dtype=jnp.int32)
        else:
            attention_mask = jnp.asarray(attention_mask, dtype=jnp.int32)

        if getattr(self.config, "is_encoder_decoder", False):
            # encoder inputs are correctly RIGHT-padded; no repack warning applies
            return self._generate_seq2seq(params, input_ids, attention_mask, g, seed, streamer,
                                          logits_processors)

        tail = np.asarray(attention_mask[:, -1])
        if (tail == 0).any():
            logger.warning_once(
                "right-padded prompts detected in generate(); use tokenizer padding_side='left' for batched decode"
            )

        if g.max_new_tokens is not None:
            max_length = T0 + int(g.max_new_tokens)
        else:
            max_length = T0 + int(g.max_length)  # reference semantics: max_length counts new tokens
        procs = self.get_logits_processors(g, T0)
        if logits_processors:
            procs.extend(logits_processors)
        warpers = self.get_logits_warpers(g) if g.do_sample else LogitsProcessorList()

        eos_ids = tuple(g.eos_token_id) if isinstance(g.eos_token_id, (list, tuple)) else (
            (g.eos_token_id,) if g.eos_token_id is not None else ()
        )
        use_beams = (g.num_beams or 1) > 1 or g.decode_strategy in ("beam_search", "group_beam_search")
        if use_beams:
            if g.do_sample:
                logger.warning_once(
                    "num_beams>1 runs deterministic beam search; do_sample/temperature/"
                    "top_k/top_p are ignored (stochastic beam sampling is not implemented)"
                )
            num_groups = g.num_beam_groups if g.decode_strategy == "group_beam_search" or g.num_beam_groups > 1 else 1
            beam_decode = self._get_beam_decode_fn(
                max_length=max_length,
                prompt_len=T0,
                pad_id=int(g.pad_token_id),
                eos_ids=eos_ids,
                num_beams=max(g.num_beams, num_groups),
                num_groups=num_groups,
                length_penalty=float(g.length_penalty if g.length_penalty is not None else 1.0),
                diversity_penalty=float(getattr(g, "diversity_penalty", 0.0) or 0.0),
                procs=procs,
            )
            if streamer is not None:
                streamer.put(np.asarray(input_ids))
            ids_buf, best_scores = beam_decode(params, input_ids, attention_mask)
            if streamer is not None:
                for t in range(T0, max_length):
                    streamer.put(np.asarray(ids_buf[:, t]))
                streamer.end()
            if g.trunc_input:
                return ids_buf[:, T0:], best_scores
            return ids_buf, best_scores
        decode = self._get_decode_fn(
            max_length=max_length,
            prompt_len=T0,
            do_sample=bool(g.do_sample),
            pad_id=int(g.pad_token_id),
            eos_ids=eos_ids,
            procs=procs,
            warpers=warpers,
            forced_eos=None,
        )
        key = jax.random.key(seed)
        if streamer is not None:
            streamer.put(np.asarray(input_ids))
        ids_buf, lengths = decode(params, input_ids, attention_mask, key)
        if streamer is not None:
            for t in range(T0, max_length):
                streamer.put(np.asarray(ids_buf[:, t]))
            streamer.end()
        if g.trunc_input:
            return ids_buf[:, T0:], None
        return ids_buf, None

    # ------------------------------------------------------------------ seq2seq
    def _generate_seq2seq(self, params, input_ids, attention_mask, g, seed, streamer, extra_procs):
        """Encoder-decoder decode: encode ONCE, precompute cross-attention K/V,
        then one ``lax.while_loop`` over the decoder (t5/bart). The decoder
        "prompt" is the single ``decoder_start_token_id`` slot; returned ids
        exclude it (new tokens only, matching ``trunc_input`` semantics)."""
        cfg = self.config
        max_new = int(g.max_new_tokens if g.max_new_tokens is not None else g.max_length)
        max_length = max_new + 1  # slot 0 = decoder_start token
        if (g.num_beams or 1) > 1 or g.decode_strategy in ("beam_search", "group_beam_search"):
            logger.warning_once(
                "beam search for encoder-decoder models is not implemented yet; using "
                + ("sampling" if g.do_sample else "greedy")
            )
        procs = self.get_logits_processors(g, prompt_len=1)
        # HF seq2seq conventions (bart): force BOS at the first generated slot,
        # force EOS at the length cap
        # an EXPLICIT forced_*=None in generate kwargs disables the config default
        forced_bos = g.__dict__.get("forced_bos_token_id", getattr(cfg, "forced_bos_token_id", None))
        if forced_bos is not None:
            from .logits_process import ForcedBOSTokenLogitsProcessor

            procs.append(ForcedBOSTokenLogitsProcessor(int(forced_bos)))
        forced_eos = g.__dict__.get("forced_eos_token_id", getattr(cfg, "forced_eos_token_id", None))
        if forced_eos is not None:
            procs.append(ForcedEOSTokenLogitsProcessor(max_length, int(forced_eos)))
        if extra_procs:
            procs.extend(extra_procs)
        warpers = self.get_logits_warpers(g) if g.do_sample else LogitsProcessorList()
        eos_ids = tuple(g.eos_token_id) if isinstance(g.eos_token_id, (list, tuple)) else (
            (g.eos_token_id,) if g.eos_token_id is not None else ()
        )
        start_id = getattr(g, "decoder_start_token_id", None)
        if start_id is None:
            start_id = getattr(cfg, "decoder_start_token_id", None)
        if start_id is None:
            start_id = g.pad_token_id
        decode = self._get_seq2seq_decode_fn(
            max_length=max_length, start_id=int(start_id), do_sample=bool(g.do_sample),
            pad_id=int(g.pad_token_id), eos_ids=eos_ids, procs=procs, warpers=warpers,
        )
        key = jax.random.key(seed)
        ids_buf, _ = decode(params, input_ids, attention_mask, key)
        if streamer is not None:
            for t in range(1, max_length):
                streamer.put(np.asarray(ids_buf[:, t]))
            streamer.end()
        return ids_buf[:, 1:], None

    def _get_seq2seq_decode_fn(self, *, max_length, start_id, do_sample, pad_id, eos_ids, procs, warpers):
        cache_key = ("seq2seq", max_length, start_id, do_sample, pad_id, eos_ids, _procs_sig(procs), _procs_sig(warpers))
        cache = getattr(self, "_decode_cache", None)
        if cache is None:
            cache = self._decode_cache = {}
        if cache_key in cache:
            return cache[cache_key]

        module = self.module
        config = self.config

        def decode(params, enc_ids, enc_mask, key):
            from ..transformers.cache_utils import KVCache

            B = enc_ids.shape[0]
            enc_h = module.apply({"params": params}, enc_ids, enc_mask, method="encode")
            cross = module.apply({"params": params}, enc_h, method="init_cross_kv")
            n_layers = getattr(config, "num_decoder_layers", None) or config.num_hidden_layers
            n_kv = getattr(config, "num_key_value_heads", config.num_attention_heads)
            head_dim = getattr(config, "head_dim", config.hidden_size // config.num_attention_heads)
            kv_dtype = jnp.bfloat16 if module.dtype == jnp.bfloat16 else jnp.float32
            shape = (n_layers, B, max_length, n_kv, head_dim)
            kv = KVCache(keys=jnp.zeros(shape, kv_dtype), values=jnp.zeros(shape, kv_dtype),
                         offset=jnp.zeros((), jnp.int32))
            ids_buf = jnp.full((B, max_length), pad_id, jnp.int32)
            ids_buf = ids_buf.at[:, 0].set(start_id)
            finished = jnp.zeros((B,), jnp.bool_)

            def sample_token(logits, ids_buf, cur_len, key, finished):
                V = logits.shape[-1]
                written = jnp.arange(max_length)[None, :] < cur_len
                proc_ids = jnp.where(written, ids_buf, V)  # sentinel for unwritten slots
                logits = procs(proc_ids, logits, cur_len)
                if do_sample:
                    logits = warpers(proc_ids, logits, cur_len)
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, logits, axis=-1)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                nxt = jnp.where(finished, pad_id, nxt).astype(jnp.int32)
                newly = jnp.zeros_like(finished)
                for e in eos_ids:
                    newly = newly | (nxt == e)
                return nxt, key, finished | newly

            def cond(state):
                _, _, cur_len, _, finished = state
                return (cur_len < max_length) & ~finished.all()

            def body(state):
                ids_buf, kv, cur_len, key, finished = state
                tok = jax.lax.dynamic_slice(ids_buf, (0, cur_len - 1), (B, 1))
                out = module.apply(
                    {"params": params}, tok, enc_h,
                    encoder_attention_mask=enc_mask, cache=kv, cross_kvs=cross, method="decode",
                )
                logits = out.logits[:, -1].astype(jnp.float32)
                nxt, key, finished = sample_token(logits, ids_buf, cur_len, key, finished)
                ids_buf = jax.lax.dynamic_update_slice(ids_buf, nxt[:, None], (0, cur_len))
                return (ids_buf, out.past_key_values, cur_len + 1, key, finished)

            state = (ids_buf, kv, jnp.asarray(1, jnp.int32), key, finished)
            state = jax.lax.while_loop(cond, body, state)
            ids_buf, _, cur_len, _, _ = state
            return ids_buf, cur_len

        fn = jax.jit(decode)
        cache[cache_key] = fn
        return fn

    # ------------------------------------------------------------------
    def _get_beam_decode_fn(self, *, max_length, prompt_len, pad_id, eos_ids, num_beams,
                            num_groups, length_penalty, diversity_penalty, procs):
        """Beam / group-beam search as ONE ``lax.while_loop`` over flat beam state
        (reference ``generation/utils.py:1496`` beam_search, ``:1663``
        group_beam_search — there a Python loop over a BeamHypotheses object;
        here the hypotheses ARE the carry: [B*K] token buffers + per-beam
        scores/finished/lengths, with the KV cache gather-reordered in place).

        Finished beams are frozen by construction: their only candidate
        continuation is ``pad`` at unchanged score, so selection keeps them
        exactly when they remain top-K. Diverse groups subtract
        ``diversity_penalty`` times the count of tokens already chosen by
        earlier groups at the same step (Hamming diversity)."""
        cache_key = ("beams", max_length, prompt_len, pad_id, eos_ids, num_beams, num_groups,
                     length_penalty, diversity_penalty, _procs_sig(procs))
        cache = getattr(self, "_decode_cache", None)
        if cache is None:
            cache = self._decode_cache = {}
        if cache_key in cache:
            return cache[cache_key]

        module = self.module
        config = self.config
        K, G = num_beams, num_groups
        if K % G != 0:
            raise ValueError(f"num_beams {K} must be divisible by num_beam_groups {G}")
        gk = K // G
        NEG = -1.0e9

        def decode(params, input_ids, attention_mask):
            B, T0 = input_ids.shape
            BK = B * K
            rep = lambda x: jnp.repeat(x, K, axis=0)  # [B, ...] -> [B*K, ...]
            ids_buf = jnp.full((BK, max_length), pad_id, jnp.int32)
            ids_buf = jax.lax.dynamic_update_slice(ids_buf, rep(input_ids), (0, 0))
            pad_mask = jnp.concatenate(
                [rep(attention_mask), jnp.ones((BK, max_length - T0), jnp.int32)], axis=1
            )
            kv = self._init_decode_cache(BK, max_length)
            prompt_pos = jnp.clip(jnp.cumsum(rep(attention_mask), axis=1) - 1, 0)
            prompt_pos = self._gen_position_ids(prompt_pos, rep(attention_mask), prefill=True)
            out = module.apply({"params": params}, input_ids=rep(input_ids),
                               attention_mask=pad_mask, position_ids=prompt_pos,
                               cache=kv, deterministic=True)
            kv = out.past_key_values
            logits = out.logits[:, -1].astype(jnp.float32)  # [BK, V]
            V = logits.shape[-1]

            # beam 0 of each group starts live; the rest at -inf (identical prompts)
            init_scores = jnp.full((B, K), NEG, jnp.float32)
            init_scores = init_scores.at[:, ::gk].set(0.0) if G > 1 else init_scores.at[:, 0].set(0.0)
            finished = jnp.zeros((B, K), jnp.bool_)
            lengths = jnp.zeros((B, K), jnp.int32)  # generated-token counts

            eos_arr = jnp.asarray(list(eos_ids) or [-1], jnp.int32)

            def select(logits, scores, finished, lengths, cur_len, ids_buf):
                """One beam-selection step over all groups; returns reorder index
                [B, K] (global beam row per batch), next tokens, new state."""
                proc_ids = jnp.where(pad_mask > 0, ids_buf, V)
                logits = procs(proc_ids, logits, cur_len)
                logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
                new_beam, new_tok, new_scores, new_fin, new_len = [], [], [], [], []
                counts = jnp.zeros((B, V), jnp.float32)
                for g in range(G):
                    sl = slice(g * gk, (g + 1) * gk)
                    lp = logp[:, sl] - diversity_penalty * counts[:, None, :]
                    base = scores[:, sl]
                    cand = base[:, :, None] + lp  # [B, gk, V]
                    # finished beams: single pad candidate at unchanged score
                    fin = finished[:, sl]
                    pad_only = jnp.full((B, gk, V), NEG).at[:, :, pad_id].set(0.0) + base[:, :, None]
                    cand = jnp.where(fin[:, :, None], pad_only, cand)
                    flat = cand.reshape(B, gk * V)
                    top_v, top_i = jax.lax.top_k(flat, gk)
                    b_idx = top_i // V + g * gk  # global beam index within K
                    t_idx = (top_i % V).astype(jnp.int32)
                    sel_fin = jnp.take_along_axis(finished, b_idx, axis=1)
                    sel_len = jnp.take_along_axis(lengths, b_idx, axis=1)
                    hit_eos = (t_idx[..., None] == eos_arr[None, None, :]).any(-1)
                    new_beam.append(b_idx)
                    new_tok.append(t_idx)
                    new_scores.append(top_v)
                    new_fin.append(sel_fin | (hit_eos & ~sel_fin))
                    new_len.append(jnp.where(sel_fin, sel_len, sel_len + 1))
                    if G > 1:
                        counts = counts + jax.nn.one_hot(t_idx, V, dtype=jnp.float32).sum(axis=1)
                return (jnp.concatenate(new_beam, 1), jnp.concatenate(new_tok, 1),
                        jnp.concatenate(new_scores, 1), jnp.concatenate(new_fin, 1),
                        jnp.concatenate(new_len, 1))

            def _flat_idx(beam_idx):
                return (jnp.arange(B)[:, None] * K + beam_idx).reshape(-1)

            def reorder(buf, beam_idx):
                """Gather beam rows of ids_buf ([B*K, L], batch on dim 0)."""
                return buf[_flat_idx(beam_idx)]

            def reorder_kv(kv, beam_idx):
                """Gather cache beams BY FIELD — batch rides axis 1 of every
                state array ([layers, B*K, ...]); offset is a scalar. Explicit
                per-type fields instead of shape sniffing: a leaf whose dims
                coincide with (num_layers, B*K) must not be mis-gathered."""
                from ..transformers.cache_utils import KVCache

                idx = _flat_idx(beam_idx)
                if isinstance(kv, KVCache):
                    return KVCache(keys=kv.keys[:, idx], values=kv.values[:, idx], offset=kv.offset)
                from ..transformers.mamba.modeling import MambaCache

                if isinstance(kv, MambaCache):
                    return MambaCache(conv_states=kv.conv_states[:, idx],
                                      ssm_states=kv.ssm_states[:, idx], offset=kv.offset)
                raise TypeError(f"beam search cannot reorder cache type {type(kv).__name__}")

            def apply_step(state, logits):
                ids_buf, kv, cur_len, scores, finished, lengths = state
                beam_idx, tok, scores, finished, lengths = select(
                    logits, scores, finished, lengths, cur_len, ids_buf
                )
                ids_buf = reorder(ids_buf, beam_idx)
                kv = reorder_kv(kv, beam_idx)
                ids_buf = jax.lax.dynamic_update_slice(ids_buf, tok.reshape(BK, 1), (0, cur_len))
                return ids_buf, kv, cur_len + 1, scores, finished, lengths

            state = apply_step((ids_buf, kv, jnp.asarray(T0, jnp.int32), init_scores, finished, lengths), logits)

            def cond(state):
                _, _, cur_len, _, finished, _ = state
                return (cur_len < max_length) & ~finished.all()

            def body(state):
                ids_buf, kv, cur_len, scores, finished, lengths = state
                tok = jax.lax.dynamic_slice(ids_buf, (0, cur_len - 1), (BK, 1))
                pos = jnp.sum(pad_mask * (jnp.arange(max_length)[None, :] < (cur_len - 1)), axis=1)
                step_pos = self._gen_position_ids(pos[:, None], pad_mask[:, :T0], prefill=False)
                out = module.apply({"params": params}, input_ids=tok, attention_mask=pad_mask,
                                   position_ids=step_pos, cache=kv, deterministic=True)
                logits = out.logits[:, -1].astype(jnp.float32)
                return apply_step((ids_buf, out.past_key_values, cur_len, scores, finished, lengths), logits)

            if max_length > T0 + 1:
                state = jax.lax.while_loop(cond, body, state)
            ids_buf, _, _, scores, finished, lengths = state
            # length-penalized final selection (reference BeamHypotheses.add)
            norm = scores / jnp.maximum(lengths.astype(jnp.float32), 1.0) ** length_penalty
            best = jnp.argmax(norm, axis=1)  # [B]
            rows = jnp.arange(B) * K + best
            return ids_buf.reshape(B * K, max_length)[rows], jnp.take_along_axis(norm, best[:, None], 1)[:, 0]

        fn = jax.jit(decode)
        cache[cache_key] = fn
        return fn

    def _get_decode_fn(self, *, max_length, prompt_len, do_sample, pad_id, eos_ids, procs, warpers, forced_eos):
        cache_key = (max_length, prompt_len, do_sample, pad_id, eos_ids, _procs_sig(procs), _procs_sig(warpers))
        cache = getattr(self, "_decode_cache", None)
        if cache is None:
            cache = self._decode_cache = {}
        if cache_key in cache:
            return cache[cache_key]

        module = self.module
        config = self.config

        def decode(params, input_ids, attention_mask, key):
            B, T0 = input_ids.shape
            ids_buf = jnp.full((B, max_length), pad_id, dtype=jnp.int32)
            ids_buf = jax.lax.dynamic_update_slice(ids_buf, input_ids, (0, 0))
            pad_mask = jnp.concatenate(
                [attention_mask, jnp.ones((B, max_length - T0), jnp.int32)], axis=1
            )
            kv = self._init_decode_cache(B, max_length)

            # ---- prefill ----
            prompt_pos = jnp.clip(jnp.cumsum(attention_mask, axis=1) - 1, 0)
            prompt_pos = self._gen_position_ids(prompt_pos, attention_mask, prefill=True)
            out = module.apply(
                {"params": params},
                input_ids=input_ids,
                attention_mask=pad_mask,
                position_ids=prompt_pos,
                cache=kv,
                deterministic=True,
            )
            kv = out.past_key_values
            logits0 = out.logits[:, -1].astype(jnp.float32)
            finished = jnp.zeros((B,), jnp.bool_)

            def sample_token(logits, ids_buf, cur_len, key, finished):
                # Left-pad prompt slots must not feed repetition/ngram processors:
                # replace them with an out-of-range sentinel (one_hot drops it).
                proc_ids = jnp.where(pad_mask > 0, ids_buf, logits.shape[-1])
                logits = procs(proc_ids, logits, cur_len)
                if do_sample:
                    logits = warpers(proc_ids, logits, cur_len)
                    key, sub = jax.random.split(key)
                    nxt = jax.random.categorical(sub, logits, axis=-1)
                else:
                    nxt = jnp.argmax(logits, axis=-1)
                nxt = jnp.where(finished, pad_id, nxt).astype(jnp.int32)
                newly = jnp.zeros_like(finished)
                for e in eos_ids:
                    newly = newly | (nxt == e)
                finished = finished | newly
                return nxt, key, finished

            nxt, key_, finished = sample_token(logits0, ids_buf, jnp.asarray(T0), key, finished)
            ids_buf = jax.lax.dynamic_update_slice(ids_buf, nxt[:, None], (0, T0))

            def cond(state):
                ids_buf, kv, cur_len, key, finished = state
                return (cur_len < max_length) & ~finished.all()

            def body(state):
                ids_buf, kv, cur_len, key, finished = state
                tok = jax.lax.dynamic_slice(ids_buf, (0, cur_len - 1), (B, 1))
                pos = jnp.sum(pad_mask * (jnp.arange(max_length)[None, :] < (cur_len - 1)), axis=1)
                step_pos = self._gen_position_ids(pos[:, None], pad_mask[:, :T0], prefill=False)
                out = module.apply(
                    {"params": params},
                    input_ids=tok,
                    attention_mask=pad_mask,
                    position_ids=step_pos,
                    cache=kv,
                    deterministic=True,
                )
                kv = out.past_key_values
                logits = out.logits[:, -1].astype(jnp.float32)
                nxt, key, finished = sample_token(logits, ids_buf, cur_len, key, finished)
                ids_buf = jax.lax.dynamic_update_slice(ids_buf, nxt[:, None], (0, cur_len))
                return (ids_buf, kv, cur_len + 1, key, finished)

            state = (ids_buf, kv, jnp.asarray(T0 + 1, jnp.int32), key_, finished)
            if max_length > T0 + 1:
                state = jax.lax.while_loop(cond, body, state)
            ids_buf, kv, cur_len, _, finished = state
            return ids_buf, cur_len

        fn = jax.jit(decode)
        cache[cache_key] = fn
        return fn
