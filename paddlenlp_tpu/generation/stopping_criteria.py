"""Stopping criteria (reference: paddlenlp/generation/stopping_criteria.py, 91 LoC).

Inside the jitted decode loop, stopping is a traced predicate over
``(ids_buf, cur_len, finished)``; max-length/max-time live at the loop boundary.
"""

from __future__ import annotations

import time
from typing import List

import jax.numpy as jnp

__all__ = ["StoppingCriteria", "StoppingCriteriaList", "MaxLengthCriteria", "MaxTimeCriteria"]


class StoppingCriteria:
    def __call__(self, ids_buf, cur_len, **kwargs) -> bool:
        raise NotImplementedError


class StoppingCriteriaList(list):
    def __call__(self, ids_buf, cur_len, **kwargs):
        done = jnp.asarray(False)
        for crit in self:
            done = jnp.logical_or(done, crit(ids_buf, cur_len, **kwargs))
        return done

    @property
    def max_length(self):
        for c in self:
            if isinstance(c, MaxLengthCriteria):
                return c.max_length
        return None


class MaxLengthCriteria(StoppingCriteria):
    def __init__(self, max_length: int):
        self.max_length = max_length

    def __call__(self, ids_buf, cur_len, **kwargs):
        return cur_len >= self.max_length


class MaxTimeCriteria(StoppingCriteria):
    """Host-side wall clock bound — usable only in the eager (streamer) loop."""

    def __init__(self, max_time: float):
        self.max_time = max_time
        self.start = time.time()

    def __call__(self, ids_buf, cur_len, **kwargs):
        return jnp.asarray(time.time() - self.start > self.max_time)
