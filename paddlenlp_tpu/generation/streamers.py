"""Token streamers (reference: paddlenlp/generation/streamers.py —
``TextStreamer``, ``TextIteratorStreamer``)."""

from __future__ import annotations

from queue import Queue
from typing import Optional

__all__ = ["BaseStreamer", "TextStreamer", "TextIteratorStreamer"]


class BaseStreamer:
    def put(self, value):
        raise NotImplementedError

    def end(self):
        raise NotImplementedError


class TextStreamer(BaseStreamer):
    """Decode and print tokens as they arrive (word-boundary buffered)."""

    def __init__(self, tokenizer, skip_prompt: bool = False, **decode_kwargs):
        self.tokenizer = tokenizer
        self.skip_prompt = skip_prompt
        self.decode_kwargs = decode_kwargs
        self.token_cache = []
        self.print_len = 0
        self.next_tokens_are_prompt = True

    def put(self, value):
        import numpy as np

        value = np.asarray(value).reshape(-1)
        if self.skip_prompt and self.next_tokens_are_prompt:
            self.next_tokens_are_prompt = False
            return
        self.token_cache.extend(int(v) for v in value)
        text = self.tokenizer.decode(self.token_cache, **self.decode_kwargs)
        if text.endswith("\n"):
            printable = text[self.print_len :]
            self.token_cache = []
            self.print_len = 0
        elif len(text) > 0 and _ends_mid_char(text):
            printable = ""
        else:
            printable = text[self.print_len : text.rfind(" ") + 1] if " " in text[self.print_len :] else ""
            self.print_len += len(printable)
        if printable:
            self.on_finalized_text(printable)

    def end(self):
        if self.token_cache:
            text = self.tokenizer.decode(self.token_cache, **self.decode_kwargs)
            printable = text[self.print_len :]
        else:
            printable = ""
        self.token_cache = []
        self.print_len = 0
        self.next_tokens_are_prompt = True
        self.on_finalized_text(printable, stream_end=True)

    def on_finalized_text(self, text: str, stream_end: bool = False):
        print(text, flush=True, end="" if not stream_end else None)


def _ends_mid_char(text: str) -> bool:
    return text.endswith("�")


class TextIteratorStreamer(TextStreamer):
    """Streamer exposing an iterator interface (for serving)."""

    def __init__(self, tokenizer, skip_prompt: bool = False, timeout: Optional[float] = None, **decode_kwargs):
        super().__init__(tokenizer, skip_prompt, **decode_kwargs)
        self.queue: Queue = Queue()
        self.stop_signal = None
        self.timeout = timeout

    def on_finalized_text(self, text: str, stream_end: bool = False):
        if text:
            self.queue.put(text, timeout=self.timeout)
        if stream_end:
            self.queue.put(self.stop_signal, timeout=self.timeout)

    def __iter__(self):
        return self

    def __next__(self):
        value = self.queue.get(timeout=self.timeout)
        if value == self.stop_signal:
            raise StopIteration
        return value
