from .configuration_utils import GenerationConfig  # noqa: F401
from .logits_process import (  # noqa: F401
    FrequencyPenaltyLogitsProcessor,
    LogitsProcessorList,
    MinLengthLogitsProcessor,
    NoRepeatNGramLogitsProcessor,
    PresencePenaltyLogitsProcessor,
    RepetitionPenaltyLogitsProcessor,
    TemperatureLogitsWarper,
    TopKLogitsWarper,
    TopPLogitsWarper,
)
from .stopping_criteria import MaxLengthCriteria, MaxTimeCriteria, StoppingCriteriaList  # noqa: F401
from .streamers import TextIteratorStreamer, TextStreamer  # noqa: F401
from .utils import GenerationMixin  # noqa: F401
