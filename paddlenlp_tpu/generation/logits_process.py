"""Logits processors & warpers — all trace-compatible (run inside the jitted
decode loop; no data-dependent Python control flow).

Counterpart of ``paddlenlp/generation/logits_process.py`` (646 LoC): repetition /
presence / frequency penalties, min-length, no-repeat-ngram, top-k/top-p/temperature.
Each processor is ``(ids_buf, logits, cur_len) -> logits`` where ``ids_buf`` is the
static [B, max_len] decode buffer (prefix < cur_len is valid) — the static-shape
re-expression of the reference's dynamically-growing ``input_ids``.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "LogitsProcessorList",
    "MinLengthLogitsProcessor",
    "RepetitionPenaltyLogitsProcessor",
    "PresencePenaltyLogitsProcessor",
    "FrequencyPenaltyLogitsProcessor",
    "NoRepeatNGramLogitsProcessor",
    "ForcedBOSTokenLogitsProcessor",
    "ForcedEOSTokenLogitsProcessor",
    "TemperatureLogitsWarper",
    "TopKLogitsWarper",
    "TopPLogitsWarper",
]

NEG_INF = -1e9


class LogitsProcessor:
    def __call__(self, ids_buf, logits, cur_len):
        raise NotImplementedError


class LogitsProcessorList(list):
    def __call__(self, ids_buf, logits, cur_len):
        for proc in self:
            logits = proc(ids_buf, logits, cur_len)
        return logits


def _valid_counts(ids_buf: jnp.ndarray, cur_len, vocab_size: int) -> jnp.ndarray:
    """[B, vocab] counts of each token in the valid prefix (one-hot scatter-sum).

    Callers exclude positions (e.g. left-pad prompt slots) by setting them to an
    out-of-range sentinel id (>= vocab_size): ``one_hot`` maps those to all-zero
    rows, so they never contribute to the counts.
    """
    B, L = ids_buf.shape
    valid = (jnp.arange(L)[None, :] < cur_len).astype(jnp.int32)
    onehot = jax.nn.one_hot(ids_buf, vocab_size, dtype=jnp.int32)
    return (onehot * valid[..., None]).sum(axis=1)


class MinLengthLogitsProcessor(LogitsProcessor):
    def __init__(self, min_length: int, eos_token_id, prompt_len: int = 0):
        self.min_length = min_length
        ids = eos_token_id if isinstance(eos_token_id, (list, tuple)) else [eos_token_id]
        self.eos_token_ids = tuple(int(i) for i in ids)
        self.prompt_len = prompt_len

    def __call__(self, ids_buf, logits, cur_len):
        block = (cur_len - self.prompt_len) < self.min_length
        eos_mask = jnp.zeros_like(logits)
        for eos in self.eos_token_ids:
            eos_mask = eos_mask.at[:, eos].set(NEG_INF)
        return jnp.where(block, logits + eos_mask, logits)


class RepetitionPenaltyLogitsProcessor(LogitsProcessor):
    """CTRL-style: divide positive / multiply negative logits of seen tokens."""

    def __init__(self, penalty: float):
        self.penalty = penalty

    def __call__(self, ids_buf, logits, cur_len):
        counts = _valid_counts(ids_buf, cur_len, logits.shape[-1])
        seen = counts > 0
        penalized = jnp.where(logits > 0, logits / self.penalty, logits * self.penalty)
        return jnp.where(seen, penalized, logits)


class PresencePenaltyLogitsProcessor(LogitsProcessor):
    def __init__(self, penalty: float):
        self.penalty = penalty

    def __call__(self, ids_buf, logits, cur_len):
        seen = _valid_counts(ids_buf, cur_len, logits.shape[-1]) > 0
        return logits - seen.astype(logits.dtype) * self.penalty


class FrequencyPenaltyLogitsProcessor(LogitsProcessor):
    def __init__(self, penalty: float):
        self.penalty = penalty

    def __call__(self, ids_buf, logits, cur_len):
        counts = _valid_counts(ids_buf, cur_len, logits.shape[-1])
        return logits - counts.astype(logits.dtype) * self.penalty


class NoRepeatNGramLogitsProcessor(LogitsProcessor):
    """Ban tokens that would complete an already-seen n-gram (vectorized O(L^2))."""

    def __init__(self, ngram_size: int):
        self.n = ngram_size

    def __call__(self, ids_buf, logits, cur_len):
        n = self.n
        B, L = ids_buf.shape
        if n <= 1 or L < n:
            return logits
        # current (n-1)-gram suffix ending at cur_len-1
        def suffix_at(off):
            return jnp.take_along_axis(ids_buf, (cur_len - (n - 1) + off)[None, None].repeat(B, 0), axis=1)[:, 0]

        cur_suffix = jnp.stack([suffix_at(jnp.asarray(i)) for i in range(n - 1)], axis=1)  # [B, n-1]
        # all historical (n-1)-grams and their next tokens
        starts = jnp.arange(L - n + 1)
        windows = jnp.stack([ids_buf[:, s : s + L - n + 1] for s in range(n - 1)], axis=2)  # [B, L-n+1, n-1]
        next_tokens = ids_buf[:, n - 1 :]  # [B, L-n+1]
        match = (windows == cur_suffix[:, None, :]).all(axis=-1)  # [B, L-n+1]
        # only n-grams fully inside the valid prefix count
        valid = (starts[None, :] + n - 1) < cur_len
        match = match & valid & ((cur_len - (n - 1)) >= 0)
        banned = jax.vmap(
            lambda m, nt: jnp.zeros(logits.shape[-1], jnp.bool_).at[nt].max(m)
        )(match, next_tokens)
        return jnp.where(banned, logits + NEG_INF, logits)


class ForcedBOSTokenLogitsProcessor(LogitsProcessor):
    def __init__(self, bos_token_id: int):
        self.bos_token_id = bos_token_id

    def __call__(self, ids_buf, logits, cur_len):
        forced = jnp.full_like(logits, NEG_INF).at[:, self.bos_token_id].set(0.0)
        return jnp.where(cur_len == 1, forced, logits)


class ForcedEOSTokenLogitsProcessor(LogitsProcessor):
    def __init__(self, max_length: int, eos_token_id: int):
        self.max_length = max_length
        self.eos_token_id = eos_token_id

    def __call__(self, ids_buf, logits, cur_len):
        forced = jnp.full_like(logits, NEG_INF).at[:, self.eos_token_id].set(0.0)
        return jnp.where(cur_len == self.max_length - 1, forced, logits)


class TemperatureLogitsWarper(LogitsProcessor):
    def __init__(self, temperature: float):
        self.temperature = temperature

    def __call__(self, ids_buf, logits, cur_len):
        return logits / self.temperature


class TopKLogitsWarper(LogitsProcessor):
    def __init__(self, top_k: int):
        self.top_k = top_k

    def __call__(self, ids_buf, logits, cur_len):
        k = min(self.top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        return jnp.where(logits < kth, NEG_INF, logits)


class TopPLogitsWarper(LogitsProcessor):
    def __init__(self, top_p: float):
        self.top_p = top_p

    def __call__(self, ids_buf, logits, cur_len):
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep smallest prefix with cumulative prob >= top_p (always keep the top-1)
        keep_sorted = (cum - probs) < self.top_p
        kth = jnp.where(keep_sorted, sorted_logits, jnp.inf).min(axis=-1, keepdims=True)
        return jnp.where(logits < kth, NEG_INF, logits)
