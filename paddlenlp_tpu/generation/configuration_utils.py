"""GenerationConfig (reference: paddlenlp/generation/configuration_utils.py)."""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, Optional

from ..utils.env import GENERATION_CONFIG_NAME
from ..utils.log import logger

__all__ = ["GenerationConfig"]


class GenerationConfig:
    def __init__(self, **kwargs):
        self.max_length = kwargs.pop("max_length", 20)
        self.max_new_tokens = kwargs.pop("max_new_tokens", None)
        self.min_length = kwargs.pop("min_length", 0)
        self.min_new_tokens = kwargs.pop("min_new_tokens", None)
        self.do_sample = kwargs.pop("do_sample", False)
        self.num_beams = kwargs.pop("num_beams", 1)
        self.num_beam_groups = kwargs.pop("num_beam_groups", 1)
        self.temperature = kwargs.pop("temperature", 1.0)
        self.top_k = kwargs.pop("top_k", 50)
        self.top_p = kwargs.pop("top_p", 1.0)
        self.repetition_penalty = kwargs.pop("repetition_penalty", 1.0)
        self.presence_penalty = kwargs.pop("presence_penalty", 0.0)
        self.frequency_penalty = kwargs.pop("frequency_penalty", 0.0)
        self.no_repeat_ngram_size = kwargs.pop("no_repeat_ngram_size", None)
        self.length_penalty = kwargs.pop("length_penalty", 1.0)
        self.early_stopping = kwargs.pop("early_stopping", False)
        self.num_return_sequences = kwargs.pop("num_return_sequences", 1)
        self.pad_token_id = kwargs.pop("pad_token_id", None)
        self.bos_token_id = kwargs.pop("bos_token_id", None)
        self.eos_token_id = kwargs.pop("eos_token_id", None)
        self.decode_strategy = kwargs.pop("decode_strategy", None)  # reference naming
        self.use_cache = kwargs.pop("use_cache", True)
        self.trunc_input = kwargs.pop("trunc_input", True)
        self._from_model_config = kwargs.pop("_from_model_config", False)
        for k, v in kwargs.items():
            try:
                setattr(self, k, v)
            except AttributeError:
                logger.warning(f"can't set generation config key {k}")

    # ------------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy({k: v for k, v in self.__dict__.items()})

    def update(self, **kwargs) -> Dict[str, Any]:
        unused = {}
        for k, v in kwargs.items():
            if hasattr(self, k) or not k.startswith("_"):
                setattr(self, k, v)
            else:
                unused[k] = v
        return unused

    def save_pretrained(self, save_directory: str):
        os.makedirs(save_directory, exist_ok=True)
        with open(os.path.join(save_directory, GENERATION_CONFIG_NAME), "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True, default=str)

    @classmethod
    def from_pretrained(cls, pretrained_model_name_or_path, **kwargs) -> "GenerationConfig":
        from ..utils.downloader import resolve_file

        path = resolve_file(pretrained_model_name_or_path, GENERATION_CONFIG_NAME)
        with open(path) as f:
            return cls(**{**json.load(f), **kwargs})

    @classmethod
    def from_model_config(cls, model_config) -> "GenerationConfig":
        return cls(
            bos_token_id=getattr(model_config, "bos_token_id", None),
            eos_token_id=getattr(model_config, "eos_token_id", None),
            pad_token_id=getattr(model_config, "pad_token_id", None),
            _from_model_config=True,
        )

    def __repr__(self):
        return f"GenerationConfig {json.dumps(self.to_dict(), indent=2, default=str)}"
