"""Megatron-style GPT pretraining dataset over mmap corpora.

Counterpart of ``paddlenlp/data/causal_dataset.py`` (711 LoC):
``build_train_valid_test_datasets`` (:112) with weighted multi-corpus blending
(blendable_dataset.py), ``GPTDataset`` (:282) with cached doc/sample/shuffle index
build (:417, one rank builds / others wait). Index hot loops run in the native
helper (csrc/sample_idx.cpp) with a NumPy fallback; caches are keyed by
(seq_length, n_samples, seed) next to the corpus files.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.log import logger
from .indexed_dataset import MMapIndexedDataset, make_dataset
from .native import build_sample_idx

__all__ = ["GPTDataset", "BlendableDataset", "build_train_valid_test_datasets", "get_train_valid_test_split_"]


def get_train_valid_test_split_(splits_string: str, size: int) -> List[int]:
    """'949,50,1' -> cumulative sample boundaries (reference helper)."""
    splits = [float(s) for s in splits_string.replace("/", ",").split(",")]
    while len(splits) < 3:
        splits.append(0.0)
    total = sum(splits) or 1.0
    weights = [s / total for s in splits]
    bounds = [0]
    for w in weights:
        bounds.append(bounds[-1] + int(round(w * size)))
    bounds[3] = size
    return bounds


class GPTDataset:
    """Fixed-length causal-LM samples drawn from a document stream.

    Produces dicts with ``input_ids`` [seq_length] and ``labels`` (next tokens) —
    samples span document boundaries exactly like the reference (:282).
    """

    def __init__(
        self,
        indexed: MMapIndexedDataset,
        doc_ids: np.ndarray,  # document indices belonging to this split
        seq_length: int,
        n_samples: int,
        seed: int = 0,
        name: str = "train",
        cache_dir: Optional[str] = None,
    ):
        self.indexed = indexed
        self.seq_length = seq_length
        self.n_samples = n_samples
        self.seed = seed
        self.name = name

        doc_ids = np.asarray(doc_ids, dtype=np.int64)
        tokens_per_epoch = int(self.indexed.sizes[doc_ids].sum())
        n_epochs = max(1, int(np.ceil((n_samples * (seq_length + 1)) / max(tokens_per_epoch, 1))) + 1)

        cache_key = hashlib.md5(
            f"{name}-{seq_length}-{n_samples}-{seed}-{len(doc_ids)}-{tokens_per_epoch}".encode()
        ).hexdigest()[:16]
        cache_base = cache_dir or os.path.join(os.path.dirname(indexed._prefix) or ".", "index-cache")
        cache_path = os.path.join(cache_base, f"{os.path.basename(indexed._prefix)}-{cache_key}")
        done_marker = cache_path + "-done"
        if os.path.isfile(done_marker):  # marker written LAST via atomic rename
            self.doc_idx = np.load(cache_path + "-doc.npy")
            self.sample_idx = np.load(cache_path + "-sample.npy")
            self.shuffle_idx = np.load(cache_path + "-shuffle.npy")
            return

        t0 = time.time()
        rng = np.random.default_rng(seed)
        # epoch-repeated shuffled document order
        self.doc_idx = np.concatenate([rng.permutation(doc_ids) for _ in range(n_epochs)])
        self.sample_idx = build_sample_idx(self.indexed.sizes, self.doc_idx, seq_length, n_samples)
        self.shuffle_idx = rng.permutation(n_samples).astype(np.int64)
        try:
            # concurrent-safe publish: per-file tmp + os.replace, done-marker last.
            # Concurrent builders compute identical (deterministic) indices, so the
            # last replace wins harmlessly; readers gate on the marker.
            os.makedirs(cache_base, exist_ok=True)
            tmp_suffix = f".tmp{os.getpid()}"
            for suffix, arr in (("-doc.npy", self.doc_idx), ("-sample.npy", self.sample_idx),
                                ("-shuffle.npy", self.shuffle_idx)):
                np.save(cache_path + suffix + tmp_suffix, arr)
                os.replace(cache_path + suffix + tmp_suffix + ".npy", cache_path + suffix)
            with open(done_marker + tmp_suffix, "w") as f:
                f.write("ok")
            os.replace(done_marker + tmp_suffix, done_marker)
        except OSError as e:
            logger.warning(f"index cache write failed: {e}")
        logger.info(f"built {name} GPTDataset index in {time.time() - t0:.2f}s "
                    f"(docs/epoch={len(doc_ids)}, epochs={n_epochs}, samples={n_samples})")

    def __len__(self) -> int:
        return self.n_samples

    def __getitem__(self, idx: int):
        idx = int(self.shuffle_idx[idx % self.n_samples])
        doc_pos0, offset0 = self.sample_idx[idx]
        doc_pos1, offset1 = self.sample_idx[idx + 1]
        parts = []
        if doc_pos0 == doc_pos1:
            parts.append(self.indexed.get(int(self.doc_idx[doc_pos0]), int(offset0),
                                          int(offset1 - offset0)))
        else:
            parts.append(self.indexed.get(int(self.doc_idx[doc_pos0]), int(offset0)))
            for p in range(int(doc_pos0) + 1, int(doc_pos1)):
                parts.append(self.indexed.get(int(self.doc_idx[p])))
            if offset1 > 0:
                parts.append(self.indexed.get(int(self.doc_idx[doc_pos1]), 0, int(offset1)))
        tokens = np.concatenate(parts).astype(np.int64)
        assert len(tokens) == self.seq_length + 1, (len(tokens), self.seq_length)
        return {"input_ids": tokens[:-1].astype(np.int32), "labels": tokens[1:].astype(np.int32)}


class BlendableDataset:
    """Weighted mixture of datasets (reference blendable_dataset.py): sample i of
    the blend is drawn from the component whose running quota is furthest behind."""

    def __init__(self, datasets: Sequence, weights: Sequence[float], n_samples: int, seed: int = 0):
        assert len(datasets) == len(weights) and datasets
        self.datasets = list(datasets)
        w = np.asarray(weights, dtype=np.float64)
        w = w / w.sum()
        self.n_samples = n_samples
        # deterministic largest-deficit assignment in the native helper (the
        # reference/Megatron build_blending_indices hot loop)
        from .native import build_blending_indices

        self.dataset_index, self.dataset_sample_index = build_blending_indices(w, n_samples)

    def __len__(self):
        return self.n_samples

    def __getitem__(self, idx):
        d = self.dataset_index[idx]
        return self.datasets[d][int(self.dataset_sample_index[idx]) % len(self.datasets[d])]


def build_train_valid_test_datasets(
    data_prefix,
    seq_length: int,
    train_valid_test_num_samples: Tuple[int, int, int],
    splits_string: str = "949,50,1",
    seed: int = 0,
    cache_dir: Optional[str] = None,
):
    """Reference causal_dataset.py:112 — single corpus or weighted blend
    (``[w1, prefix1, w2, prefix2, ...]``)."""
    if isinstance(data_prefix, (list, tuple)) and len(data_prefix) > 1:
        weights = [float(w) for w in data_prefix[0::2]]
        prefixes = [str(p) for p in data_prefix[1::2]]
        total_w = sum(weights)
        per_split = []
        for split_i in range(3):
            n = train_valid_test_num_samples[split_i]
            if n <= 0:
                per_split.append(None)
                continue
            comps = []
            for prefix, w in zip(prefixes, weights):
                # each component only needs ~weight*n samples (+margin for the
                # greedy assignment), not the full blend size
                comp_counts = [0, 0, 0]
                comp_counts[split_i] = int(np.ceil(n * w / total_w)) + 1
                t, v, te = build_train_valid_test_datasets(
                    prefix, seq_length, tuple(comp_counts), splits_string, seed, cache_dir
                )
                comps.append((t, v, te)[split_i])
            per_split.append(BlendableDataset(comps, weights, n, seed))
        return tuple(per_split)

    prefix = data_prefix[0] if isinstance(data_prefix, (list, tuple)) else data_prefix
    indexed = make_dataset(str(prefix))
    bounds = get_train_valid_test_split_(splits_string, indexed.n_docs)
    out = []
    for i, name in enumerate(["train", "valid", "test"]):
        n = train_valid_test_num_samples[i]
        docs = np.arange(bounds[i], bounds[i + 1], dtype=np.int64)
        if n <= 0 or len(docs) == 0:
            out.append(None)
            continue
        out.append(GPTDataset(indexed, docs, seq_length, n, seed=seed, name=name, cache_dir=cache_dir))
    return tuple(out)
