"""Data collators (reference: paddlenlp/data/data_collator.py — default/padding
collators :1-320, ``DataCollatorForSeq2Seq`` :321, LM masking :501)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = [
    "default_data_collator",
    "DataCollatorWithPadding",
    "DataCollatorForSeq2Seq",
    "DataCollatorForLanguageModeling",
]


def default_data_collator(features: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    batch = {}
    for k in features[0]:
        vals = [f[k] for f in features]
        if isinstance(vals[0], (int, float, np.integer, np.floating)):
            batch[k] = np.asarray(vals)
        else:
            batch[k] = np.stack([np.asarray(v) for v in vals])
    return batch


def _pad_to(arrs: List[np.ndarray], pad_value, multiple: Optional[int] = None, side: str = "right"):
    target = max(len(a) for a in arrs)
    if multiple:
        target = ((target + multiple - 1) // multiple) * multiple
    out = np.full((len(arrs), target), pad_value, dtype=np.asarray(arrs[0]).dtype)
    for i, a in enumerate(arrs):
        if side == "right":
            out[i, : len(a)] = a
        else:
            out[i, target - len(a):] = a
    return out


@dataclasses.dataclass
class DataCollatorWithPadding:
    tokenizer: Any
    padding: bool = True
    max_length: Optional[int] = None
    pad_to_multiple_of: Optional[int] = None
    return_attention_mask: bool = True
    label_pad_token_id: int = -100

    def __call__(self, features: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
        pad_id = self.tokenizer.pad_token_id if self.tokenizer is not None else 0
        if pad_id is None:
            pad_id = 0
        ids = [np.asarray(f["input_ids"]) for f in features]
        side = getattr(self.tokenizer, "padding_side", "right")
        batch = {"input_ids": _pad_to(ids, pad_id, self.pad_to_multiple_of, side)}
        if self.return_attention_mask:
            masks = [np.ones(len(a), dtype=np.int64) for a in ids]
            batch["attention_mask"] = _pad_to(masks, 0, self.pad_to_multiple_of, side)
        for key in features[0]:
            if key in ("input_ids", "attention_mask"):
                continue
            vals = [np.asarray(f[key]) for f in features]
            if vals[0].ndim == 0:
                batch[key] = np.stack(vals)
            else:
                fill = self.label_pad_token_id if key == "labels" else 0
                batch[key] = _pad_to(vals, fill, self.pad_to_multiple_of, side)
        return batch


@dataclasses.dataclass
class DataCollatorForSeq2Seq(DataCollatorWithPadding):
    pass


@dataclasses.dataclass
class DataCollatorForLanguageModeling:
    """MLM masking (reference :501): 15% of tokens -> 80% [MASK] / 10% random / 10% keep."""

    tokenizer: Any
    mlm: bool = True
    mlm_probability: float = 0.15
    pad_to_multiple_of: Optional[int] = None
    seed: Optional[int] = None

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)

    def __call__(self, features: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
        pad_id = self.tokenizer.pad_token_id or 0
        ids = [np.asarray(f["input_ids"]) for f in features]
        input_ids = _pad_to(ids, pad_id, self.pad_to_multiple_of)
        attention_mask = _pad_to([np.ones(len(a), np.int64) for a in ids], 0, self.pad_to_multiple_of)
        if not self.mlm:
            labels = input_ids.copy()
            labels[attention_mask == 0] = -100
            return {"input_ids": input_ids, "attention_mask": attention_mask, "labels": labels}

        labels = input_ids.copy()
        special = np.zeros_like(input_ids, dtype=bool)
        for tid in (self.tokenizer.cls_token_id, self.tokenizer.sep_token_id, pad_id):
            if tid is not None:
                special |= input_ids == tid
        prob = self._rng.random(input_ids.shape)
        masked = (prob < self.mlm_probability) & ~special & (attention_mask == 1)
        labels[~masked] = -100
        decider = self._rng.random(input_ids.shape)
        mask_id = self.tokenizer.mask_token_id
        replace = masked & (decider < 0.8)
        if mask_id is not None:
            input_ids[replace] = mask_id
        randomize = masked & (decider >= 0.8) & (decider < 0.9)
        input_ids[randomize] = self._rng.integers(0, self.tokenizer.vocab_size, randomize.sum())
        return {"input_ids": input_ids, "attention_mask": attention_mask, "labels": labels}
