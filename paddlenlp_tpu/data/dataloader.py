"""Host-side data loading.

Counterpart of ``paddlenlp/data/dist_dataloader.py`` + ``utils/batch_sampler.py``.
The reference loads data on dataset-replica rank 0 and **broadcasts** batches over
mp/pp comm groups (dist_dataloader.py:135-205). Under a single-controller JAX
program there is nothing to broadcast: the host assembles the global batch and
``device_put`` shards it onto the mesh's data axes. On multi-host, each process
feeds its addressable shard (``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

__all__ = ["DataLoader", "DistributedBatchSampler"]


class DistributedBatchSampler:
    """Deterministic shuffled batch sampler with ``consumed_samples`` fast-forward
    for resume (reference utils/batch_sampler.py:22,119-145)."""

    def __init__(
        self,
        dataset_len: int,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        consumed_samples: int = 0,
        num_shards: int = 1,
        shard_id: int = 0,
        shard_span: int = 1,
    ):
        """``batch_size`` is the GLOBAL batch, split into ``num_shards`` row
        groups (the mesh's data-shard groups, dp x fsdp); this sampler yields the
        contiguous slice covering groups ``[shard_id, shard_id + shard_span)`` —
        the multihost replacement for the reference's broadcast dataloader
        (dist_dataloader.py:41): every process loads exactly the rows its
        addressable devices will hold (identical rows on processes that share a
        data shard, e.g. tp spanning hosts). A final partial batch is padded by
        wrapping to the epoch start (reference DistributedBatchSampler
        complete-the-batch semantics) so every shard stays consistent."""
        if batch_size % max(num_shards, 1) != 0:
            raise ValueError(f"global batch {batch_size} not divisible by {num_shards} data shards")
        self.dataset_len = dataset_len
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        self.consumed_samples = consumed_samples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shard_span = shard_span
        self.filler_rows: List[int] = []  # local rows that are wrap-pad duplicates

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.dataset_len // self.batch_size
        return (self.dataset_len + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[List[int]]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        # consumed_samples is a ONE-TIME fast-forward for the resumed epoch; later
        # epochs iterate in full.
        start = self.consumed_samples % self.dataset_len if self.consumed_samples else 0
        self.consumed_samples = 0
        order = order[start:]
        n = len(order)
        end = n - n % self.batch_size if self.drop_last else n
        local = self.batch_size // self.num_shards
        for i in range(0, end, self.batch_size):
            batch = order[i : i + self.batch_size]
            self.filler_rows = []
            if len(batch) < self.batch_size and self.num_shards > 1:
                # pad the final partial batch by wrapping so every shard slices
                # a consistent full-size batch (duplicates, not drops); record
                # which LOCAL rows are filler so the loader can mask their labels
                n_real = len(batch)
                pad = np.resize(order, self.batch_size - n_real)
                batch = np.concatenate([batch, pad])
                lo, hi = self.shard_id * local, (self.shard_id + self.shard_span) * local
                self.filler_rows = [j - lo for j in range(max(n_real, lo), hi)]
            if self.num_shards > 1:
                batch = batch[self.shard_id * local : (self.shard_id + self.shard_span) * local]
            yield batch.tolist()


class DataLoader:
    """Minimal map-style loader: sampler + collate into numpy batches."""

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Optional[Callable] = None,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        sampler: Optional[DistributedBatchSampler] = None,
        num_shards: int = 1,
        shard_id: int = 0,
        shard_span: int = 1,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _stack_collate
        if sampler is None and _has_len(dataset):
            sampler = DistributedBatchSampler(
                len(dataset), batch_size, shuffle=shuffle, drop_last=drop_last, seed=seed,
                num_shards=num_shards, shard_id=shard_id, shard_span=shard_span,
            )
        elif sampler is None and num_shards > 1:
            raise ValueError(
                "iterable (length-less) datasets are not shardable across processes; "
                "pre-shard the stream per host or use a map-style dataset"
            )
        self.batch_sampler = sampler

    def set_epoch(self, epoch: int):
        if self.batch_sampler is not None:
            self.batch_sampler.set_epoch(epoch)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("iterable dataset has no length")
        return len(self.batch_sampler)

    def __iter__(self):
        if self.batch_sampler is not None:
            for idx_batch in self.batch_sampler:
                batch = self.collate_fn([self.dataset[i] for i in idx_batch])
                filler = getattr(self.batch_sampler, "filler_rows", [])
                if filler and isinstance(batch, dict) and "labels" in batch:
                    # wrap-padded duplicate rows must not count toward eval
                    # loss/perplexity — mask them like single-host filler
                    labels = np.array(batch["labels"], copy=True)
                    labels[filler] = -100
                    batch["labels"] = labels
                yield batch
        else:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []


def _stack_collate(features: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    return {k: np.stack([np.asarray(f[k]) for f in features]) for k in features[0]}


def _has_len(x) -> bool:
    try:
        len(x)
        return True
    except TypeError:
        return False
