"""Host-side data loading.

Counterpart of ``paddlenlp/data/dist_dataloader.py`` + ``utils/batch_sampler.py``.
The reference loads data on dataset-replica rank 0 and **broadcasts** batches over
mp/pp comm groups (dist_dataloader.py:135-205). Under a single-controller JAX
program there is nothing to broadcast: the host assembles the global batch and
``device_put`` shards it onto the mesh's data axes. On multi-host, each process
feeds its addressable shard (``jax.make_array_from_process_local_data``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np

__all__ = ["DataLoader", "DistributedBatchSampler"]


class DistributedBatchSampler:
    """Deterministic shuffled batch sampler with ``consumed_samples`` fast-forward
    for resume (reference utils/batch_sampler.py:22,119-145)."""

    def __init__(
        self,
        dataset_len: int,
        batch_size: int,
        shuffle: bool = True,
        drop_last: bool = True,
        seed: int = 0,
        consumed_samples: int = 0,
    ):
        self.dataset_len = dataset_len
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        self.consumed_samples = consumed_samples

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.dataset_len // self.batch_size
        return (self.dataset_len + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[List[int]]:
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            order = rng.permutation(self.dataset_len)
        else:
            order = np.arange(self.dataset_len)
        # consumed_samples is a ONE-TIME fast-forward for the resumed epoch; later
        # epochs iterate in full.
        start = self.consumed_samples % self.dataset_len if self.consumed_samples else 0
        self.consumed_samples = 0
        order = order[start:]
        n = len(order)
        end = n - n % self.batch_size if self.drop_last else n
        for i in range(0, end, self.batch_size):
            yield order[i : i + self.batch_size].tolist()


class DataLoader:
    """Minimal map-style loader: sampler + collate into numpy batches."""

    def __init__(
        self,
        dataset,
        batch_size: int,
        collate_fn: Optional[Callable] = None,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        sampler: Optional[DistributedBatchSampler] = None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _stack_collate
        if sampler is None and _has_len(dataset):
            sampler = DistributedBatchSampler(
                len(dataset), batch_size, shuffle=shuffle, drop_last=drop_last, seed=seed
            )
        self.batch_sampler = sampler

    def set_epoch(self, epoch: int):
        if self.batch_sampler is not None:
            self.batch_sampler.set_epoch(epoch)

    def __len__(self):
        if self.batch_sampler is None:
            raise TypeError("iterable dataset has no length")
        return len(self.batch_sampler)

    def __iter__(self):
        if self.batch_sampler is not None:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])
        else:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield self.collate_fn(buf)
                    buf = []


def _stack_collate(features: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    return {k: np.stack([np.asarray(f[k]) for f in features]) for k in features[0]}


def _has_len(x) -> bool:
    try:
        len(x)
        return True
    except TypeError:
        return False
