"""Memory-mapped token dataset: ``.bin`` (token stream) + ``.idx`` (index).

Counterpart of ``paddlenlp/data/indexed_dataset.py`` (mmap binary format,
``make_dataset`` :56). Layout (little-endian):

``.idx``: magic ``PDNLPTPU`` | u64 version | u8 dtype_code | u64 n_seqs | u64 n_docs
          | i32 sizes[n_seqs] | i64 pointers[n_seqs] | i64 doc_idx[n_docs+1]
``.bin``: concatenated token arrays.

Reads are ``np.memmap``-backed: only touched pages hit disk — the property the
reference's format exists for (pretraining corpora >> RAM).
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional

import numpy as np

__all__ = ["MMapIndexedDataset", "MMapIndexedDatasetBuilder", "make_dataset", "data_file_path", "index_file_path"]

_MAGIC = b"PDNLPTPU"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    def __init__(self, out_prefix: str, dtype=np.uint16):
        self._prefix = out_prefix
        self._dtype = np.dtype(dtype)
        os.makedirs(os.path.dirname(os.path.abspath(out_prefix)), exist_ok=True)
        self._bin = open(data_file_path(out_prefix), "wb")
        self._sizes: List[int] = []
        self._doc_idx: List[int] = [0]

    def add_item(self, tokens) -> None:
        arr = np.asarray(tokens, dtype=self._dtype)
        self._bin.write(arr.tobytes(order="C"))
        self._sizes.append(len(arr))

    def end_document(self) -> None:
        self._doc_idx.append(len(self._sizes))

    def add_document(self, tokens) -> None:
        self.add_item(tokens)
        self.end_document()

    def finalize(self) -> None:
        self._bin.close()
        sizes = np.asarray(self._sizes, dtype=np.int32)
        pointers = np.zeros(len(sizes), dtype=np.int64)
        np.cumsum(sizes[:-1] * self._dtype.itemsize, out=pointers[1:])
        doc_idx = np.asarray(self._doc_idx, dtype=np.int64)
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<Q", _VERSION))
            f.write(struct.pack("<B", _DTYPE_CODES[self._dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx) - 1))
            f.write(sizes.tobytes())
            f.write(pointers.tobytes())
            f.write(doc_idx.tobytes())


class MMapIndexedDataset:
    """Sequence-indexed view over the token stream; ``get(i, offset, length)``
    slices within a sequence without loading it fully."""

    def __init__(self, prefix: str):
        self._prefix = prefix
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(8)
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(prefix)}: bad magic {magic!r}")
            (version,) = struct.unpack("<Q", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            (dtype_code,) = struct.unpack("<B", f.read(1))
            (n_seqs,) = struct.unpack("<Q", f.read(8))
            (n_docs,) = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        self.dtype = np.dtype(_DTYPES[dtype_code])
        idx_map = np.memmap(index_file_path(prefix), mode="r", dtype=np.uint8, offset=offset)
        pos = 0
        self.sizes = idx_map[pos : pos + 4 * n_seqs].view(np.int32)
        pos += 4 * n_seqs
        self.pointers = idx_map[pos : pos + 8 * n_seqs].view(np.int64)
        pos += 8 * n_seqs
        self.doc_idx = idx_map[pos : pos + 8 * (n_docs + 1)].view(np.int64)
        self._bin = np.memmap(data_file_path(prefix), mode="r", dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.sizes)

    @property
    def n_docs(self) -> int:
        return len(self.doc_idx) - 1

    def get(self, idx: int, offset: int = 0, length: Optional[int] = None) -> np.ndarray:
        size = int(self.sizes[idx])
        if length is None:
            length = size - offset
        start = int(self.pointers[idx]) + offset * self.dtype.itemsize
        raw = self._bin[start : start + length * self.dtype.itemsize]
        return raw.view(self.dtype)

    def __getitem__(self, idx):
        return self.get(idx)


def make_dataset(prefix: str) -> MMapIndexedDataset:
    """Open a prebuilt dataset (reference indexed_dataset.py:56)."""
    return MMapIndexedDataset(prefix)
