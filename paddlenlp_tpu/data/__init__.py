from .dataloader import DataLoader, DistributedBatchSampler  # noqa: F401
