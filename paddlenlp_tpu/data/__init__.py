from .causal_dataset import BlendableDataset, GPTDataset, build_train_valid_test_datasets  # noqa: F401
from .data_collator import (  # noqa: F401
    DataCollatorForLanguageModeling,
    DataCollatorForSeq2Seq,
    DataCollatorWithPadding,
    default_data_collator,
)
from .dataloader import DataLoader, DistributedBatchSampler  # noqa: F401
from .indexed_dataset import MMapIndexedDataset, MMapIndexedDatasetBuilder, make_dataset  # noqa: F401
