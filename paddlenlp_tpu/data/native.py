"""ctypes bridge to the native data helpers (csrc/sample_idx.cpp).

The reference ships compiled dataset helpers for the index-building hot loop;
here a single C++ TU is compiled lazily with g++ (cached beside the source) and
loaded via ctypes — no pybind11 dependency. Every entry point has a NumPy
fallback so the package works without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..utils.log import logger

__all__ = ["build_sample_idx", "native_available"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_CSRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "csrc")


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        src = os.path.join(_CSRC, "sample_idx.cpp")
        so = os.path.join(_CSRC, "libpdnlp_data.so")
        try:
            if not os.path.isfile(so) or os.path.getmtime(so) < os.path.getmtime(src):
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", so, src],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(so)
            lib.build_sample_idx.restype = ctypes.c_int
            lib.build_sample_idx.argtypes = [
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.build_blending_indices.restype = None
            lib.build_blending_indices.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64),
            ]
            _lib = lib
        except Exception as e:
            logger.warning(f"native data helpers unavailable ({e}); using numpy fallback")
            _lib = None
        return _lib


def native_available() -> bool:
    return _load() is not None


def build_sample_idx(sizes: np.ndarray, doc_idx: np.ndarray, seq_length: int, n_samples: int) -> np.ndarray:
    """[(doc_pos, doc_offset)] per sample boundary; shape [n_samples+1, 2]."""
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    doc_idx = np.ascontiguousarray(doc_idx, dtype=np.int64)
    out = np.zeros((n_samples + 1, 2), dtype=np.int64)
    lib = _load()
    if lib is not None:
        rc = lib.build_sample_idx(
            sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            doc_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(doc_idx),
            seq_length,
            n_samples,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc != 0:
            raise ValueError("corpus exhausted before n_samples; increase epochs in doc_idx")
        return out
    return _build_sample_idx_np(sizes, doc_idx, seq_length, n_samples)


def build_blending_indices(weights: np.ndarray, n_samples: int):
    """Largest-deficit greedy blend assignment -> (dataset_index i32, sample_index i64)."""
    weights = np.ascontiguousarray(weights, dtype=np.float64)
    dataset_index = np.zeros(n_samples, dtype=np.int32)
    dataset_sample_index = np.zeros(n_samples, dtype=np.int64)
    lib = _load()
    if lib is not None:
        lib.build_blending_indices(
            weights.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            len(weights),
            n_samples,
            dataset_index.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dataset_sample_index.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return dataset_index, dataset_sample_index
    counts = np.zeros(len(weights))
    for i in range(n_samples):
        d = int(np.argmax((i + 1) * weights - counts))
        dataset_index[i] = d
        dataset_sample_index[i] = counts[d]
        counts[d] += 1
    return dataset_index, dataset_sample_index


def _build_sample_idx_np(sizes, doc_idx, seq_length, n_samples):
    out = np.zeros((n_samples + 1, 2), dtype=np.int64)
    doc_pos, doc_offset = 0, 0
    for i in range(1, n_samples + 1):
        remaining = seq_length + 1
        while remaining > 0:
            if doc_pos >= len(doc_idx):
                raise ValueError("corpus exhausted before n_samples; increase epochs in doc_idx")
            doc_len = int(sizes[doc_idx[doc_pos]]) - doc_offset
            if doc_len > remaining:
                doc_offset += remaining
                remaining = 0
            else:
                remaining -= doc_len
                doc_pos += 1
                doc_offset = 0
        out[i] = (doc_pos, doc_offset)
    return out
