"""paddlenlp_tpu: a TPU-native large-model development suite.

Brand-new JAX/XLA/Pallas/pjit implementation of the capabilities of
PaddlePaddle/PaddleNLP (see SURVEY.md for the blueprint).
"""

__version__ = "0.1.0.dev0"

from . import data, datasets, generation, metrics, ops, parallel, peft, quantization  # noqa: F401
from . import dataaug, embeddings, layers, losses, seq2vec, server, serving  # noqa: F401
from . import taskflow, trainer, transformers, trl, utils  # noqa: F401
