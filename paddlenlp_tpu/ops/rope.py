"""Rotary position embedding + scaling strategies.

Counterpart of the reference's rotary classes (``llama/modeling.py:402-556``:
base/NTK/dynamic-NTK/linear/Llama3) and ``long_sequence_strategies/embedding_strategies.py``.
Tables are computed in fp32 (TPU bf16 mantissa is too short for large positions)
and applied with the half-rotate convention used by LLaMA-family HF checkpoints.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["rope_frequencies", "apply_rotary_pos_emb", "rotate_half", "apply_rotary_partial_interleaved"]


def apply_rotary_partial_interleaved(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k: jnp.ndarray,  # [B, T, n_kv, head_dim]
    position_ids: jnp.ndarray,  # [B, T] or [T]
    rotary_dim: int,
    base: float = 10000.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """ChatGLM2/GPT-J-style rotary: the FIRST ``rotary_dim`` dims rotate as
    interleaved (x_{2i}, x_{2i+1}) pairs; the remaining dims pass through."""
    pos = position_ids if position_ids.ndim == 2 else position_ids[None, :]
    inv = 1.0 / (base ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim))
    freqs = pos[..., None].astype(jnp.float32) * inv[None, None, :]  # [B, T, r/2]
    cos = jnp.cos(freqs)[:, :, None, :]
    sin = jnp.sin(freqs)[:, :, None, :]

    def rot(x):
        xr, rest = x[..., :rotary_dim], x[..., rotary_dim:]
        xr = xr.astype(jnp.float32).reshape(xr.shape[:-1] + (rotary_dim // 2, 2))
        x0, x1 = xr[..., 0], xr[..., 1]
        o = jnp.stack([x0 * cos - x1 * sin, x1 * cos + x0 * sin], axis=-1)
        return jnp.concatenate([o.reshape(o.shape[:-2] + (rotary_dim,)).astype(x.dtype), rest], axis=-1)

    return rot(q), rot(k)


def rope_frequencies(
    head_dim: int,
    base: float = 10000.0,
    scaling: Optional[dict] = None,
) -> np.ndarray:
    """inv_freq [head_dim//2], with optional rope_scaling dict (HF conventions)."""
    inv_freq = 1.0 / (base ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if not scaling:
        return inv_freq.astype(np.float32)
    rope_type = scaling.get("rope_type", scaling.get("type", "default"))
    factor = float(scaling.get("factor", 1.0))
    if rope_type == "linear":
        inv_freq = inv_freq / factor
    elif rope_type in ("ntk", "dynamic"):
        # static NTK-by-parts approximation of dynamic NTK at the scaled context
        base = base * (factor ** (head_dim / max(head_dim - 2, 1)))
        inv_freq = 1.0 / (base ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    elif rope_type == "llama3":
        low_factor = float(scaling.get("low_freq_factor", 1.0))
        high_factor = float(scaling.get("high_freq_factor", 4.0))
        orig_ctx = float(scaling.get("original_max_position_embeddings", 8192))
        wavelen = 2 * math.pi / inv_freq
        low_wl = orig_ctx / low_factor
        high_wl = orig_ctx / high_factor
        scaled = inv_freq / factor
        smooth = (orig_ctx / wavelen - low_factor) / max(high_factor - low_factor, 1e-6)
        smooth = np.clip(smooth, 0.0, 1.0)
        blended = (1 - smooth) * scaled + smooth * inv_freq
        inv_freq = np.where(wavelen > low_wl, scaled, np.where(wavelen < high_wl, inv_freq, blended))
    elif rope_type == "yarn":
        # YaRN interpolation (simplified NTK-by-parts with attention temperature folded out)
        orig_ctx = float(scaling.get("original_max_position_embeddings", 4096))
        beta_fast = float(scaling.get("beta_fast", 32.0))
        beta_slow = float(scaling.get("beta_slow", 1.0))

        def find_dim(n_rot):
            return (head_dim * math.log(orig_ctx / (n_rot * 2 * math.pi))) / (2 * math.log(base))

        low = max(math.floor(find_dim(beta_fast)), 0)
        high = min(math.ceil(find_dim(beta_slow)), head_dim // 2 - 1)
        ramp = np.clip((np.arange(head_dim // 2) - low) / max(high - low, 1), 0, 1)
        inv_freq = inv_freq / factor * ramp + inv_freq * (1 - ramp)
    return inv_freq.astype(np.float32)


def rope_tables(position_ids: jnp.ndarray, inv_freq: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin [B, T, head_dim] (half-dim tables tiled to full)."""
    freqs = position_ids[..., None].astype(jnp.float32) * inv_freq[None, None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rotary_pos_emb(
    q: jnp.ndarray,  # [B, T, n_heads, head_dim]
    k: jnp.ndarray,  # [B, T, n_kv, head_dim]
    cos: jnp.ndarray,  # [B, T, head_dim]
    sin: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    dtype = q.dtype
    q32, k32 = q.astype(jnp.float32), k.astype(jnp.float32)
    q_out = q32 * cos + rotate_half(q32) * sin
    k_out = k32 * cos + rotate_half(k32) * sin
    return q_out.astype(dtype), k_out.astype(dtype)
