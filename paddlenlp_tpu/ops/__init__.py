from .cross_entropy import causal_lm_loss, cross_entropy_with_ignore  # noqa: F401
from .flash_attention import dot_product_attention, make_causal_mask, make_segment_mask  # noqa: F401
from .rope import apply_rotary_pos_emb, rope_frequencies  # noqa: F401
