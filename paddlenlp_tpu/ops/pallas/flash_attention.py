"""Pallas TPU flash attention (causal, GQA-aware), forward AND backward.

Counterpart of the reference's attention custom ops (csrc/gpu/append_attention.cu,
FlashAttention-2 dispatch in llama/fusion_ops.py:147, flash_attn_bwd.cc) and of
FlashMask packed-batch semantics (fusion_ops.py:223-238) via ``segment_ids``:
an O(T)-memory fused attention kernel family tiled for the MXU.

Structure (classic flash-attention-2 schedule):
- forward: grid = (batch*heads, T/block_q, S/block_kv); the kv axis is innermost
  and sequential ("arbitrary"), carrying VMEM scratch accumulators (m, l, acc);
  emits the per-row logsumexp L = m + log(l) as a residual for the backward;
- fully-invisible blocks are skipped under causal/window masking (@pl.when);
- GQA maps query-head blocks onto shared kv heads in the BlockSpec index maps —
  no materialized repeat;
- backward: two kernels re-streaming K/V — dq (kv innermost) and dk/dv
  (q innermost), with p recomputed from the saved logsumexp and
  delta = rowsum(dO*O) precomputed by XLA. dk/dv accumulate per KV head INSIDE
  the kernel: the grid batch axis is B*K and the innermost sequential axis
  walks (query-head-in-group, q-block) pairs, so for an N/K = g GQA model the
  dk/dv output traffic and K/V re-streaming drop by g× versus the per-query-head
  scheme (outputs were [B*N, S, H] + an XLA group-sum pass; now [B*K, S, H]);
- ``segment_ids`` restricts attention to same-segment tokens (ZeroPadding packed
  batches); ``window`` adds the mistral sliding-window lower bound.

Off-TPU (tests), the kernels run in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams

__all__ = ["flash_attention"]

NEG_INF = -1e30


def _visible(s_shape, q_start, k_start, causal, window, q_len, kv_len, seg_q, seg_k):
    """Element-level visibility mask for one [block_q, block_kv] tile.

    ``seg_q`` is [block_q, 1] and ``seg_k`` is [1, block_kv] (the trailing/leading
    unit dims come from the TPU-tileable [B, T, 1] / [B, 1, S] segment layouts).
    """
    rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s_shape, 0)
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s_shape, 1)
    valid = (cols < kv_len) & (rows < q_len)
    if causal:
        valid &= cols <= rows
    if window is not None:
        valid &= cols > rows - window
    if seg_q is not None:
        valid &= seg_q == seg_k
    return valid


def _zero_oob(x, start, limit):
    """Zero rows past ``limit`` (Pallas pads partial edge blocks with garbage —
    even p=0 coefficients turn garbage into NaN via 0*NaN)."""
    idx = start + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(idx < limit, x, 0.0)


def _block_runs(q_start, k_start, block_q, block_kv, causal, window):
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1
    if window is not None:
        run = jnp.logical_and(run, k_start + block_kv - 1 > q_start - window) if causal else run
    return run


# ---------------------------------------------------------------- forward
def _fa_kernel(q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref,
               m_scratch, l_scratch, acc_scratch, *,
               scale, block_q, block_kv, causal, window, q_len, kv_len, use_segments):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = ki * block_kv
    run = _block_runs(q_start, k_start, block_q, block_kv, causal, window)

    @pl.when(run)
    def _compute():
        q = _zero_oob(q_ref[0].astype(jnp.float32), q_start, q_len)
        k = _zero_oob(k_ref[0].astype(jnp.float32), k_start, kv_len)
        v = _zero_oob(v_ref[0].astype(jnp.float32), k_start, kv_len)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale
        seg_q = sq_ref[0] if use_segments else None
        seg_k = sk_ref[0] if use_segments else None
        valid = _visible(s.shape, q_start, k_start, causal, window, q_len, kv_len, seg_q, seg_k)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scratch[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(valid, p, 0.0)  # exp(NEG-NEG)=1 on fully-masked rows
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scratch[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot(p, v)
        m_scratch[...] = m_new
        l_scratch[...] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_scratch[...], 1e-37)
        o_ref[0] = (acc_scratch[...] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scratch[...] + jnp.log(l)  # [block_q, 1]


def _fold(x):  # [B, T, N, H] -> [B*N, T, H]
    B, T, N, H = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * N, T, H)


def _flash_fwd(q, k, v, segments, scale, causal, window, block_q, block_kv, interpret):
    B, T, N, H = q.shape
    if causal and T != k.shape[1]:
        raise ValueError(
            f"causal flash_attention requires T == S (got T={T}, S={k.shape[1]}); "
            "cross-length causal (KV cache) goes through the XLA dispatcher path"
        )
    if window is not None and not causal:
        raise ValueError("sliding window requires causal=True (matches the XLA dispatcher)")
    S, K = k.shape[1], k.shape[2]
    group = N // K
    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    use_seg = segments is not None
    seg = segments if use_seg else jnp.zeros((B, T), jnp.int32)
    # TPU tiling requires the last two block dims divisible by (8, 128) or equal
    # to the array dims — per-row 1D data rides a trailing/middle unit dim.
    seg_q3 = seg[:, :, None]  # [B, T, 1] -> block (1, block_q, 1)
    seg_k3 = seg[:, None, :]  # [B, 1, S] -> block (1, 1, block_kv)
    block_q = min(block_q, T)
    block_kv = min(block_kv, S)
    grid = (B * N, pl.cdiv(T, block_q), pl.cdiv(S, block_kv))

    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=block_q, block_kv=block_kv,
        causal=causal, window=window, q_len=T, kv_len=S, use_segments=use_seg,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bn, qi, ki, g=group: (bn // g, ki, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bn, qi, ki, g=group: (bn // g, ki, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bn, qi, ki, n=N: (bn // n, qi, 0)),
            pl.BlockSpec((1, 1, block_kv), lambda bn, qi, ki, n=N: (bn // n, 0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bn, qi, ki: (bn, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * N, T, H), q.dtype),
            jax.ShapeDtypeStruct((B * N, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # m
            pltpu.VMEM((block_q, 1), jnp.float32),  # l
            pltpu.VMEM((block_q, H), jnp.float32),  # acc
        ],
        compiler_params=CompilerParams(dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf, seg_q3, seg_k3)
    return out.reshape(B, N, T, H).transpose(0, 2, 1, 3), lse[..., 0]


# ---------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
                   dq_ref, dq_scratch, *,
                   scale, block_q, block_kv, causal, window, q_len, kv_len, use_segments):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scratch[...] = jnp.zeros_like(dq_scratch)

    q_start = qi * block_q
    k_start = ki * block_kv
    run = _block_runs(q_start, k_start, block_q, block_kv, causal, window)

    @pl.when(run)
    def _compute():
        q = _zero_oob(q_ref[0].astype(jnp.float32), q_start, q_len)
        k = _zero_oob(k_ref[0].astype(jnp.float32), k_start, kv_len)
        v = _zero_oob(v_ref[0].astype(jnp.float32), k_start, kv_len)
        do = _zero_oob(do_ref[0].astype(jnp.float32), q_start, q_len)
        lse = lse_ref[0]  # [block_q, 1]
        # delta rows past q_len are Pallas edge-block garbage; p=0 there cannot
        # save ds (0 * NaN = NaN), and dkv's column reduction would spread it
        row_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        delta = jnp.where(row_idx < q_len, delta_ref[0], 0.0)  # [block_q, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale
        seg_q = sq_ref[0] if use_segments else None
        seg_k = sk_ref[0] if use_segments else None
        valid = _visible(s.shape, q_start, k_start, causal, window, q_len, kv_len, seg_q, seg_k)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))  # [bq, bkv]
        ds = p * (dp - delta) * scale
        dq_scratch[...] += jax.lax.dot(ds, k)

    @pl.when(ki == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_scratch[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref,
                    dk_ref, dv_ref, dk_scratch, dv_scratch, *,
                    scale, block_q, block_kv, causal, window, q_len, kv_len, use_segments,
                    n_q):
    ki = pl.program_id(1)
    j = pl.program_id(2)  # walks (query-head-in-group, q-block) pairs
    n_j = pl.num_programs(2)
    qi = j % n_q

    @pl.when(j == 0)
    def _init():
        dk_scratch[...] = jnp.zeros_like(dk_scratch)
        dv_scratch[...] = jnp.zeros_like(dv_scratch)

    q_start = qi * block_q
    k_start = ki * block_kv
    run = _block_runs(q_start, k_start, block_q, block_kv, causal, window)

    @pl.when(run)
    def _compute():
        q = _zero_oob(q_ref[0].astype(jnp.float32), q_start, q_len)
        k = _zero_oob(k_ref[0].astype(jnp.float32), k_start, kv_len)
        v = _zero_oob(v_ref[0].astype(jnp.float32), k_start, kv_len)
        do = _zero_oob(do_ref[0].astype(jnp.float32), q_start, q_len)
        lse = lse_ref[0]  # [block_q, 1]
        # delta rows past q_len are Pallas edge-block garbage; p=0 there cannot
        # save ds (0 * NaN = NaN), and dkv's column reduction would spread it
        row_idx = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        delta = jnp.where(row_idx < q_len, delta_ref[0], 0.0)  # [block_q, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale
        seg_q = sq_ref[0] if use_segments else None
        seg_k = sk_ref[0] if use_segments else None
        valid = _visible(s.shape, q_start, k_start, causal, window, q_len, kv_len, seg_q, seg_k)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)  # [bq, bkv]
        dv_scratch[...] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())))  # p^T @ do
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())))
        ds = p * (dp - delta) * scale
        dk_scratch[...] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())))  # ds^T @ q

    @pl.when(j == n_j - 1)
    def _finalize():
        dk_ref[0] = dk_scratch[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, segments, out, lse, g, scale, causal, window, block_q, block_kv, interpret):
    B, T, N, H = q.shape
    S, K = k.shape[1], k.shape[2]
    group = N // K
    qf, kf, vf, dof = _fold(q), _fold(k), _fold(v), _fold(g)
    of = _fold(out)
    # [B*N, T, 1]: trailing unit dim keeps the block TPU-tileable (see _flash_fwd)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1, keepdims=True)
    lse3 = lse[..., None]
    use_seg = segments is not None
    seg = segments if use_seg else jnp.zeros((B, T), jnp.int32)
    seg_q3 = seg[:, :, None]  # [B, T, 1]
    seg_k3 = seg[:, None, :]  # [B, 1, S]
    block_q = min(block_q, T)
    block_kv = min(block_kv, S)
    n_q, n_k = pl.cdiv(T, block_q), pl.cdiv(S, block_kv)

    common = dict(scale=scale, block_q=block_q, block_kv=block_kv, causal=causal,
                  window=window, q_len=T, kv_len=S, use_segments=use_seg)
    params = CompilerParams(dimension_semantics=("parallel", "parallel", "arbitrary"))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(B * N, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bn, qi, ki, g_=group: (bn // g_, ki, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bn, qi, ki, g_=group: (bn // g_, ki, 0)),
            pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bn, qi, ki, n=N: (bn // n, qi, 0)),
            pl.BlockSpec((1, 1, block_kv), lambda bn, qi, ki, n=N: (bn // n, 0, ki)),
        ],
        out_specs=pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * N, T, H), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, H), jnp.float32)],
        compiler_params=params,
        interpret=interpret,
    )(qf, kf, vf, dof, lse3, delta, seg_q3, seg_k3)

    # dk/dv: grid batch axis is the B*K kv heads; the sequential axis walks the
    # group*n_q (query-head-in-group, q-block) pairs so dk/dv for a kv head
    # accumulate in VMEM across its whole query group (no outside group-sum).
    qhead = lambda bk, j, g_=group, nq=n_q: bk * g_ + j // nq
    dk_p, dv_p = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common, n_q=n_q),
        grid=(B * K, n_k, group * n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, H), lambda bk, ki, j, nq=n_q: (qhead(bk, j), j % nq, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bk, ki, j: (bk, ki, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bk, ki, j: (bk, ki, 0)),
            pl.BlockSpec((1, block_q, H), lambda bk, ki, j, nq=n_q: (qhead(bk, j), j % nq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bk, ki, j, nq=n_q: (qhead(bk, j), j % nq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bk, ki, j, nq=n_q: (qhead(bk, j), j % nq, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bk, ki, j, kk=K, nq=n_q: (bk // kk, j % nq, 0)),
            pl.BlockSpec((1, 1, block_kv), lambda bk, ki, j, kk=K: (bk // kk, 0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, H), lambda bk, ki, j: (bk, ki, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bk, ki, j: (bk, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * K, S, H), jnp.float32),
            jax.ShapeDtypeStruct((B * K, S, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, H), jnp.float32),
            pltpu.VMEM((block_kv, H), jnp.float32),
        ],
        compiler_params=params,
        interpret=interpret,
    )(qf, kf, vf, dof, lse3, delta, seg_q3, seg_k3)

    dq = dq.reshape(B, N, T, H).transpose(0, 2, 1, 3)
    dk = dk_p.reshape(B, K, S, H).transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv_p.reshape(B, K, S, H).transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------- public api
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def flash_attention(
    q: jnp.ndarray,  # [B, T, N, H]
    k: jnp.ndarray,  # [B, S, K, H]
    v: jnp.ndarray,
    segment_ids: Optional[jnp.ndarray] = None,  # [B, T] packed-batch segments
    scale: Optional[float] = None,
    causal: bool = True,
    window: Optional[int] = None,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    out, _ = _flash_fwd(q, k, v, segment_ids, scale, causal, window, block_q, block_kv, interpret)
    return out


def _fwd(q, k, v, segment_ids, scale, causal, window, block_q, block_kv, interpret):
    scale_v = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    out, lse = _flash_fwd(q, k, v, segment_ids, scale_v, causal, window, block_q, block_kv, interpret)
    return out, (q, k, v, segment_ids, out, lse)


def _bwd(scale, causal, window, block_q, block_kv, interpret, residuals, g):
    q, k, v, segment_ids, out, lse = residuals
    scale_v = scale if scale is not None else q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    dq, dk, dv = _flash_bwd(q, k, v, segment_ids, out, lse, g,
                            scale_v, causal, window, block_q, block_kv, interpret)
    dseg = None if segment_ids is None else np.zeros(segment_ids.shape, jax.dtypes.float0)
    return dq, dk, dv, dseg


flash_attention.defvjp(_fwd, _bwd)
