"""Pallas TPU flash attention (causal, GQA-aware).

Counterpart of the reference's attention custom ops (csrc/gpu/append_attention.cu
and FlashAttention-2 dispatch in llama/fusion_ops.py:147): an O(T) -memory fused
attention kernel tiled for the MXU, written in Pallas.

Structure (classic flash-attention-2 schedule):
- grid = (batch*heads, T/block_q, S/block_kv); the kv axis is innermost and
  sequential ("arbitrary"), carrying VMEM scratch accumulators (m, l, acc);
- fully-future blocks are skipped under causal masking (@pl.when);
- GQA maps query-head blocks onto shared kv heads in the BlockSpec index maps —
  no materialized repeat;
- backward: custom_vjp recomputes through the XLA math-attention path (a Pallas
  bwd kernel is the planned follow-up); forward-only consumers (inference)
  never pay for it.

Off-TPU (tests), the kernel runs in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention"]

NEG_INF = -1e30


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch, acc_scratch, *, scale, block_q, block_kv,
               causal, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    q_start = qi * block_q
    k_start = ki * block_kv

    run = True
    if causal:
        run = k_start <= q_start + block_q - 1  # any col in this kv block can be visible

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [block_q, H]
        k = k_ref[0].astype(jnp.float32)  # [block_kv, H]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale  # [block_q, block_kv]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < kv_len  # mask block padding when S % block_kv != 0
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (cols <= rows)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_scratch[...]  # [block_q, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scratch[...] + jnp.sum(p, axis=-1, keepdims=True)
        # zero padded V rows: p is 0 there, but 0 * garbage (block padding) = NaN
        v_row_valid = (k_start + jax.lax.broadcasted_iota(jnp.int32, v.shape, 0)) < kv_len
        v = jnp.where(v_row_valid, v, 0.0)
        acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot(p, v)
        m_scratch[...] = m_new
        l_scratch[...] = l_new

    @pl.when(ki == n_k - 1)
    def _finalize():
        o_ref[0] = (acc_scratch[...] / jnp.maximum(l_scratch[...], 1e-37)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q, block_kv, interpret):
    B, T, N, H = q.shape
    S, K = k.shape[1], k.shape[2]
    group = N // K
    # fold (batch, heads): q' [B*N, T, H]; k'/v' [B*K, S, H]
    qf = q.transpose(0, 2, 1, 3).reshape(B * N, T, H)
    kf = k.transpose(0, 2, 1, 3).reshape(B * K, S, H)
    vf = v.transpose(0, 2, 1, 3).reshape(B * K, S, H)
    block_q = min(block_q, T)
    block_kv = min(block_kv, S)
    grid = (B * N, pl.cdiv(T, block_q), pl.cdiv(S, block_kv))

    kernel = functools.partial(
        _fa_kernel, scale=scale, block_q=block_q, block_kv=block_kv, causal=causal, kv_len=S
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bn, qi, ki, g=group: (bn // g, ki, 0)),
            pl.BlockSpec((1, block_kv, H), lambda bn, qi, ki, g=group: (bn // g, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, H), lambda bn, qi, ki: (bn, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * N, T, H), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # m
            pltpu.VMEM((block_q, 1), jnp.float32),  # l
            pltpu.VMEM((block_q, H), jnp.float32),  # acc
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, N, T, H).transpose(0, 2, 1, 3)


def _math_reference(q, k, v, scale, causal):
    from ..flash_attention import _math_attention, make_causal_mask

    B, T = q.shape[:2]
    S = k.shape[1]
    mask = jnp.broadcast_to(make_causal_mask(T, S), (B, 1, T, S)) if causal else None
    return _math_attention(q, k, v, mask, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jnp.ndarray,  # [B, T, N, H]
    k: jnp.ndarray,  # [B, S, K, H]
    v: jnp.ndarray,
    scale: Optional[float] = None,
    causal: bool = True,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if causal and q.shape[1] != k.shape[1]:
        raise ValueError(
            f"causal flash_attention requires T == S (got T={q.shape[1]}, S={k.shape[1]}); "
            "cross-length causal (KV cache) goes through the XLA dispatcher path"
        )
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    return _flash_fwd(q, k, v, scale, causal, block_q, block_kv, interpret)


def _fwd(q, k, v, scale, causal, block_q, block_kv, interpret):
    out = flash_attention(q, k, v, scale, causal, block_q, block_kv, interpret)
    return out, (q, k, v)


def _bwd(scale, causal, block_q, block_kv, interpret, residuals, g):
    q, k, v = residuals
    scale_v = scale if scale is not None else q.shape[-1] ** -0.5
    _, vjp = jax.vjp(lambda q, k, v: _math_reference(q, k, v, scale_v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
