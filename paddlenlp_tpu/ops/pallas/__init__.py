"""Pallas TPU kernels (flash attention, paged/ragged paged attention).

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``; the shim
below resolves whichever this jax provides so the kernels import (and their
tests run) on both sides of the rename.
"""

from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams", None) or _pltpu.TPUCompilerParams

__all__ = ["CompilerParams"]
