"""Pallas TPU ragged paged-attention kernel (mixed prefill-chunk + decode).

Counterpart of the reference's block-attention ops
(``csrc/gpu/append_attention.cu:801`` + ``csrc/gpu/append_attn/*.cuh``) in the
shape the *Ragged Paged Attention* TPU kernel paper describes: ONE launch
computes attention for a ragged batch where each sequence contributes a
different number of new query tokens — a prefill chunk (tens to hundreds of
tokens picking up at ``q_start`` = its already-prefilled length), a decode step
(one token), or nothing (padded slot) — against its own paged KV, walking each
sequence's block table and streaming the addressed KV blocks HBM->VMEM with an
online-softmax accumulator. No ``[B, max_blocks*bs, K, H]`` gathered copy of
the cache ever materializes (the XLA fallback's cost).

Design:
- grid = (B, K, max_blocks); the block axis is innermost and sequential,
  carrying (m, l, acc) VMEM scratch per (T*group, H) query tile;
- the block table plus per-sequence ``q_start``/``q_lens`` ride scalar
  prefetch (``pltpu.PrefetchScalarGridSpec``): the KV BlockSpec index map
  reads ``tables[b, j]`` to aim the DMA at the right pool block — the table
  gather IS the address computation, exactly like the CUDA kernel's block
  walk;
- causal masking is per query ROW: query token t of sequence b sits at
  absolute position ``q_start[b] + t`` and sees kv positions ``<= q_start+t``
  — correct across chunk boundaries (a chunk's first token attends over the
  whole prefilled span, its last over prefilled+chunk-1);
- rows past ``q_lens[b]`` (padding) and fully-masked rows produce exact zeros
  (their softmax denominator stays 0); blocks past the highest live query
  position are skipped entirely (@pl.when);
- GQA: queries fold to [B, K, T*group, H]; each grid cell attends its kv
  head's whole query group for every chunk token at once;
- ``q_lens = 1`` everywhere reduces to the classic paged decode kernel —
  :func:`paged_decode_attention` is that wrapper, kept as the stable
  decode-only API (``_layer`` now always dispatches the ragged kernel; the
  wrapper has no library call sites, only external/test callers).

Off-TPU (tests), the kernel runs in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams

__all__ = ["paged_decode_attention", "ragged_paged_attention"]

NEG_INF = -1e30


def _kernel(tables_ref, start_ref, len_ref, q_ref, k_ref, v_ref, *rest,
            bs, scale, use_kv_scale, group):
    if use_kv_scale:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    start = start_ref[b]
    qlen = len_ref[b]
    # highest live query position: blocks past it contribute nothing to any row
    hi = start + qlen - 1

    @pl.when((qlen > 0) & (j * bs <= hi))
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [T*group, H]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, H]
        v = v_ref[0, 0].astype(jnp.float32)
        if use_kv_scale:  # int8/fp8 cache: dequant the streamed block in VMEM
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale  # [T*group, bs]
        kv_pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        t = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group  # query token idx
        valid = (kv_pos <= start + t) & (t < qlen)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_s[...] = alpha * l_s[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot(p, v)
        m_s[...] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        # dead rows (t >= q_lens, or q_lens == 0) kept l == 0 -> exact zeros
        o_ref[0, 0] = (acc_s[...] / jnp.maximum(l_s[...], 1e-37)).astype(o_ref.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,  # [B, T, N, H] new-token queries (rows past q_lens ignored)
    pool_k: jnp.ndarray,  # [num_blocks, K, bs, H] (kv-head-major: TPU-tileable DMA)
    pool_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    q_start: jnp.ndarray,  # [B] absolute position of q[:, 0]
    q_lens: jnp.ndarray,  # [B] valid new tokens per sequence (0 = inactive row)
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,  # [num_blocks, K, bs, 1] quantized-pool scales
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """One-launch attention for a ragged mixed prefill/decode batch.

    Query token t of row b attends kv positions ``[0, q_start[b] + t]`` read
    through ``block_tables[b]`` — the KV for positions ``< q_start`` was
    written by earlier chunks/steps, the chunk's own KV by this step's scatter
    (ordered before the kernel by jit data dependence on the pool). Returns
    ``[B, T, N, H]`` with rows ``t >= q_lens[b]`` zeroed.
    """
    B, T, N, H = q.shape
    nb, K, bs, _ = pool_k.shape
    group = N // K
    max_blocks = block_tables.shape[1]
    scale = scale if scale is not None else H**-0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    use_kv_scale = k_scale is not None

    # [B, T, N, H] -> [B, K, T*group, H]: head n = kh*group + g, so T and group
    # interleave as rows (t, g) -> row t*group + g of kv head kh
    qf = q.reshape(B, T, K, group, H).transpose(0, 2, 1, 3, 4).reshape(B, K, T * group, H)
    kv_spec = pl.BlockSpec((1, 1, bs, H), lambda b, kh, j, t, s, l: (t[b, j], kh, 0, 0))
    sc_spec = pl.BlockSpec((1, 1, bs, 1), lambda b, kh, j, t, s, l: (t[b, j], kh, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, T * group, H), lambda b, kh, j, t, s, l: (b, kh, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [qf, pool_k, pool_v]
    if use_kv_scale:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, K, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, T * group, H), lambda b, kh, j, t, s, l: (b, kh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((T * group, 1), jnp.float32),  # m
            pltpu.VMEM((T * group, 1), jnp.float32),  # l
            pltpu.VMEM((T * group, H), jnp.float32),  # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, scale=scale, use_kv_scale=use_kv_scale,
                          group=group),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, K, T * group, H), q.dtype),
        compiler_params=CompilerParams(dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), q_start.astype(jnp.int32),
      q_lens.astype(jnp.int32), *operands)
    return out.reshape(B, K, T, group, H).transpose(0, 2, 1, 3, 4).reshape(B, T, N, H)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, N, H] one query token per sequence
    pool_k: jnp.ndarray,  # [num_blocks, K, bs, H]
    pool_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 (position of the current token)
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Decode-only wrapper: every sequence contributes exactly one query token
    at position ``context_lens[b]`` (the ragged kernel with ``q_lens = 1``)."""
    B = q.shape[0]
    out = ragged_paged_attention(
        q[:, None], pool_k, pool_v, block_tables,
        q_start=context_lens, q_lens=jnp.ones((B,), jnp.int32),
        scale=scale, interpret=interpret, k_scale=k_scale, v_scale=v_scale,
    )
    return out[:, 0]
