"""Pallas TPU paged-attention decode kernel.

Counterpart of the reference's block-attention decode op
(``csrc/gpu/append_attention.cu:801`` + ``csrc/gpu/append_attn/*.cuh``): one
fused kernel walks each sequence's block table, streams the addressed KV blocks
HBM->VMEM, and runs the online-softmax attention — no [B, max_blocks*bs, K, H]
gathered copy of the cache ever materializes (the XLA fallback's cost).

Design:
- grid = (B, K, max_blocks); the block axis is innermost and sequential,
  carrying (m, l, acc) VMEM scratch per (group, H) query tile;
- the block table and per-sequence context lengths ride scalar prefetch
  (``pltpu.PrefetchScalarGridSpec``): the KV BlockSpec index map reads
  ``tables[b, j]`` to aim the DMA at the right pool block — the table gather
  IS the address computation, exactly like the CUDA kernel's block walk;
- blocks past the context length are skipped (@pl.when), tail slots inside the
  last block are masked;
- GQA: queries fold to [B, K, group, H]; each grid cell attends its kv head's
  whole query group.

Off-TPU (tests), the kernel runs in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["paged_decode_attention"]

NEG_INF = -1e30


def _kernel(tables_ref, ctx_ref, q_ref, k_ref, v_ref, *rest, bs, scale, use_kv_scale):
    if use_kv_scale:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    j = pl.program_id(2)
    nj = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    ctx = ctx_ref[b]

    @pl.when(j * bs <= ctx)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [group, H]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, H]
        v = v_ref[0, 0].astype(jnp.float32)
        if use_kv_scale:  # int8/fp8 cache: dequant the streamed block in VMEM
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ()))) * scale  # [group, bs]
        pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = pos <= ctx
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_s[...] = alpha * l_s[...] + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot(p, v)
        m_s[...] = m_new

    @pl.when(j == nj - 1)
    def _finalize():
        o_ref[0, 0] = (acc_s[...] / jnp.maximum(l_s[...], 1e-37)).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, N, H] one query token per sequence
    pool_k: jnp.ndarray,  # [num_blocks, K, bs, H] (kv-head-major: TPU-tileable DMA)
    pool_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 (position of the current token)
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jnp.ndarray] = None,  # [num_blocks, K, bs, 1] quantized-pool scales
    v_scale: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    B, N, H = q.shape
    nb, K, bs, _ = pool_k.shape
    group = N // K
    max_blocks = block_tables.shape[1]
    scale = scale if scale is not None else H**-0.5
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu",)
    use_kv_scale = k_scale is not None

    qf = q.reshape(B, K, group, H)
    kv_spec = pl.BlockSpec((1, 1, bs, H), lambda b, kh, j, t, c: (t[b, j], kh, 0, 0))
    sc_spec = pl.BlockSpec((1, 1, bs, 1), lambda b, kh, j, t, c: (t[b, j], kh, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, group, H), lambda b, kh, j, t, c: (b, kh, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [qf, pool_k, pool_v]
    if use_kv_scale:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, K, max_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, group, H), lambda b, kh, j, t, c: (b, kh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),  # m
            pltpu.VMEM((group, 1), jnp.float32),  # l
            pltpu.VMEM((group, H), jnp.float32),  # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, scale=scale, use_kv_scale=use_kv_scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, K, group, H), q.dtype),
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32), *operands)
    return out.reshape(B, N, H)
