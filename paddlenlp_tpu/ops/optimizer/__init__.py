from .adamwdl import adamwdl, layerwise_lr_decay_mask  # noqa: F401
from .ema import ExponentialMovingAverage, ema  # noqa: F401
