"""Exponential moving average of params (reference:
``paddlenlp/ops/optimizer/ema.py``). Functional: ``ema()`` is an optax-style
state transform; ``ExponentialMovingAverage`` is the stateful facade the
reference exposes (update/apply/restore)."""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["ema", "ExponentialMovingAverage"]


class EMAState(NamedTuple):
    shadow: Any
    count: jnp.ndarray


def ema(decay: float = 0.999, debias: bool = True):
    """Returns (init_fn, update_fn): shadow = decay*shadow + (1-decay)*params."""

    def init(params):
        return EMAState(shadow=jax.tree.map(jnp.asarray, params), count=jnp.zeros((), jnp.int32))

    def update(params, state: EMAState) -> EMAState:
        count = state.count + 1
        d = jnp.minimum(decay, (1.0 + count) / (10.0 + count)) if debias else decay
        shadow = jax.tree.map(lambda s, p: s * d + p.astype(s.dtype) * (1.0 - d),
                              state.shadow, params)
        return EMAState(shadow=shadow, count=count)

    return init, update


class ExponentialMovingAverage:
    def __init__(self, params, decay: float = 0.999, debias: bool = True):
        self._init, self._update = ema(decay, debias)
        self.state = self._init(params)
        self._backup = None

    def update(self, params):
        self.state = jax.jit(self._update)(params, self.state)

    def apply(self, params):
        """Return EMA params (callers swap them in for eval)."""
        self._backup = params
        return self.state.shadow

    def restore(self):
        params, self._backup = self._backup, None
        return params
