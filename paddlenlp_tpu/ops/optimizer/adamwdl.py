"""AdamW with layer-wise learning-rate decay (reference:
``paddlenlp/ops/optimizer/adamwdl.py`` — AdamWDL, the BERT/ELECTRA finetuning
staple: lr(layer) = base_lr * decay^(n_layers - layer)).

optax-native: one ``optax.multi_transform`` over per-depth scale groups; the
depth of a param is parsed from its path (``layers_<i>`` / ``layers``-stacked /
``h_<i>`` segments; embeddings get depth -1, heads get n_layers).
"""

from __future__ import annotations

import re
from typing import Callable, Optional

import jax
import optax

__all__ = ["adamwdl", "layerwise_lr_decay_mask"]

_DEPTH_RE = re.compile(r"(?:layers?|h|blocks?)_(\d+)\b")


def _param_depth(path: str, n_layers: int) -> int:
    m = _DEPTH_RE.search(path)
    if m:
        return int(m.group(1))
    if any(k in path for k in ("embed", "wte", "wpe", "word_embeddings", "position_embeddings")):
        return -1
    if "/layers/" in f"/{path}" or "/h/" in f"/{path}":
        return -2  # scanned stack: one shared tensor spans all depths
    return n_layers  # head / final norm


def layerwise_lr_decay_mask(params, n_layers: int) -> dict:
    """pytree of depth labels matching ``params`` (for multi_transform)."""
    from ...transformers.conversion_utils import flatten_params, unflatten_params

    flat = flatten_params(params)
    return unflatten_params({p: str(_param_depth(p, n_layers)) for p in flat})


def adamwdl(
    learning_rate,
    n_layers: int,
    layerwise_decay: float = 0.8,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.01,
    wd_mask: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """AdamW where depth d gets lr scale ``layerwise_decay^(n_layers - d)``.

    A scanned [L]-stacked param (depth label -2) cannot vary lr across its own
    leading axis with a scalar scale; it receives the mean scale (exact per-layer
    scaling needs the unrolled layout).
    """
    def tx_for(scale: float):
        return optax.chain(
            optax.adamw(learning_rate, b1=b1, b2=b2, eps=eps,
                        weight_decay=weight_decay, mask=wd_mask),
            optax.scale(scale),
        )

    scales = {str(d): layerwise_decay ** (n_layers - d) for d in range(n_layers)}
    scales[str(-1)] = layerwise_decay ** (n_layers + 1)
    scales[str(n_layers)] = 1.0
    scales[str(-2)] = sum(layerwise_decay ** (n_layers - d) for d in range(n_layers)) / n_layers

    def label_fn(params):
        return layerwise_lr_decay_mask(params, n_layers)

    return optax.multi_transform({k: tx_for(v) for k, v in scales.items()}, label_fn)
