"""Attention dispatch: Pallas flash attention on TPU, fused XLA math elsewhere.

Counterpart of the reference's ``llama/fusion_ops.py:147-238``
(``fusion_flash_attention``: FlashAttention-2 / flashmask / ring / vendor-op dispatch).
TPU-native structure:

- default path: ``jax.nn.dot_product_attention`` — XLA fuses the softmax chain onto
  the MXU and handles GQA natively; on TPU this already hits the fused attention path;
- ``segment_ids`` support for packed (ZeroPadding) batches — the FlashMask
  ``startend_row_indices`` equivalent: tokens attend only within their segment,
  causally (reference fusion_ops.py:223-238);
- context-parallel path: ring attention over the ``cp`` mesh axis
  (``ops/ring_attention.py``), selected by the caller when cp > 1;
- a Pallas splash/flash kernel path for long sequences (`use_pallas=True`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..parallel.mesh import shard_map as _shard_map

__all__ = ["dot_product_attention", "make_causal_mask", "make_segment_mask"]


def make_causal_mask(q_len: int, kv_len: int, offset=0, dtype=jnp.bool_, window: Optional[int] = None) -> jnp.ndarray:
    """[1, 1, q_len, kv_len] causal mask; ``offset`` = absolute position of q row 0.
    ``window`` adds a sliding-window lower bound (mistral-style local attention)."""
    rows = jnp.arange(q_len)[:, None] + offset
    cols = jnp.arange(kv_len)[None, :]
    mask = cols <= rows
    if window is not None:
        mask = mask & (cols > rows - window)
    return mask.astype(dtype)[None, None]


def make_segment_mask(q_segments: jnp.ndarray, kv_segments: jnp.ndarray) -> jnp.ndarray:
    """[B, 1, T, S] same-segment mask for packed batches (flashmask equivalent)."""
    return (q_segments[:, None, :, None] == kv_segments[:, None, None, :])


def alibi_slopes(n_heads: int) -> jnp.ndarray:
    """Closed-form ALiBi slopes (reference attention_strategies.py
    AttentionWithLinearBias :24 / the bloom convention)."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start**i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return jnp.asarray(pow2_slopes(n_heads), jnp.float32)
    closest = 2 ** int(math.floor(math.log2(n_heads)))
    slopes = pow2_slopes(closest)
    extra = pow2_slopes(2 * closest)[0::2][: n_heads - closest]
    return jnp.asarray(slopes + extra, jnp.float32)


def alibi_bias(n_heads: int, q_len: int, kv_len: int, offset=0) -> jnp.ndarray:
    """[1, n_heads, q_len, kv_len] additive bias: -slope * (q_pos - k_pos)."""
    slopes = alibi_slopes(n_heads)
    rows = jnp.arange(q_len)[:, None] + offset
    cols = jnp.arange(kv_len)[None, :]
    dist = (rows - cols).astype(jnp.float32)  # >= 0 within the causal region
    return (-slopes[:, None, None] * dist)[None]


def dot_product_attention(
    query: jnp.ndarray,  # [B, T, n_heads, head_dim]
    key: jnp.ndarray,  # [B, S, n_kv, head_dim]
    value: jnp.ndarray,  # [B, S, n_kv, head_dim]
    *,
    attention_mask: Optional[jnp.ndarray] = None,  # [B, S] padding mask (1 = keep)
    segment_ids: Optional[jnp.ndarray] = None,  # [B, S] packed-batch segments
    causal: bool = True,
    q_offset=0,  # absolute pos of query row 0 (decode with KV cache)
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    window: Optional[int] = None,
    positions: Optional[jnp.ndarray] = None,  # [B, T] or [T] ABSOLUTE positions (permuted layouts)
    use_pallas: Optional[bool] = None,
    use_alibi: bool = False,  # additive -slope*(q_pos-k_pos) bias (bloom/baichuan-13b)
    bias: Optional[jnp.ndarray] = None,  # [B|1, N|1, T, S] additive bias (t5 relative positions)
) -> jnp.ndarray:
    """Fused attention; returns [B, T, n_heads, head_dim] in query dtype.

    ``positions``: when the sequence axis is physically permuted (context-parallel
    zigzag layout), index order != causal order; pass absolute positions and the
    causal/window mask is built from them instead of array indices.

    ``use_pallas``: None (default) enables the Pallas flash kernel automatically
    on TPU for eligible shapes (causal self-attention, optional segment_ids /
    sliding window, no dropout/padding-mask/cache). Inside a sharded jit the
    kernel runs under a ``shard_map`` over the batch/head mesh axes so it
    composes with GSPMD (pallas_call alone is opaque to the partitioner).
    Pass False to force the XLA path, True to force Pallas (interpret off-TPU).
    """
    B, T, N, H = query.shape
    S = key.shape[1]
    K = key.shape[2]
    scale = scale if scale is not None else H**-0.5

    pallas_eligible = (
        causal
        and bias is None
        and attention_mask is None
        and positions is None
        and dropout_rate == 0.0
        and not use_alibi
        and T == S  # self-attention, no KV cache
        and (isinstance(q_offset, int) and q_offset == 0)
        and N % K == 0
    )
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"  # default ON for TPU
    if use_pallas and pallas_eligible:
        try:
            out = _pallas_dispatch(query, key, value, segment_ids, scale, window)
            if out is not None:
                return out
        except Exception as e:  # pallas unavailable/lowering failure: fall through
            from ..utils.log import logger

            logger.warning_once(f"pallas flash attention failed ({type(e).__name__}: {e}); using XLA path")

    mask = None
    if causal and positions is not None:
        pos = positions if positions.ndim == 2 else positions[None, :]
        pos = jnp.broadcast_to(pos, (B, S))
        q_pos = pos[:, -T:] if T != S else pos
        m = pos[:, None, None, :] <= q_pos[:, None, :, None]
        if window is not None:
            m = m & (pos[:, None, None, :] > q_pos[:, None, :, None] - window)
        mask = m
    elif causal:
        mask = jnp.broadcast_to(make_causal_mask(T, S, q_offset, window=window), (B, 1, T, S))
    if segment_ids is not None:
        q_seg = segment_ids[:, -T:] if T != S else segment_ids
        seg_mask = make_segment_mask(q_seg, segment_ids)
        mask = seg_mask if mask is None else jnp.logical_and(mask, seg_mask)
    if attention_mask is not None:
        pad = attention_mask[:, None, None, :].astype(jnp.bool_)
        mask = pad if mask is None else jnp.logical_and(mask, pad)

    if use_alibi:
        if positions is not None:
            # permuted layouts (cp zigzag): distances from ABSOLUTE positions
            pos = positions if positions.ndim == 2 else positions[None, :]
            pos = jnp.broadcast_to(pos, (B, S)).astype(jnp.float32)
            q_pos = pos[:, -T:] if T != S else pos
            dist = q_pos[:, None, :, None] - pos[:, None, None, :]
            ab = -alibi_slopes(N)[None, :, None, None] * dist
            bias = ab if bias is None else bias + ab
        else:
            ab = jnp.broadcast_to(alibi_bias(N, T, S, q_offset), (B, N, T, S))
            bias = ab if bias is None else bias + ab

    if dropout_rate == 0.0:
        try:
            return jax.nn.dot_product_attention(query, key, value, bias=bias, mask=mask, scale=scale)
        except TypeError:  # API-signature drift across jax versions only
            from ..utils.log import logger

            logger.warning_once("jax.nn.dot_product_attention signature mismatch; using math attention")
    return _math_attention(query, key, value, mask, scale, dropout_rate, dropout_rng, bias=bias)


def _pallas_dispatch(query, key, value, segment_ids, scale, window):
    """Run the Pallas kernel directly (off-mesh) or under a shard_map over the
    batch/head mesh axes (the GSPMD composition the reference gets from fleet's
    per-rank kernel launches). Returns None when the active sharding cannot be
    expressed (fall back to the XLA path)."""
    import os

    from jax.sharding import Mesh, PartitionSpec as PS

    from ..parallel.partition import _current_mesh
    from .pallas.flash_attention import flash_attention as _pf

    # hardware-sweepable tile sizes (tools/bench sweep; default 128x128).
    # Invalid values fall back to the default rather than crashing at the
    # ENCLOSING jit's compile (same contract as the shape gate below).
    def _tile(env_name):
        try:
            b = int(os.environ.get(env_name, 128))
        except ValueError:
            return 128
        return b if b >= 128 and b % 128 == 0 else 128

    pallas_flash = functools.partial(
        _pf, block_q=_tile("PDNLP_FLASH_BLOCK_Q"), block_kv=_tile("PDNLP_FLASH_BLOCK_KV")
    )

    B, T, N, H = query.shape
    K = key.shape[2]
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and not (T % 128 == 0 and H % 64 == 0):
        # Mosaic tiling gate: compile errors surface at the ENCLOSING jit's
        # compile, outside our try/except — so unsupported shapes must be
        # rejected here, not discovered as a crash.
        return None
    mesh = _current_mesh()
    if mesh is None:
        return pallas_flash(query, key, value, segment_ids, scale, True, window)
    live = lambda axes: tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
    if not live(("dp", "fsdp", "tp", "sep", "cp")):
        return pallas_flash(query, key, value, segment_ids, scale, True, window)
    if not isinstance(mesh, Mesh):
        return None  # AbstractMesh (AOT/topology): let the XLA path partition
    if live(("cp",)):  # seq would be sharded; ring/XLA paths own that case
        return None
    batch_ax = live(("dp", "fsdp"))
    head_ax = live(("tp", "sep"))
    nb, nh = 1, 1
    for a in batch_ax:
        nb *= mesh.shape[a]
    for a in head_ax:
        nh *= mesh.shape[a]
    if B % nb or N % nh or K % nh or (N // nh) % max(K // nh, 1):
        return None
    qkv_spec = PS(batch_ax or None, None, head_ax or None, None)
    fn = functools.partial(pallas_flash, scale=scale, causal=True, window=window)
    if segment_ids is None:
        return _shard_map(
            lambda q, k, v: fn(q, k, v, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )(query, key, value)
    seg_spec = PS(batch_ax or None, None)
    return _shard_map(
        lambda q, k, v, s: fn(q, k, v, s),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )(query, key, value, segment_ids)


def _math_attention(query, key, value, mask, scale, dropout_rate=0.0, dropout_rng=None, bias=None):
    B, T, N, H = query.shape
    S = key.shape[1]
    K = key.shape[2]
    if K != N:  # GQA: broadcast kv heads over query groups
        rep = N // K
        key = jnp.repeat(key, rep, axis=2)
        value = jnp.repeat(value, rep, axis=2)
    logits = jnp.einsum("btnh,bsnh->bnts", query.astype(jnp.float32), key.astype(jnp.float32)) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bnts,bsnh->btnh", probs, value.astype(jnp.float32))
    return out.astype(query.dtype)
