"""Cross-entropy losses for LM pretraining.

Counterpart of the reference's ``LlamaPretrainingCriterion`` (llama/modeling.py:1777)
+ ``tensor_parallel_utils.py`` parallel cross entropy. Under GSPMD there is no
separate "parallel" CE module: we keep logits sharded over the tp axis (vocab dim)
with a sharding constraint and let XLA turn the log-sum-exp + gather into
reduce-scattered collectives — the reference's fused parallel CE falls out of the
partitioner.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["cross_entropy_with_ignore", "causal_lm_loss"]

IGNORE_INDEX = -100


def cross_entropy_with_ignore(
    logits: jnp.ndarray,  # [..., vocab]
    labels: jnp.ndarray,  # [...]
    ignore_index: int = IGNORE_INDEX,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Token-mean CE over non-ignored labels; fp32 accumulation. Returns (loss, n_valid)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    token_loss = jnp.where(valid, lse - picked, 0.0)
    n_valid = valid.sum()
    loss = token_loss.sum() / jnp.maximum(n_valid, 1)
    return loss, n_valid


def causal_lm_loss(
    logits: jnp.ndarray,  # [B, T, vocab]
    labels: jnp.ndarray,  # [B, T] — already shifted or raw (set shift=True)
    ignore_index: int = IGNORE_INDEX,
    shift: bool = False,
) -> jnp.ndarray:
    if shift:
        logits = logits[:, :-1]
        labels = labels[:, 1:]
    loss, _ = cross_entropy_with_ignore(logits, labels, ignore_index)
    return loss
