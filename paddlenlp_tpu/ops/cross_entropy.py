"""Cross-entropy losses for LM pretraining.

Counterpart of the reference's ``LlamaPretrainingCriterion`` (llama/modeling.py:1777)
+ ``tensor_parallel_utils.py`` parallel cross entropy. Under GSPMD there is no
separate "parallel" CE module: we keep logits sharded over the tp axis (vocab dim)
with a sharding constraint and let XLA turn the log-sum-exp + gather into
reduce-scattered collectives — the reference's fused parallel CE falls out of the
partitioner.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["cross_entropy_with_ignore", "causal_lm_loss", "fused_linear_cross_entropy"]

IGNORE_INDEX = -100


def cross_entropy_with_ignore(
    logits: jnp.ndarray,  # [..., vocab]
    labels: jnp.ndarray,  # [...]
    ignore_index: int = IGNORE_INDEX,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Token-mean CE over non-ignored labels; fp32 accumulation. Returns (loss, n_valid)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    token_loss = jnp.where(valid, lse - picked, 0.0)
    n_valid = valid.sum()
    loss = token_loss.sum() / jnp.maximum(n_valid, 1)
    return loss, n_valid


def fused_linear_cross_entropy(
    hidden: jnp.ndarray,  # [B, T, H] last hidden states (bf16 fine)
    weight: jnp.ndarray,  # [H, V] lm_head kernel (or embed.T when tied)
    labels: jnp.ndarray,  # [B, T] targets aligned with hidden
    ignore_index: int = IGNORE_INDEX,
    chunk: int = 512,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Token-mean CE of ``lm_head(hidden)`` without materializing [B, T, V] logits.

    The reference's memory answer to the head is fused parallel CE
    (llama/modeling.py:1777 + tensor_parallel_utils.py); on TPU the [B,T,V]
    fp32 logits + softmax temporaries are the HBM cliff (≈2 GB per copy at
    B8/T2k/V32k), so we scan over token chunks and checkpoint each chunk:
    forward AND backward peak at [B, chunk, V], and the head matmul still runs
    chunk-batched on the MXU. Returns (loss, n_valid).
    """
    B, T, H = hidden.shape
    pad = (-T) % chunk
    if pad:
        hidden = jnp.pad(hidden, ((0, 0), (0, pad), (0, 0)))
        labels = jnp.pad(labels, ((0, 0), (0, pad)), constant_values=ignore_index)
    nc = (T + pad) // chunk
    hs = hidden.reshape(B, nc, chunk, H).swapaxes(0, 1)  # [nc, B, chunk, H]
    ls = labels.reshape(B, nc, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def chunk_loss(h, l):
        logits = (h @ weight.astype(h.dtype)).astype(jnp.float32)
        valid = l != ignore_index
        safe = jnp.where(valid, l, 0)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        token_loss = jnp.where(valid, lse - picked, 0.0)
        return token_loss.sum(), valid.sum()

    def body(carry, xs):
        s, n = carry
        ds, dn = chunk_loss(*xs)
        return (s + ds, n + dn), None

    (total, n_valid), _ = jax.lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)), (hs, ls)
    )
    return total / jnp.maximum(n_valid, 1), n_valid


def causal_lm_loss(
    logits: jnp.ndarray,  # [B, T, vocab]
    labels: jnp.ndarray,  # [B, T] — already shifted or raw (set shift=True)
    ignore_index: int = IGNORE_INDEX,
    shift: bool = False,
) -> jnp.ndarray:
    if shift:
        logits = logits[:, :-1]
        labels = labels[:, 1:]
    loss, _ = cross_entropy_with_ignore(logits, labels, ignore_index)
    return loss
