"""Ring attention: exact attention over sequence chunks sharded on the ``cp`` axis.

Counterpart of ``paddlenlp/transformers/ring_flash_attention.py`` (``RingCommunicator``
P2P :24, ``balanced_ring_flash_attention_fwd_func`` :97 with log-sum-exp merge :69,
custom backward) and ``context_parallel_utils.py``. TPU-native redesign:

- the NCCL isend/irecv ring becomes ``lax.ppermute`` over the ``cp`` mesh axis
  inside ``shard_map`` — XLA schedules the collective-permute to overlap with the
  per-chunk attention compute on ICI;
- the hand-written backward disappears: the ring is a ``lax.scan`` of traceable
  ops, so reverse-mode AD derives it (ppermute's transpose is the reverse ring);
  the scan body is ``jax.checkpoint``-ed so K/V chunks are re-permuted, not stored;
- causal masking uses absolute positions, so any chunk layout works; the zigzag
  load-balanced split of the reference (:32) is provided for contiguous causal
  runs.

Per-device memory is O(S/cp) for K/V — the point of ring attention vs letting
GSPMD all-gather the sequence axis.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import shard_map as _shard_map


def _axis_size(axis_name: str) -> int:
    """Static size of a manual mesh axis: ``jax.lax.axis_size`` where it
    exists; older jax spells it ``jax.core.axis_frame`` (which returns the
    bare int on those builds)."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return getattr(frame, "size", frame)

__all__ = ["ring_attention_local", "ring_self_attention", "zigzag_split", "zigzag_unsplit"]


def _chunk_attention(q, k, v, q_pos, kv_pos, scale):
    """Masked attention contribution of one kv chunk: returns UNNORMALIZED
    (num [B,Tq,N,H], den [B,N,Tq], m [B,N,Tq]) in fp32 — the flash-attention
    accumulator triple. ``m`` is -inf for fully-masked rows. Positions are
    per-row [B, Tq]/[B, Tk] (absolute), so heterogeneous batches mask correctly."""
    B, Tq, N, H = q.shape
    K = k.shape[2]
    if K != N:
        rep = N // K
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("btnh,bsnh->bnts", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    mask = kv_pos[:, None, None, :] <= q_pos[:, None, :, None]  # [B,1,Tq,Tk] causal by abs position
    logits = jnp.where(mask, logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)  # [B,N,Tq], -inf when fully masked
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    probs = jnp.where(mask, jnp.exp(logits - safe_m[..., None]), 0.0)
    den = probs.sum(axis=-1)
    num = jnp.einsum("bnts,bsnh->btnh", probs, v.astype(jnp.float32))
    return num, den, m


def _merge(num_a, den_a, m_a, num_b, den_b, m_b):
    """Numerically-stable merge of two unnormalized partials (the reference's
    update_out_and_lse, ring_flash_attention.py:69, in (num, den, max) form)."""
    m = jnp.maximum(m_a, m_b)
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    wa = jnp.where(jnp.isfinite(m_a), jnp.exp(m_a - safe_m), 0.0)
    wb = jnp.where(jnp.isfinite(m_b), jnp.exp(m_b - safe_m), 0.0)
    num = num_a * wa.transpose(0, 2, 1)[..., None] + num_b * wb.transpose(0, 2, 1)[..., None]
    den = den_a * wa + den_b * wb
    return num, den, m


def ring_attention_local(
    q: jnp.ndarray,  # [B, Tq, N, H] — this device's query chunk
    k: jnp.ndarray,  # [B, Tk, K, H] — this device's kv chunk
    v: jnp.ndarray,
    q_positions: jnp.ndarray,  # [B, Tq] absolute positions of the q chunk
    kv_positions: jnp.ndarray,  # [B, Tk] absolute positions of the kv chunk
    axis_name: str = "cp",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Runs INSIDE shard_map: each step attends to the resident kv chunk, then
    ppermutes (k, v, kv_positions) one hop around the ring."""
    H = q.shape[-1]
    scale = scale if scale is not None else H**-0.5
    cp = _axis_size(axis_name)
    perm = [(i, (i + 1) % cp) for i in range(cp)]

    @jax.checkpoint
    def step(carry, _):
        num_acc, den_acc, m_acc, k_c, v_c, kv_pos = carry
        num_c, den_c, m_c = _chunk_attention(q, k_c, v_c, q_positions, kv_pos, scale)
        num_acc, den_acc, m_acc = _merge(num_acc, den_acc, m_acc, num_c, den_c, m_c)
        k_n = jax.lax.ppermute(k_c, axis_name, perm)
        v_n = jax.lax.ppermute(v_c, axis_name, perm)
        p_n = jax.lax.ppermute(kv_pos, axis_name, perm)
        return (num_acc, den_acc, m_acc, k_n, v_n, p_n), None

    B, Tq, N, _ = q.shape
    num0 = jnp.zeros((B, Tq, N, H), jnp.float32)
    den0 = jnp.zeros((B, N, Tq), jnp.float32)
    m0 = jnp.full((B, N, Tq), -jnp.inf, jnp.float32)
    (num, den, _, _, _, _), _ = jax.lax.scan(step, (num0, den0, m0, k, v, kv_positions), None, length=cp)
    out = num / jnp.maximum(den, 1e-37).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_self_attention(
    q: jnp.ndarray,  # [B, S, N, H] — logical (global) arrays, seq sharded over cp
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    positions: Optional[jnp.ndarray] = None,  # [S] or [B, S] absolute positions (zigzag layouts)
    axis_name: str = "cp",
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """shard_map wrapper: manual over ``cp`` only — batch/heads axes stay under
    GSPMD (the reference needs a dedicated cp process group; here it's one axis)."""
    B, S = q.shape[:2]
    if positions is None:
        positions = jnp.arange(S, dtype=jnp.int32)
    if positions.ndim == 1:
        positions = jnp.broadcast_to(positions[None, :], (B, S))

    def local(q_c, k_c, v_c, pos_c):
        return ring_attention_local(q_c, k_c, v_c, pos_c, pos_c, axis_name, scale)

    qspec = P(None, axis_name, None, None)
    return _shard_map(
        local,
        mesh=mesh,
        in_specs=(qspec, qspec, qspec, P(None, axis_name)),
        out_specs=qspec,
        axis_names={axis_name},
        check_vma=False,
    )(q, k, v, positions)


def zigzag_split(x: jnp.ndarray, cp: int, axis: int = 1) -> jnp.ndarray:
    """Reorder the sequence axis into the load-balanced zigzag layout (reference
    context_parallel_utils.py:32): rank r gets chunks (r, 2*cp-1-r) so every rank
    sees a balanced mix of early (cheap) and late (expensive) causal positions.
    Returns the permuted array (same shape); pair with position ids from
    ``zigzag_positions`` so ring attention masks by absolute position."""
    S = x.shape[axis]
    idx = zigzag_positions(S, cp)
    return jnp.take(x, idx, axis=axis)


@functools.lru_cache(maxsize=64)
def zigzag_positions(S: int, cp: int) -> "np.ndarray":
    """Absolute positions, zigzag order: concat over r of chunk r and chunk 2cp-1-r.
    Pure NumPy + cached: this sits on the per-batch host data path."""
    import numpy as np

    if S % (2 * cp) != 0:
        raise ValueError(
            f"context parallel requires seq_len divisible by 2*cp for the zigzag "
            f"load-balanced split: got seq_len={S}, cp={cp} (need a multiple of {2 * cp})"
        )
    chunk = S // (2 * cp)
    order = []
    for r in range(cp):
        order.extend(range(r * chunk, (r + 1) * chunk))
        order.extend(range((2 * cp - 1 - r) * chunk, (2 * cp - r) * chunk))
    return np.asarray(order, dtype=np.int32)


def zigzag_unsplit(x: jnp.ndarray, cp: int, axis: int = 1) -> jnp.ndarray:
    import numpy as np

    S = x.shape[axis]
    idx = np.asarray(zigzag_positions(S, cp))
    inv = np.zeros_like(idx)
    inv[idx] = np.arange(S, dtype=np.int32)
    return jnp.take(x, inv, axis=axis)
