"""Device mesh construction — the substrate for every parallelism strategy.

The reference expresses hybrid parallelism as a stack of fleet wrappers over NCCL
process groups with a configurable axis order
(``paddlenlp/trainer/training_args.py:1265-1303``, axes dp/pp/sharding/sep/mp and
``fleet.get_hybrid_communicate_group()`` accessors at 1744-1797). TPU-native, all of
that collapses into ONE ``jax.sharding.Mesh`` whose named axes are the strategies:

=========  =====================================================================
axis       strategy it carries
=========  =====================================================================
``dp``     pure data parallel (replicated params; batch sharded)
``fsdp``   ZeRO / "sharding stage 1-3": params+grads+opt state sharded over it,
           batch also sharded over it (it is a data axis for activations)
``pp``     pipeline parallel (layer-stacked scan over stages, collective_permute)
``sep``    Ulysses/segment parallel (seq<->heads all-to-all inside attention)
``cp``     context parallel (ring attention over seq chunks)
``tp``     tensor parallel (Megatron column/row sharding; innermost => ICI-nearest)
=========  =====================================================================

Axis ORDER is ICI-locality: later axes vary fastest over the physical device
order, so ``tp`` neighbours are ICI neighbours; the outermost ``dp`` axis is the
one to map onto DCN for multi-slice.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["MESH_AXES", "MeshConfig", "create_mesh", "mesh_axis_size", "get_abstract_mesh"]

MESH_AXES: Tuple[str, ...] = ("dp", "fsdp", "pp", "sep", "cp", "tp")

# Axes over which the global batch is sharded (activation batch dim).
BATCH_AXES: Tuple[str, ...] = ("dp", "fsdp")
# Axes over which the sequence dim of activations is sharded.
SEQ_AXES: Tuple[str, ...] = ("sep", "cp")


@dataclasses.dataclass
class MeshConfig:
    """Degrees for each mesh axis (product must divide the device count)."""

    dp: int = -1  # -1: absorb remaining devices
    fsdp: int = 1
    pp: int = 1
    sep: int = 1
    cp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> "MeshConfig":
        fixed = self.fsdp * self.pp * self.sep * self.cp * self.tp
        dp = self.dp
        if dp == -1:
            if n_devices % fixed != 0:
                raise ValueError(f"device count {n_devices} not divisible by fixed axes product {fixed}")
            dp = n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"mesh {dp}x{self.fsdp}x{self.pp}x{self.sep}x{self.cp}x{self.tp} != {n_devices} devices"
            )
        return dataclasses.replace(self, dp=dp)

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.dp, self.fsdp, self.pp, self.sep, self.cp, self.tp)

    @property
    def data_degree(self) -> int:
        return self.dp * self.fsdp

    @classmethod
    def from_training_args(cls, args) -> "MeshConfig":
        return cls(
            dp=-1,
            fsdp=args.sharding_parallel_degree if args.sharding_parallel_degree > 0 else 1,
            pp=args.pipeline_parallel_degree,
            sep=args.sep_parallel_degree,
            cp=args.context_parallel_degree,
            tp=args.tensor_parallel_degree,
        )


def create_mesh(config: Optional[MeshConfig] = None, devices: Optional[Sequence] = None):
    """Build the named Mesh; uses ``mesh_utils`` for ICI-aware device placement.

    All axes are ``AxisType.Auto``: GSPMD propagates shardings from the hints the
    models emit (``shard_constraint``) — the moral equivalent of the reference's
    semi-auto parallel (``auto_trainer.py``), but applied to every strategy.
    On jax builds predating ``jax.sharding.AxisType`` (<= 0.4.x) every axis is
    implicitly Auto already, so the Mesh is built without axis_types.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    config = (config or MeshConfig()).resolve(len(devices))
    shape = config.shape
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=np.asarray(devices))
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    AxisType = getattr(jax.sharding, "AxisType", None)
    if AxisType is None:
        return Mesh(dev_array, MESH_AXES)
    return Mesh(dev_array, MESH_AXES, axis_types=(AxisType.Auto,) * len(MESH_AXES))


def use_mesh(mesh):
    """Context manager activating ``mesh`` for bare-PartitionSpec sharding hints.

    ``jax.sharding.set_mesh`` where this jax has it; on older builds the Mesh
    object itself is the context manager (the legacy ``with mesh:`` thread
    resource, which `partition._current_mesh` also knows how to read)."""
    import jax

    set_mesh = getattr(jax.sharding, "set_mesh", None)
    if set_mesh is None:
        return mesh
    return set_mesh(mesh)


def shard_map(f, mesh, in_specs, out_specs, axis_names=None, check_vma: bool = False):
    """Version-portable ``shard_map`` (mirrors the ``use_mesh`` shim above).

    Newer jax exposes ``jax.shard_map(..., axis_names=..., check_vma=...)``;
    older builds only have ``jax.experimental.shard_map.shard_map`` with the
    ``check_rep``/``auto`` spelling — ``axis_names`` (axes mapped manually)
    is the complement of ``auto`` (axes left to GSPMD)."""
    import jax

    native = getattr(jax, "shard_map", None)
    if native is not None:
        kw = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma)
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return native(f, **kw)
    from jax.experimental.shard_map import shard_map as legacy

    # ``axis_names`` (manual over a subset, GSPMD over the rest) maps to the
    # legacy ``auto=`` complement — but partially-auto shard_map ABORTS XLA's
    # CPU backend on these old builds, so run fully manual instead: the specs
    # leave the other axes unmentioned (replicated), which is numerically the
    # same program minus GSPMD's freedom to co-shard the untouched axes.
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma)


def mesh_axis_size(mesh, axis) -> int:
    """Product size of one axis or tuple of axes (absent axes count as 1)."""
    if mesh is None:
        return 1
    if isinstance(axis, (tuple, list)):
        return math.prod(mesh_axis_size(mesh, a) for a in axis)
    return mesh.shape.get(axis, 1)


def get_abstract_mesh(config: MeshConfig, n_devices: int):
    """An AbstractMesh for shape-only compilation (AOT/topology runs)."""
    from jax.sharding import AbstractMesh

    config = config.resolve(n_devices)
    return AbstractMesh(config.shape, MESH_AXES)
