from .mesh import MESH_AXES, MeshConfig, create_mesh, mesh_axis_size, use_mesh  # noqa: F401
from .partition import (  # noqa: F401
    DEFAULT_LOGICAL_RULES,
    P,
    logical_axis_rules,
    resolve_spec,
    shard_constraint,
    shard_params,
    sharding_tree,
    spec_tree_from_rules,
)
from .launch import init_distributed, local_batch_to_global  # noqa: F401
