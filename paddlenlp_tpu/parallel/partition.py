"""Logical-axis partitioning: regex rules -> NamedSharding trees.

This replaces the reference's per-model ``_get_tensor_parallel_mappings`` +
fleet Column/RowParallelLinear wrappers (``paddlenlp/transformers/conversion_utils.py:352-676``,
``llama/modeling.py:723-799``): instead of *rewriting modules* per strategy, each
model declares, once, a list of ``(param-path regex, logical PartitionSpec)`` rules;
the trainer maps logical axis names to physical mesh axes. The same model code then
runs dp-only, tp, fsdp, or any hybrid purely by changing the mapping — XLA/GSPMD
inserts all collectives.

Logical axis vocabulary (superset of t5x/maxtext conventions):

=========== ==========================================================
``vocab``    embedding/vocab dim        -> tp
``embed``    model hidden dim           -> fsdp (ZeRO param shard)
``mlp``      ffn intermediate dim       -> tp
``heads``    attention heads dim        -> tp
``kv``       head_dim                   -> None
``expert``   MoE expert dim             -> ep-bearing axes
``batch``    activation batch           -> (dp, fsdp)
``seq``      activation sequence        -> (sep, cp)
=========== ==========================================================
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

__all__ = [
    "P",
    "DEFAULT_LOGICAL_RULES",
    "resolve_spec",
    "spec_tree_from_rules",
    "sharding_tree",
    "shard_params",
    "shard_constraint",
    "logical_axis_size",
    "batch_spec",
    "param_path_tree",
]

PartitionRules = Sequence[Tuple[str, PartitionSpec]]

# logical axis name -> physical mesh axis (or tuple of axes, or None=replicate)
DEFAULT_LOGICAL_RULES: Dict[str, Any] = {
    # ---- parameter axes ----
    "vocab": "tp",
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "expert": ("dp", "fsdp"),  # expert parallel rides the data axes (reference: use_expert_parallel)
    "norm": None,
    "layers": None,  # becomes "pp" when the stacked-layer pipeline path is active
    "stage": "pp",  # pipeline stage axis of the [S, L/S, ...] param view / state
    # ---- activation axes ----
    "batch": ("dp", "fsdp"),
    "seq": ("sep", "cp"),
    "act_seq": ("sep", "cp"),  # residual-stream seq dim (sequence_parallel adds "tp")
    "act_seq_attn": ("cp",),  # seq dim inside attention: sep moved onto heads (Ulysses)
    "act_heads": ("tp", "sep"),
    "act_kv_heads": ("tp", "sep"),
    "act_mlp": "tp",
    "act_vocab": "tp",
    "act_embed": None,
}

_thread_rules = __import__("threading").local()


class logical_axis_rules:
    """Context manager overriding logical->physical mapping (e.g. Megatron SP adds
    ``tp`` to the residual seq axis: ``{"act_seq": ("sep", "cp", "tp")}``)."""

    def __init__(self, overrides: Dict[str, Any]):
        self.rules = {**DEFAULT_LOGICAL_RULES, **overrides}

    def __enter__(self):
        self._prev = getattr(_thread_rules, "rules", None)
        _thread_rules.rules = self.rules
        return self.rules

    def __exit__(self, *exc):
        _thread_rules.rules = self._prev


def active_logical_rules() -> Dict[str, Any]:
    return getattr(_thread_rules, "rules", None) or DEFAULT_LOGICAL_RULES


def _axes_size(mesh: Optional[Mesh], phys) -> int:
    if mesh is None:
        return 1
    if phys is None:
        return 1
    if isinstance(phys, (tuple, list)):
        out = 1
        for p in phys:
            out *= mesh.shape.get(p, 1)
        return out
    return mesh.shape.get(phys, 1)


def resolve_spec(
    logical_spec: PartitionSpec,
    mesh: Optional[Mesh],
    rules: Optional[Dict[str, Any]] = None,
    shape: Optional[Tuple[int, ...]] = None,
) -> PartitionSpec:
    """Map a logical PartitionSpec to physical mesh axes.

    Axes whose mesh size is 1 are dropped; if ``shape`` is given, axes that do not
    divide the corresponding dim are dropped (with the same fallback semantics as the
    reference's GQA ``assign_kv_heads`` escape hatch — replicate rather than crash).
    """
    rules = rules or active_logical_rules()
    out = []
    used = set()
    for i, name in enumerate(logical_spec):
        if name is None:
            out.append(None)
            continue
        phys = rules.get(name, None) if isinstance(name, str) else name
        if phys is None:
            out.append(None)
            continue
        # drop axes already consumed by an earlier dim (a mesh axis may appear once)
        if isinstance(phys, (tuple, list)):
            phys = tuple(p for p in phys if p not in used and mesh is not None and mesh.shape.get(p, 1) > 1)
            phys = phys if phys else None
        else:
            if phys in used or _axes_size(mesh, phys) == 1:
                phys = None
        if phys is not None and shape is not None:
            size = _axes_size(mesh, phys)
            if shape[i] % size != 0:
                phys = None
        if phys is not None:
            for p in phys if isinstance(phys, tuple) else (phys,):
                used.add(p)
        out.append(phys)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def param_path_tree(tree) -> Any:
    """Pytree of '/'-joined key paths, same structure as ``tree``."""

    def _name(k):
        if hasattr(k, "key"):
            return str(k.key)
        if hasattr(k, "idx"):
            return str(k.idx)
        return str(k)

    paths = []
    leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    for path, _ in leaves:
        paths.append("/".join(_name(k) for k in path))
    return jax.tree_util.tree_unflatten(treedef, paths)


def spec_tree_from_rules(
    tree,
    partition_rules: PartitionRules,
    mesh: Optional[Mesh] = None,
    logical_rules: Optional[Dict[str, Any]] = None,
) -> Any:
    """Match each param path against the regex rules; produce a PartitionSpec tree."""
    compiled = [(re.compile(pat), spec) for pat, spec in partition_rules]

    def resolve_one(path, leaf):
        shape = getattr(leaf, "shape", None)
        for pat, spec in compiled:
            if pat.search(path):
                # scanned-layer stacks carry a leading [L] axis not present in the
                # per-layer rule: prepend the `layers` logical axis (maps to pp).
                if shape is not None and len(shape) == len(spec) + 1 and (
                    "/layers/" in f"/{path}" or "/h/" in f"/{path}"
                ):
                    spec = PartitionSpec("layers", *spec)
                return resolve_spec(spec, mesh, logical_rules, shape)
        return PartitionSpec()

    paths = param_path_tree(tree)
    return jax.tree.map(resolve_one, paths, tree)


def sharding_tree(tree, partition_rules: PartitionRules, mesh: Mesh, logical_rules=None):
    specs = spec_tree_from_rules(tree, partition_rules, mesh, logical_rules)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)


def shard_params(params, partition_rules: PartitionRules, mesh: Mesh, logical_rules=None):
    """device_put a param tree according to its rules (host->HBM placement)."""
    shardings = sharding_tree(params, partition_rules, mesh, logical_rules)
    return jax.device_put(params, shardings)


def shard_constraint(x, logical_spec: PartitionSpec, mesh: Optional[Mesh] = None, logical_rules=None):
    """``with_sharding_constraint`` that understands logical names; no-op off-mesh."""
    if mesh is None:
        mesh = _current_mesh()
    if mesh is None:
        return x
    spec = resolve_spec(logical_spec, mesh, logical_rules, shape=np.shape(x))
    if all(s is None for s in spec):
        return x
    if isinstance(mesh, Mesh):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    # AbstractMesh (from jax.sharding.use_mesh context): bare specs are accepted
    return jax.lax.with_sharding_constraint(x, spec)


def _current_mesh():
    """Active mesh from the `set_mesh`/`use_mesh` context (concrete preferred)."""
    try:
        m = jax.sharding.get_mesh()  # concrete mesh if one was set
        if m is not None and isinstance(m, Mesh) and not m.empty:
            return m
    except Exception:
        pass
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is not None and not am.empty:
            return am
    except Exception:
        pass
    try:
        from jax._src import mesh as mesh_lib

        m = mesh_lib.thread_resources.env.physical_mesh
        return None if m.empty else m
    except Exception:
        return None


def logical_axis_size(name: str, mesh: Optional[Mesh] = None, rules=None) -> int:
    """Product of the mesh-axis sizes a logical axis currently maps to (1 off-mesh)."""
    mesh = mesh if mesh is not None else _current_mesh()
    rules = rules or active_logical_rules()
    return _axes_size(mesh, rules.get(name))


def batch_spec(extra_dims: int = 1) -> PartitionSpec:
    """Spec for (batch, seq, ...) activations/inputs: batch over data axes, seq over sep/cp."""
    return PartitionSpec(("dp", "fsdp"), ("sep", "cp"), *([None] * max(0, extra_dims - 2)))
