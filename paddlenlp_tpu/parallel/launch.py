"""Multi-host launch wiring.

Counterpart of the reference's process-launch contract (§2.13:
``paddle.distributed.launch`` + ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINER_ENDPOINTS``
env vars, ``fleet.init(is_collective=True)`` NCCL groups). TPU-native: one
``jax.distributed.initialize`` call per host; afterwards ``jax.devices()`` spans
the slice/pod and every mesh in this framework is global automatically — there
are no process groups to construct.

Env contract (auto-detected on Cloud TPU; explicit for manual launch):
- ``PDNLP_COORDINATOR`` (host:port of process 0)  [or JAX_COORDINATOR_ADDRESS]
- ``PDNLP_NUM_PROCESSES``                          [or JAX_NUM_PROCESSES]
- ``PDNLP_PROCESS_ID``                             [or JAX_PROCESS_ID]
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.log import logger

__all__ = ["init_distributed", "is_distributed_initialized", "local_batch_to_global"]

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed once per process; no-op on single host.

    Returns True when multi-host is active. Call BEFORE any jax device use
    (the trainer entry points call it first thing).
    """
    global _initialized
    if _initialized:
        return True
    import jax

    explicit = coordinator_address is not None
    coordinator_address = coordinator_address or os.environ.get("PDNLP_COORDINATOR") \
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
    explicit = explicit or coordinator_address is not None

    def _env_int(*names):
        for n in names:
            v = os.environ.get(n)
            if v not in (None, ""):
                return int(v)
        return None  # let jax auto-detect

    if num_processes is None:
        num_processes = _env_int("PDNLP_NUM_PROCESSES", "JAX_NUM_PROCESSES")
    if process_id is None:
        process_id = _env_int("PDNLP_PROCESS_ID", "JAX_PROCESS_ID")

    on_cloud_tpu = os.environ.get("TPU_WORKER_HOSTNAMES") not in (None, "", "localhost")
    if coordinator_address is None and not on_cloud_tpu:
        return False
    try:
        # None values are auto-detected by jax (Cloud TPU metadata / env)
        jax.distributed.initialize(coordinator_address, num_processes, process_id)
        _initialized = True
        logger.info(
            f"jax.distributed initialized: process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local / {jax.device_count()} global devices"
        )
        return True
    except Exception as e:
        if explicit:
            # an explicitly-configured multihost job silently running single-host
            # would duplicate data and clobber checkpoints — fail loudly
            raise RuntimeError(f"jax.distributed.initialize failed for coordinator "
                               f"{coordinator_address}: {e}") from e
        logger.warning(f"jax.distributed.initialize failed ({e}); continuing single-host")
        return False


def is_distributed_initialized() -> bool:
    return _initialized


def local_batch_to_global(host_batch, mesh, spec):
    """Assemble a global sharded array from this host's LOCAL batch shard.

    Multi-host replacement for the single-host ``device_put``: each process feeds
    only its own rows (the reference broadcasts batches over comm groups instead —
    dist_dataloader.py:135-205 — which a single-controller runtime doesn't need).
    """
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), host_batch
    )
