"""Pipeline parallelism — GSPMD spatial microbatch pipeline over the ``pp`` axis.

Counterpart of the reference's pipeline stack: per-model ``modeling_pp.py``
networks built from ``LayerDesc``/``SharedLayerDesc`` (e.g.
``paddlenlp/transformers/llama/modeling_pp.py:296``), the fleet 1F1B/interleave
runtime (``paddlenlp/trainer/trainer.py:2246`` ``training_pipeline_step``), and
the pp knobs (``training_args.py:1112-1170``).

TPU-native redesign — no second network definition, no schedule runtime:

- the scanned decoder stack's [L, ...] params are VIEWED as [S, L/S, ...] with
  the stage axis sharded over the mesh's ``pp`` axis (each pp rank holds its
  contiguous block of layers);
- every "tick" runs ALL stages in parallel (``vmap`` over the stage axis), each
  stage scanning its local layers over its current microbatch;
- between ticks, activations shift one stage forward; the stage-sharded shift
  (slice + concat on a pp-sharded dim) is lowered by GSPMD to
  ``collective-permute`` — the reference's P2P send/recv;
- stage 0 injects a fresh microbatch each tick, the last stage's outputs are
  collected after the (S-1)-tick fill.

Differentiating through the tick loop reverses it, yielding the backward
pipeline automatically (ppermute transposes to the opposite ring); per-layer
rematerialization keeps live activations at stage boundaries only. Fill/drain
bubble is (S-1)/(M+S-1) per direction — 1F1B's throughput shape for M >> S.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from .partition import _current_mesh

__all__ = ["spatial_pipeline", "stage_view"]


def _stage_constraint(x):
    """Constrain ONLY dim 0 onto the pp axis; every other dim stays UNCONSTRAINED
    (an omitted/None trailing dim in a PartitionSpec means REPLICATED, which would
    all-gather tp/fsdp-sharded params and dp-sharded activations every tick)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = _current_mesh()
    if mesh is None or mesh.shape.get("pp", 1) <= 1:
        return x
    spec = PartitionSpec("pp", *([PartitionSpec.UNCONSTRAINED] * (x.ndim - 1)))
    if isinstance(mesh, Mesh):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    return jax.lax.with_sharding_constraint(x, spec)


def stage_view(stacked_params: Any, n_stages: int) -> Any:
    """View stacked [L, ...] params as [S, L/S, ...], stage axis pp-sharded."""

    def split(x):
        L = x.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"num layers {L} not divisible by pipeline stages {n_stages}")
        x = x.reshape((n_stages, L // n_stages) + x.shape[1:])
        return _stage_constraint(x)

    return jax.tree.map(split, stacked_params)


def spatial_pipeline(
    layer_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    stream: Any,
    n_stages: int,
) -> Any:
    """Run ``layer_fn`` over all L layers of every microbatch, pipelined.

    Args:
      layer_fn: ``(layer_params, state) -> state`` — one decoder layer applied to
        one microbatch's state pytree (activations + anything that must travel
        with them: masks, positions, aux accumulators).
      stacked_params: pytree of [L, ...] leaves (the scanned decoder stack).
      stream: pytree of [M, ...] leaves — M microbatches of initial state.
      n_stages: S; must equal the mesh's pp-axis size and divide L.

    Returns the final-layer state for every microbatch, a pytree of [M, ...].
    """
    S = n_stages
    params_S = stage_view(stacked_params, S)
    M = jax.tree.leaves(stream)[0].shape[0]

    def stage_fn(stage_params, state):
        def body(carry, lp):
            return layer_fn(lp, carry), None

        state, _ = jax.lax.scan(body, state, stage_params)
        return state

    vstages = jax.vmap(stage_fn)

    def constrain_state(state):
        # dim 0 is the stage axis; inner dims stay UNCONSTRAINED so the layer
        # body's batch/seq shardings propagate through vmap untouched.
        return jax.tree.map(_stage_constraint, state)

    zeros_state = jax.tree.map(lambda x: jnp.zeros((S,) + x.shape[1:], x.dtype), stream)
    zeros_out = jax.tree.map(jnp.zeros_like, stream)

    def tick(carry, t):
        prev_out, outputs = carry
        # inject: stage 0 reads microbatch t (clamped during drain — the clamped
        # duplicates never reach the collected outputs, so they carry no gradient)
        inj = jax.tree.map(
            lambda x: jax.lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), axis=0, keepdims=False),
            stream,
        )
        # shift: new_state[0] = injected, new_state[s] = prev_out[s-1].
        # Expressed as a cyclic roll of the stage-sharded state (GSPMD lowers it
        # to one collective-permute) + a where-mask writing the replicated
        # injection into slot 0 — a concat of mixed-sharding operands would
        # instead force a replicate-repartition of the state every tick.
        def shift(i, p):
            rolled = jnp.roll(p, 1, axis=0)
            stage_idx = jnp.arange(S).reshape((S,) + (1,) * (p.ndim - 1))
            return jnp.where(stage_idx == 0, i[None].astype(p.dtype), rolled)

        state = jax.tree.map(shift, inj, prev_out)
        state = constrain_state(state)
        out = vstages(params_S, state)
        out = constrain_state(out)
        # collect the last stage's result at index t-(S-1). For t < S-1 the clip
        # writes warm-up garbage at index 0, overwritten by the valid write at
        # t = S-1 (ascending scan order guarantees the valid write lands last).
        idx = jnp.clip(t - (S - 1), 0)
        outputs = jax.tree.map(
            lambda o, v: jax.lax.dynamic_update_index_in_dim(o, v[-1].astype(o.dtype), idx, axis=0),
            outputs,
            out,
        )
        return (out, outputs), None

    (_, outputs), _ = jax.lax.scan(tick, (zeros_state, zeros_out), jnp.arange(M + S - 1))
    return outputs
