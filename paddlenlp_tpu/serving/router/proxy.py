"""HTTP front tier: health-aware forwarding with cross-replica failover.

``RouterServer`` sits in front of N ``ServingServer`` replicas and owns three
concerns the replicas cannot solve alone:

- **placement** — every request gets an ordered candidate list from the
  routing policy (least-loaded or prefix-affinity) over live pool snapshots;
- **re-routing** — a replica 429 (window full) / 503 (draining or its engine
  supervisor's circuit breaker) or a connect failure moves the request to the
  next candidate *before anything reaches the client*
  (``paddlenlp_router_rerouted_total``);
- **failover** — when a replica fails a request it had already accepted
  (transport drop mid-stream, or an in-band ``finish_reason="engine_error"``
  terminal), the router splits on whether the client has seen tokens:

  - **no tokens emitted** → the request is transparently resubmitted to the
    next healthy replica with the failed one excluded (bounded by
    ``max_attempts``; the client's SSE connection and the router-side timing
    anchors are preserved — the stream just pauses), counted in
    ``paddlenlp_router_failovers_total``;
  - **mid-stream** → regenerating would re-emit divergent tokens, so the
    stream finishes **in-band** with ``finish_reason="replica_error"`` and a
    usage block covering what was actually relayed — exactly the engine-loop
    supervisor's ``engine_error`` contract, one level up.

Upstream completion ids are rewritten to the router's own ``rtr-N`` ids so a
failover is invisible to the client; ``POST /v1/abort`` is routed back to
whichever replica currently owns the stream. The router's own observability
plane (``/metrics``, ``/health``, ``/debug/trace``) rides on the shared
registry/tracer machinery.

**Elastic membership (admin plane).** ``GET/POST /replicas``, ``POST
/replicas/drain`` and ``DELETE /replicas/{id}`` mutate the fleet live: a
joined replica is probed before it serves, a draining replica stops
receiving new requests while its in-flight streams finish (the router's own
open-forward count is the completion signal; a drain that outlives its
deadline fails the stuck token-less streams over via the ordinary pre-token
resubmit path — the client's SSE connection never notices), and removal is
refused with 409 until the drain lands. Membership mutations run through the
``router.membership`` fault point before any state changes.

**Request hedging.** With ``hedge_after_s`` set, a request whose primary
forward produced no first event (stream) or response (batch) inside the
budget races a shadow forward on the next ring candidate: both legs feed a
shared queue, nothing reaches the client until one leg produces a usable
event, the winner relays and the loser is aborted (socket close +
``/v1/abort`` for streams with a known upstream id; batch losers are freed by
their failed response write). Bounded by ``max_hedges_inflight``; counted in
``paddlenlp_router_hedges_total{outcome}``.
Deterministic (greedy / fixed-seed) sampling hedges token-exactly; hedging
free-running sampled requests serves whichever stream wins (see the README
for when not to hedge).

**Fleet observability.** The router is where per-process planes become one:

- every forward carries a traceparent-style header (trace id + parent span id
  + sampled flag), and the replica adopts the ``rtr-N`` id instead of minting
  its own — ``GET /debug/trace?trace=rtr-N`` then fetches the owning replica's
  spans and stitches them with the router's into one multi-process Chrome
  trace, correcting clock skew with the offset the health poller estimates
  from probe-RTT midpoints;
- the 1-in-N trace sampling decision (``trace_sample_every``) is made ONCE
  here, by deterministic hash of the trace id, and propagated in the header —
  unsampled requests take the tracer's no-op path in every tier;
- ``GET /fleet/metrics`` merges the replicas' expositions (re-labeled
  ``{replica="..."}``), and ``GET /fleet/slo`` computes multi-window
  availability + TTFT burn rates over the federated counters
  (``observability/slo.py``), exposed as ``paddlenlp_slo_*`` on the router's
  own ``/metrics``.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import http.client
import itertools
import json
import math
import os
import queue
import socket
import threading
import time
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, quote, unquote, urlsplit

from ...observability.exporter import route_observability
from ...observability.flight_recorder import RECORDER
from ...observability.goodput import WASTE_KINDS
from ...observability.postmortem import PostmortemDumper, handle_postmortem_request
from ...observability.slo import (
    DEFAULT_WINDOWS_S,
    SLOInputs,
    SLOObjectives,
    SLOTracker,
    slo_inputs_from_families,
)
from ...observability.usage import merge_aggregates
from ...observability.tracer import (
    TRACEPARENT_HEADER,
    TRACER,
    SpanTracer,
    format_traceparent,
    merge_chrome_traces,
    trace_sampled,
    use_trace,
)
from ...utils.faults import FaultPoint, InjectedFault
from ...utils.log import logger
from ..httputil import JsonRequestHandler
from ..metrics import REGISTRY, MetricsRegistry
from ...observability.prometheus import parse_prometheus_text
from .metrics import RouterMetrics, federate_families
from .policy import resolve_policy
from .pool import (
    DEGRADED,
    DOWN,
    HEALTHY,
    RECOVERING,
    DrainPendingError,
    ReplicaPool,
    ReplicaSnapshot,
)
from .pool import push_brownout as pool_push_brownout

__all__ = ["RouterServer"]

MAX_BODY_BYTES = 8 << 20

_F_FORWARD = FaultPoint("router.forward")

# fires at the top of each per-replica rollout step (before the drain): an
# injected fault must abort the whole rollout, roll swapped replicas back,
# and leave every replica serving traffic
_F_ROLLOUT = FaultPoint("router.rollout")


class _RolloutFailure(RuntimeError):
    """One replica's rollout step failed. ``reason`` draws from the
    ``rollout.abort`` closed enum (event_catalog.EVENT_REASONS)."""

    def __init__(self, reason: str, detail: str, replica: Optional[str] = None):
        super().__init__(detail)
        self.reason = reason
        self.replica = replica

#: transport-level failures on the upstream leg; InjectedFault rides along so
#: the router.forward fault point is handled exactly like a real socket error
_UPSTREAM_ERRORS = (OSError, http.client.HTTPException, InjectedFault)


def _force_close(conn, resp=None):
    """Tear down an upstream leg from ANOTHER thread. A plain ``close()``
    only drops the fd — a reader blocked in ``recv`` stays blocked;
    ``shutdown()`` is what actually wakes it with an error. The socket may
    live on the connection (keep-alive) or — after ``getresponse()`` on a
    will-close SSE response — only on the response's reader, so both are
    tried."""
    socks = [getattr(conn, "sock", None)] if conn is not None else []
    if resp is not None:
        raw = getattr(getattr(resp, "fp", None), "raw", None)
        socks.append(getattr(raw, "_sock", None))
    for sock in socks:
        if sock is None:
            continue
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
    for obj in (resp, conn):
        if obj is None:
            continue
        try:
            obj.close()
        except Exception:
            pass


def _read_sse_events(resp):
    """Parse one upstream SSE leg into ``("event", dict)`` / ``("done", None)``
    / ``("broke", err|None)`` items. "broke" covers transport errors AND a
    close without ``[DONE]`` (a crash, not a completion); the iterator always
    ends with a non-"event" item. ValueError joins the transport errors here
    because the connection may be closed under the reader on purpose (drain
    eviction, hedge-loser teardown)."""
    while True:
        try:
            line = resp.readline()
        except _UPSTREAM_ERRORS + (ValueError, AttributeError) as e:
            # ValueError/AttributeError: the response was closed UNDER the
            # reader (concurrent teardown races http.client's own close)
            yield ("broke", e)
            return
        if not line:
            yield ("broke", None)
            return
        line = line.strip()
        if not line.startswith(b"data: "):
            continue
        data = line[len(b"data: "):]
        if data == b"[DONE]":
            yield ("done", None)
            return
        try:
            ev = json.loads(data)
        except ValueError:
            continue
        yield ("event", ev)


@dataclasses.dataclass
class _Disposition:
    """How one upstream failure maps onto the router's attempt vocabulary."""

    outcome: str  # "reroute" | "failover" | "relay"
    replica_fault: bool = False  # demote the replica (skipped while draining)
    is_degraded: bool = False  # the replica said 503: note_degraded
    degraded_retry_after: Optional[str] = None  # raw Retry-After header, if any
    status: Optional[int] = None  # relay: verbatim status ...
    raw: bytes = b""  # ... and body

    def retry_after_s(self) -> Optional[float]:
        # RFC 7231 also allows an HTTP-date here; a non-numeric value from a
        # proxy in front of the replica degrades to "no hint", never a crash
        # on the relay path
        try:
            return float(self.degraded_retry_after) if self.degraded_retry_after else None
        except (TypeError, ValueError):
            return None


def _is_request_level_503(raw) -> bool:
    """True when a 503 body says the replica rejected THIS request's class
    (brownout shed / deadline-unmet), not that the replica itself is
    draining/degraded. Unparseable bodies count as replica-level (the
    conservative reading)."""
    try:
        etype = json.loads(raw or b"").get("error", {}).get("type")
    except (ValueError, AttributeError):
        return False
    return etype in ("overloaded_shed", "deadline_unmet")


def _classify_upstream_failure(kind: str, payload) -> _Disposition:
    """THE single upstream-failure → disposition mapper.

    Every way a replica can fail the router — batch or stream, plain or
    hedged leg — funnels through here with one of four failure kinds:

    - ``connect_failed``: transport error before/at the response (payload =
      the exception). Replica fault → re-route, demote (unless draining).
    - ``status``: non-200 HTTP status (payload = ``(status, raw_body,
      retry_after_header)``). 429/503 are *backpressure*, not fault →
      re-route and (503) mark degraded; ≥500 means accepted-then-failed →
      failover; anything else is the replica judging the REQUEST itself bad
      (400/413) → relay verbatim, another replica would say the same.
    - ``engine_error``: in-band supervisor give-up or an unparseable body.
      Accepted-then-failed → failover, and a replica fault for dead-leg
      accounting.
    - ``broke``: transport drop / close without ``[DONE]``. Same disposition
      as ``engine_error``.

    The *application* differs by context — an attempt's outcome switch
    already demotes on "failover" (:meth:`RouterServer._apply_failure`), a
    dead hedge leg never reaches that switch so it applies the
    ``replica_fault`` flag itself (:meth:`RouterServer._note_dead_leg`) —
    but the classification is written exactly once."""
    if kind == "connect_failed":
        return _Disposition("reroute", replica_fault=True)
    if kind == "status":
        status, raw, retry_after = payload
        if status in (429, 503):
            # per-REQUEST rejections (brownout shed of this priority class,
            # deadline-unmet on arrival) come from a healthy replica doing
            # its job — re-route in case another replica isn't browned out,
            # but never mark the replica degraded: a fleet-wide brownout
            # must not flap every healthy replica to DEGRADED
            return _Disposition(
                "reroute",
                is_degraded=status == 503 and not _is_request_level_503(raw),
                degraded_retry_after=retry_after)
        if status >= 500:
            return _Disposition("failover", replica_fault=True, status=status)
        return _Disposition("relay", status=status, raw=raw or b"")
    # engine_error / broke: the replica accepted the request, then failed it
    # before anything usable was relayed
    return _Disposition("failover", replica_fault=True)


class _RelayState:
    """Per-request relay bookkeeping shared across forward attempts. One
    instance per client request, written only by that request's handler
    thread. The drain enforcer (poller thread) additionally READS
    ``replica_id``/``tokens_relayed`` and closes ``upstream_conn`` to break a
    stuck read on a past-deadline draining replica — closing a socket that
    just finished or was replaced is a benign no-op, so these cross-thread
    touches need no lock."""

    __slots__ = ("rid", "stream", "headers_sent", "tokens_relayed", "arrival_t",
                 "attempts", "finished", "sampled", "replica_id", "upstream_conn",
                 "upstream_resp", "upstream_cid", "weights_version",
                 "upstream_path")

    def __init__(self, rid: str, stream: bool, sampled: bool = True,
                 upstream_path: str = "/v1/completions"):
        self.rid = rid
        self.stream = stream
        # which replica endpoint every forward attempt of this request hits
        # (/v1/completions, or /v1/chat/completions for chat requests — the
        # replica re-renders the conversation itself, so failover resubmits
        # the original chat body unchanged)
        self.upstream_path = upstream_path
        self.headers_sent = False
        self.tokens_relayed = 0
        self.arrival_t = time.perf_counter()  # original timing anchor
        self.attempts = 0
        self.finished = False  # a finish_reason chunk was relayed to the client
        self.sampled = sampled  # head-based trace sampling decision
        self.replica_id: Optional[str] = None  # replica of the current attempt
        self.upstream_conn = None  # live upstream HTTPConnection (drain eviction)
        self.upstream_resp = None  # its HTTPResponse (owns the socket once read)
        self.upstream_cid: Optional[str] = None  # upstream cmpl-N id once seen
        # base-weight version of the pinned replica at attempt start: a
        # mid-stream death during a fleet rollout terminates as version_skew
        # (not replica_error) when the stream's version is no longer served
        self.weights_version: Optional[str] = None


class RouterServer:
    """Multi-replica front tier over the replica pool + routing policy."""

    def __init__(self, replicas=(), pool: Optional[ReplicaPool] = None,
                 policy="least_loaded", registry: Optional[MetricsRegistry] = None,
                 max_attempts: int = 3, max_body_bytes: int = MAX_BODY_BYTES,
                 poll_interval_s: float = 1.0, probe_timeout_s: float = 2.0,
                 upstream_timeout_s: float = 600.0,
                 trace_sample_every: int = 1,
                 tracer: Optional[SpanTracer] = None,
                 slo_objectives: Optional[SLOObjectives] = None,
                 slo_windows_s: Sequence[float] = DEFAULT_WINDOWS_S,
                 scrape_timeout_s: float = 5.0,
                 hedge_after_s: Optional[float] = None,
                 max_hedges_inflight: int = 4,
                 brownout_push_level: Optional[int] = 1):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if trace_sample_every < 1:
            raise ValueError("trace_sample_every must be >= 1")
        if hedge_after_s is not None and hedge_after_s <= 0:
            raise ValueError("hedge_after_s must be > 0 (None disables hedging)")
        if max_hedges_inflight < 0:
            raise ValueError("max_hedges_inflight must be >= 0")
        self.registry = registry or REGISTRY
        # a private tracer keeps router spans out of in-process replicas' rings
        # (the launcher passes one); a dedicated router process uses the global
        self.tracer = tracer if tracer is not None else TRACER
        self.trace_sample_every = trace_sample_every
        self.scrape_timeout_s = scrape_timeout_s
        self.metrics = RouterMetrics(self.registry)
        self.slo = SLOTracker(objectives=slo_objectives, windows_s=slo_windows_s,
                              registry=self.registry)
        # router-tier black box: drain-deadline evictions and SLO fast burns
        # auto-dump a bundle (opt-in via PDNLP_TPU_POSTMORTEM_DIR); on demand
        # via POST /debug/postmortem. The process-wide flight recorder is
        # shared with in-process replicas, so a router bundle already joins
        # both tiers' decision events on the trace id.
        self.postmortem = PostmortemDumper(
            registry=self.registry, tracer=self.tracer, tier="router",
            health_fn=self._postmortem_health, config_fn=self._postmortem_config)
        self.slo.on_fast_burn = self._on_fast_burn
        self.pool = pool if pool is not None else ReplicaPool(
            metrics=self.metrics, poll_interval_s=poll_interval_s,
            probe_timeout_s=probe_timeout_s, tracer=self.tracer)
        if self.pool.metrics is None:
            self.pool.metrics = self.metrics
        for spec in replicas:
            self.pool.add(spec[0], int(spec[1]), *spec[2:3])
        self.policy = resolve_policy(policy)
        self.max_attempts = max_attempts
        self.max_body_bytes = max_body_bytes
        self.upstream_timeout_s = upstream_timeout_s
        # hedging: after hedge_after_s with no first token, race a shadow
        # request on the next candidate (None = off); the cap bounds how many
        # shadows the router may have open at once fleet-wide
        self.hedge_after_s = hedge_after_s
        self.max_hedges_inflight = max_hedges_inflight
        # SLO fast burn -> replica brownout push: the same best-effort
        # propagation channel drains use (None disables). Rate-limited so a
        # sustained burn costs one push per window, not one per scrape.
        self.brownout_push_level = brownout_push_level
        self._brownout_push_lock = threading.Lock()
        self._last_brownout_push_t = 0.0  # guarded-by: _brownout_push_lock
        self._hedge_lock = threading.Lock()
        self._hedges_inflight = 0  # guarded-by: _hedge_lock
        self._ids = itertools.count()
        self._live: Dict[str, Tuple[str, str]] = {}  # rid -> (replica_id, upstream cid)
        self._live_lock = threading.Lock()
        # relay states with an attempt in flight (drain-deadline eviction
        # walks this to find token-less streams on the draining replica)
        self._active: set = set()  # guarded-by: _live_lock
        # membership hooks: drain completion tracks the router's own open
        # forwards; the deadline hook fails stuck token-less streams over
        self.pool.drain_live = self._open_forwards_on
        self.pool.on_drain_deadline = self._drain_deadline_failover
        # trace id -> owning replica, SURVIVING request finish (stitching a
        # trace is most useful after the request completed); bounded LRU
        self._trace_owner: "OrderedDict[str, str]" = OrderedDict()
        self._trace_owner_cap = 1024
        # router-side in-flight per replica: the poller's inflight reading is
        # up to a poll interval stale, so a burst arriving between polls would
        # all see the same "least-loaded" replica — forwards the router itself
        # has open are folded into the score instead
        self._forward_inflight: Dict[str, int] = {}
        self._inflight_lock = threading.Lock()
        # rolling weight rollout: one at a time fleet-wide; the state doc is
        # what GET /admin/weights/rollout (and /replicas) report
        self._rollout_lock = threading.Lock()
        self._rollout: Optional[Dict] = None  # guarded-by: _rollout_lock
        self._rollout_thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------- routing
    def _candidates(self, prompt, exclude: set, state: _RelayState,
                    adapter_id: Optional[str] = None,
                    conversation: Optional[str] = None) -> List[ReplicaSnapshot]:
        """One routing decision: snapshot the pool, let the policy order it.
        Re-run per attempt so health transitions observed mid-request (a
        candidate marked DOWN by the poller) are honored immediately.
        ``adapter_id`` feeds adapter affinity and ``conversation`` feeds
        conversation stickiness (forwarded only when present, and dropped
        for policies predating the kwargs)."""
        t0 = time.perf_counter()
        with self.tracer.span("route", cat="router", trace=state.rid,
                              attempt=state.attempts, excluded=len(exclude)) as sp:
            snaps = self._adjusted_snapshots()
            kw = {}
            if adapter_id is not None:
                kw["adapter_id"] = adapter_id
            if conversation is not None:
                kw["conversation"] = conversation
            try:
                candidates = self.policy.select(snaps, prompt=prompt,
                                                exclude=frozenset(exclude), **kw)
            except TypeError:
                if not kw:
                    raise
                # custom policy without the affinity kwargs: route on prompt only
                candidates = self.policy.select(snaps, prompt=prompt,
                                                exclude=frozenset(exclude))
            sp.set(candidates=[c.id for c in candidates[:4]])
        self.metrics.route_decision.observe(time.perf_counter() - t0)
        return candidates

    def _adjusted_snapshots(self) -> List[ReplicaSnapshot]:
        with self._inflight_lock:
            fly = {k: v for k, v in self._forward_inflight.items() if v > 0}
        if not fly:
            return self.pool.snapshots()
        return [dataclasses.replace(s, inflight=s.inflight + fly.get(s.id, 0))
                for s in self.pool.snapshots()]

    def _inflight_delta(self, replica_id: str, delta: int):
        with self._inflight_lock:
            cur = self._forward_inflight.get(replica_id)
            if cur is None and delta < 0:
                # the replica was force-removed (entry popped) while this
                # forward was still open: recreating the key at a negative
                # value would poison the drain-completion signal for a
                # re-added id of the same name
                return
            self._forward_inflight[replica_id] = max((cur or 0) + delta, 0)

    def _open_forwards_on(self, replica_id: str) -> int:
        """Forwards the router currently has open against one replica — the
        pool's drain-completion signal (covers streams from accept to finish,
        including legs that have not produced an event yet)."""
        with self._inflight_lock:
            return self._forward_inflight.get(replica_id, 0)

    # ------------------------------------------------------------- drain eviction
    def _drain_deadline_failover(self, replica_id: str):
        """A drain outlived its deadline: break every TOKEN-LESS stream still
        pinned to the draining replica so its relay takes the ordinary
        pre-token resubmit path onto a surviving candidate (the client's SSE
        connection never notices). Streams that already relayed tokens are
        actively progressing and are left to finish — regenerating them
        elsewhere would diverge the stream. Runs on the pool's poller thread."""
        with self._live_lock:
            victims = [(st, st.upstream_conn, st.upstream_resp, st.upstream_cid)
                       for st in self._active
                       if st.replica_id == replica_id and st.tokens_relayed == 0]
        evicted = 0
        for st, conn, resp, cid in victims:
            if st.replica_id != replica_id or st.tokens_relayed != 0:
                # the relay moved on between the snapshot and now — failed
                # over to a survivor, or relayed its first token (the abort
                # call for an earlier victim can take seconds): a token-
                # bearing stream is exactly what the drain promises to leave
                # alone, and a failed-over one owns a new leg we must not break
                continue
            # relay read breaks -> pre-token failover
            _force_close(conn, resp)
            if cid is not None:
                # also free the replica-side slot/KV promptly (a queued
                # request would otherwise only notice on its first write).
                # Off-thread: this runs on the pool's POLLER thread, and a
                # wedged replica — the usual reason a deadline fired — would
                # otherwise stall every health probe for the abort timeout
                replica = self.pool.get(replica_id)
                if replica is not None:
                    threading.Thread(
                        target=self._abort_replica_request,
                        args=(replica.host, replica.port, cid),
                        daemon=True, name=f"drain-abort-{st.rid}").start()
            evicted += 1
            RECORDER.record("router.drain_evict", trace=st.rid, replica=replica_id)
            self.tracer.instant("membership", cat="router", op="drain_evict",
                                trace=st.rid, replica=replica_id)
        if evicted:
            # a drain that had to break streams is an incident worth a black
            # box (rate-limited; opt-in via PDNLP_TPU_POSTMORTEM_DIR)
            self.postmortem.dump("drain_evict", detail={
                "replica": replica_id, "evicted_streams": evicted})

    def _abort_replica_request(self, host: str, port: int, upstream_cid: str) -> bool:
        """POST /v1/abort for one upstream completion id (best effort)."""
        try:
            conn = http.client.HTTPConnection(host, port, timeout=10)
            try:
                conn.request("POST", "/v1/abort",
                             body=json.dumps({"id": upstream_cid}).encode(),
                             headers={"Content-Type": "application/json"})
                body = json.loads(conn.getresponse().read() or b"{}")
            finally:
                conn.close()
            return bool(body.get("cancelled"))
        except _UPSTREAM_ERRORS + (ValueError,) as e:
            logger.debug(f"router: upstream abort of {upstream_cid} failed: {e!r}")
            return False

    # ------------------------------------------------------------- hedge slots
    def _try_start_hedge(self) -> bool:
        with self._hedge_lock:
            if self._hedges_inflight >= self.max_hedges_inflight:
                return False
            self._hedges_inflight += 1
            return True

    def _release_hedge(self):
        with self._hedge_lock:
            self._hedges_inflight -= 1

    def _finish(self, state: _RelayState, replica_id: str, outcome: str):
        self.metrics.requests.inc(replica=replica_id, outcome=outcome)
        # NOT named "request": that name is the engine loop's per-request
        # timeline span, and /debug/trace consumers select by name
        self.tracer.add_span("router_request", self.tracer.epoch_time(state.arrival_t),
                             time.perf_counter() - state.arrival_t, cat="router",
                             trace=state.rid, replica=replica_id, outcome=outcome,
                             attempts=state.attempts, tokens=state.tokens_relayed)
        if replica_id != "none":
            self._note_owner(state.rid, replica_id)
        with self._live_lock:
            self._live.pop(state.rid, None)

    def _note_owner(self, rid: str, replica_id: str):
        with self._live_lock:
            self._trace_owner[rid] = replica_id
            self._trace_owner.move_to_end(rid)
            while len(self._trace_owner) > self._trace_owner_cap:
                self._trace_owner.popitem(last=False)

    def _track(self, state: _RelayState, replica_id: str, upstream_cid: str):
        with self._live_lock:
            self._live[state.rid] = (replica_id, upstream_cid)
        self._note_owner(state.rid, replica_id)

    # ------------------------------------------------------------- abort
    def abort(self, rid: str) -> bool:
        """Route a client abort to whichever replica owns the stream now."""
        with self._live_lock:
            owner = self._live.get(rid)
        if owner is None:
            return False
        replica_id, upstream_cid = owner
        replica = self.pool.get(replica_id)
        if replica is None:
            return False
        ok = self._abort_replica_request(replica.host, replica.port, upstream_cid)
        if not ok:
            logger.warning(f"router: abort of {rid} on {replica_id} failed")
        return ok

    # ------------------------------------------------------------- http plumbing
    def _make_httpd(self, host: str, port: int) -> ThreadingHTTPServer:
        router = self

        class Handler(JsonRequestHandler):
            log_prefix = "router"

            @property
            def max_body_bytes(self):  # live read: the cap is router-tunable
                return router.max_body_bytes

            def do_GET(self):
                try:
                    parts = urlsplit(self.path)
                    stitch_trace = None
                    if parts.path == "/debug/trace":
                        query = parse_qs(parts.query)
                        # a since_ts cursor means an incremental scrape of the
                        # router's own ring (route_observability contract) —
                        # only plain ?trace= requests pay for a two-tier stitch
                        if "since_ts" not in query:
                            stitch_trace = query.get("trace", [None])[0]
                    if stitch_trace is not None:
                        # two-tier stitch when the owning replica is known;
                        # falls back to the router-only timeline otherwise
                        doc = router.stitched_trace(stitch_trace)
                        self._send_raw(200, json.dumps(doc).encode(), "application/json")
                        return
                    if parts.path == "/fleet/metrics":
                        text, _skipped = router.fleet_metrics()
                        self._send_raw(200, text.encode(),
                                       "text/plain; version=0.0.4; charset=utf-8")
                        return
                    if parts.path == "/fleet/slo":
                        self._send_json(200, router.fleet_slo())
                        return
                    if parts.path == "/debug/efficiency":
                        self._send_json(200, router.fleet_efficiency())
                        return
                    if parts.path == "/fleet/usage":
                        self._send_json(200, router.fleet_usage())
                        return
                    if parts.path == "/replicas":
                        self._send_json(200, router.admin_list_replicas())
                        return
                    if parts.path == "/admin/weights/rollout":
                        self._send_json(200, {"rollout": router.rollout_status()})
                        return
                    routed = route_observability(self.path, router.registry, router.tracer)
                    if routed is not None:
                        self._send_raw(routed[0], routed[2], routed[1])
                    elif self.path == "/health":
                        status, code = router.health_status()
                        self._send_json(code, {
                            "status": status,
                            "policy": getattr(router.policy, "name", type(router.policy).__name__),
                            "replicas": [s.to_dict() for s in router.pool.snapshots()],
                        })
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    logger.debug("router: client disconnected during GET")

            def do_POST(self):
                try:
                    if self.path == "/v1/completions":
                        payload = self._read_body()
                        if payload is not None:
                            router._handle_completion(self, payload)
                    elif self.path == "/v1/chat/completions":
                        payload = self._read_body()
                        if payload is not None:
                            router._handle_completion(self, payload, chat=True)
                    elif self.path == "/v1/abort":
                        payload = self._read_body()
                        if payload is not None:
                            ok = router.abort(str(payload.get("id", "")))
                            self._send_json(200, {"id": payload.get("id"), "cancelled": ok})
                    elif self.path == "/replicas":
                        payload = self._read_body()
                        if payload is not None:
                            code, doc = router.admin_add_replica(payload)
                            self._send_json(code, doc)
                    elif self.path == "/replicas/drain":
                        payload = self._read_body()
                        if payload is not None:
                            code, doc = router.admin_drain_replica(payload)
                            self._send_json(code, doc)
                    elif self.path == "/admin/adapters":
                        payload = self._read_body()
                        if payload is not None:
                            code, doc = router.admin_adapters_fleet(payload)
                            self._send_json(code, doc)
                    elif self.path == "/admin/weights/rollout":
                        payload = self._read_body()
                        if payload is not None:
                            code, doc = router.admin_weights_rollout(payload)
                            self._send_json(code, doc)
                    elif self.path.split("?", 1)[0] == "/debug/postmortem":
                        # drain any request body first (keep-alive hygiene)
                        n = int(self.headers.get("Content-Length") or 0)
                        if n:
                            self.rfile.read(n)
                        routed = handle_postmortem_request(self.path, router.postmortem)
                        self._send_raw(routed[0], routed[2], routed[1])
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    logger.debug("router: client disconnected during POST")
                except Exception as e:
                    # includes an injected router.membership fault: the admin
                    # mutation fired BEFORE any state change, so a clean 500
                    # here means the pool is exactly as it was
                    logger.warning(f"router: error on {self.path}: {e!r}")
                    try:
                        self._send_error_json(500, str(e), "internal_error")
                    except (BrokenPipeError, ConnectionResetError):
                        pass

            def do_DELETE(self):
                try:
                    parts = urlsplit(self.path)
                    if parts.path.startswith("/replicas/"):
                        rid = unquote(parts.path[len("/replicas/"):])
                        force = parse_qs(parts.query).get("force", ["0"])[0] \
                            in ("1", "true")
                        code, doc = router.admin_remove_replica(rid, force=force)
                        self._send_json(code, doc)
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    logger.debug("router: client disconnected during DELETE")
                except Exception as e:
                    logger.warning(f"router: error on {self.path}: {e!r}")
                    try:
                        self._send_error_json(500, str(e), "internal_error")
                    except (BrokenPipeError, ConnectionResetError):
                        pass

        httpd = ThreadingHTTPServer((host, port), Handler)
        httpd.daemon_threads = True
        return httpd

    # ------------------------------------------------------------- admin plane
    def admin_list_replicas(self) -> Dict:
        """Live membership view: every pooled replica's snapshot + drain
        status + the router's own open forwards, plus removal tombstones."""
        replicas = []
        for snap in self.pool.snapshots():
            doc = snap.to_dict()
            doc["drain"] = self.pool.drain_status(snap.id)
            doc["open_forwards"] = self._open_forwards_on(snap.id)
            replicas.append(doc)
        return {"replicas": replicas, "removed": self.pool.removed(),
                # mixed-version visibility: per-replica weights_version above,
                # plus the rollout (if any) responsible for the mix
                "rollout": self.rollout_status()}

    def admin_add_replica(self, payload: dict) -> Tuple[int, Dict]:
        """POST /replicas {"host", "port", "id"?}: join a replica to the pool.
        One synchronous poll sweep runs before the 200 so the first routing
        decision already sees the newcomer's real health/load."""
        host, port = payload.get("host"), payload.get("port")
        if not host or not port:
            return 400, {"error": {"message": "host and port are required",
                                   "type": "invalid_request", "code": 400}}
        try:
            port = int(port)
        except (TypeError, ValueError):
            # validated BEFORE pool.add so a malformed port cannot masquerade
            # as the duplicate-id 409 (an autoscaler treats 409 as "present")
            return 400, {"error": {"message": f"port must be an integer, got {port!r}",
                                   "type": "invalid_request", "code": 400}}
        try:
            replica = self.pool.add(str(host), port,
                                    str(payload["id"]) if payload.get("id") else None)
        except ValueError as e:
            return 409, {"error": {"message": str(e),
                                   "type": "already_registered", "code": 409}}
        self.metrics.membership_changes.inc(op="add")
        self.pool.probe_one(replica.id)
        return 200, {"replica": replica.snapshot().to_dict()}

    def admin_drain_replica(self, payload: dict) -> Tuple[int, Dict]:
        """POST /replicas/drain {"id", "deadline_s"?}: stop offering the
        replica new requests; in-flight streams finish (token-less ones are
        failed over once the deadline expires). DELETE completes the exit."""
        rid = str(payload.get("id", ""))
        try:
            deadline_s = float(payload.get("deadline_s", 30.0))
            if not math.isfinite(deadline_s):
                # json.loads admits NaN/Infinity, and a NaN deadline never
                # compares past due — the drain would be un-completable
                raise ValueError
        except (TypeError, ValueError):
            return 400, {"error": {
                "message": f"deadline_s must be a finite number, got {payload.get('deadline_s')!r}",
                "type": "invalid_request", "code": 400}}
        try:
            status = self.pool.start_drain(rid, deadline_s=deadline_s)
        except KeyError:
            return 404, {"error": {"message": f"unknown replica {rid!r}",
                                   "type": "unknown_replica", "code": 404}}
        self.metrics.membership_changes.inc(op="drain")
        # replica-side propagation: tell the ServingServer itself so DIRECT
        # traffic (clients bypassing the router) also sees 503 + Retry-After.
        # Best-effort off-thread — a wedged replica must not stall the admin
        # plane, and the router-side drain is already in force either way
        replica = self.pool.get(rid)
        if replica is not None:
            threading.Thread(
                target=self._propagate_drain,
                args=(replica.host, replica.port, deadline_s),
                daemon=True, name=f"drain-propagate-{rid}").start()
        return 200, {"drain": status}

    def _propagate_drain(self, host: str, port: int, deadline_s: float) -> bool:
        """POST /admin/drain on the draining replica (best effort)."""
        try:
            conn = http.client.HTTPConnection(host, port, timeout=10)
            try:
                conn.request("POST", "/admin/drain",
                             body=json.dumps({"retry_after_s": deadline_s}).encode(),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
            finally:
                conn.close()
            return resp.status == 200
        except _UPSTREAM_ERRORS + (ValueError,) as e:
            logger.debug(f"router: drain propagation to {host}:{port} failed: {e!r}")
            return False

    def admin_remove_replica(self, rid: str, force: bool = False) -> Tuple[int, Dict]:
        """DELETE /replicas/{id}[?force=1]: take a drained (or DOWN) replica
        out of the pool; 409 while its drain is still in progress."""
        try:
            tomb = self.pool.remove(rid, force=force)
        except KeyError:
            return 404, {"error": {"message": f"unknown replica {rid!r}",
                                   "type": "unknown_replica", "code": 404}}
        except DrainPendingError as e:
            return 409, {"error": {"message": str(e),
                                   "type": "drain_pending", "code": 409}}
        self.metrics.membership_changes.inc(op="remove")
        with self._inflight_lock:
            # drop the (zero, by drain-completion) accounting entry — one
            # leaked key per scale-down would accumulate under churn
            self._forward_inflight.pop(rid, None)
        return 200, {"replica": tomb}

    def health_status(self) -> Tuple[str, int]:
        states = {s.state for s in self.pool.snapshots()}
        if states & {HEALTHY, RECOVERING}:
            return "ok", 200
        if DEGRADED in states:
            # still routable — the breaker may lift between poll and forward
            return "degraded", 200
        return "unhealthy", 503

    # ------------------------------------------------------------- fleet planes
    def _scrape_replica(self, snap: ReplicaSnapshot, path: str) -> str:
        conn = http.client.HTTPConnection(snap.host, snap.port,
                                          timeout=self.scrape_timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            text = resp.read().decode()
        finally:
            conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{snap.id}{path}: HTTP {resp.status}")
        return text

    def fleet_families(self) -> Tuple[Dict[str, Dict], List[str]]:
        """Scrape + parse every non-DOWN replica's ``/metrics``. Returns
        ``({replica_id: parsed families}, [skipped ids])`` — a dead,
        unreachable, or unparseable replica shrinks the merge, it never fails
        it (partial fleet data beats no fleet data during exactly the
        incidents you scrape during). Scrapes run concurrently: one wedged
        replica that the poller hasn't demoted yet costs the whole merge one
        scrape timeout, not a timeout per bad replica."""
        out: Dict[str, Dict] = {}
        skipped: List[str] = []

        def scrape(snap):
            return snap.id, parse_prometheus_text(
                self._scrape_replica(snap, "/metrics"))

        live = []
        for snap in self.pool.snapshots():
            if snap.state == DOWN:
                skipped.append(snap.id)
            else:
                live.append(snap)
        if not live:
            return out, skipped
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(live))) as pool:
            futures = {pool.submit(scrape, s): s for s in live}
            for fut in concurrent.futures.as_completed(futures):
                snap = futures[fut]
                try:
                    rid, fams = fut.result()
                    out[rid] = fams
                except Exception as e:
                    logger.warning(f"router: fleet scrape of {snap.id} failed: {e!r}")
                    self.metrics.fleet_scrape_errors.inc(replica=snap.id)
                    skipped.append(snap.id)
        return out, skipped

    def fleet_metrics(self) -> Tuple[str, List[str]]:
        """Federated exposition: every replica's samples under one scrape,
        re-labeled ``{replica="..."}``."""
        parsed, skipped = self.fleet_families()
        return federate_families(parsed), skipped

    def fleet_slo(self) -> Dict:
        """Scrape → fold → burn rates. Each call is one SLO observation; the
        tracker's history turns successive scrapes into windowed rates."""
        parsed, skipped = self.fleet_families()
        inputs = SLOInputs()
        for fams in parsed.values():
            inputs = inputs + slo_inputs_from_families(fams, self.slo.objectives)
        now = time.time()
        self.slo.observe(inputs, now=now)
        report = self.slo.report(now=now)
        report["replicas"] = sorted(parsed)
        report["skipped"] = skipped
        stages = self._fold_stage_series(parsed)
        if stages:
            # disaggregated replicas: TTFT and inter-token latency come from
            # different pools — surface both pressures in the SLO view so an
            # operator sees WHICH stage is burning budget
            report["stages"] = stages
        goodput = self._fold_goodput_series(parsed)
        if goodput:
            # per-replica device efficiency in the same fleet view the
            # autoscaler and on-call dashboards already scrape: an SLO burn
            # with a healthy goodput is a capacity problem; one with a
            # collapsing goodput is a padding/retrace/waste problem
            report["goodput"] = goodput
        return report

    @staticmethod
    def _fold_goodput_series(parsed: Dict[str, Dict]) -> Dict:
        """Fleet fold of the goodput-ledger counters each replica exports:
        per-replica useful/fed ratio + the fleet-wide waste decomposition.
        Empty when no replica exposes the ledger series (mixed-version
        fleets degrade to the old report shape)."""
        per_replica: Dict[str, Dict] = {}
        fleet_fed = fleet_useful = 0.0
        wasted: Dict[str, float] = {}
        for rid, fams in parsed.items():
            fed_fam = fams.get("paddlenlp_serving_fed_tokens_total")
            if fed_fam is None:
                continue
            fed = fed_fam.value() or 0.0
            useful_fam = fams.get("paddlenlp_serving_useful_tokens_total")
            useful = (useful_fam.value() or 0.0) if useful_fam is not None else 0.0
            per_replica[rid] = {
                "fed_tokens": fed,
                "useful_tokens": useful,
                "goodput_ratio": round(useful / fed, 6) if fed else 1.0,
            }
            fleet_fed += fed
            fleet_useful += useful
            waste_fam = fams.get("paddlenlp_serving_wasted_tokens_total")
            if waste_fam is not None:
                for (_sample, labels), v in waste_fam.samples.items():
                    kind = dict(labels).get("kind")
                    if kind:
                        wasted[kind] = wasted.get(kind, 0.0) + v
        if not per_replica:
            return {}
        return {
            "replicas": per_replica,
            "fleet": {
                "fed_tokens": fleet_fed,
                "useful_tokens": fleet_useful,
                "goodput_ratio": round(fleet_useful / fleet_fed, 6) if fleet_fed else 1.0,
                "wasted_tokens": {k: wasted[k] for k in sorted(wasted)},
            },
        }

    def fleet_efficiency(self) -> Dict:
        """Router-tier ``GET /debug/efficiency``: every live replica's
        efficiency doc plus a fed-token-weighted fleet goodput summary. A
        replica that fails the scrape is listed in ``skipped`` — the fold
        degrades, it never 500s (the /fleet/metrics contract)."""
        docs: Dict[str, Dict] = {}
        skipped: List[str] = []
        for snap in self.pool.snapshots():
            if snap.state == DOWN:
                skipped.append(snap.id)
                continue
            try:
                docs[snap.id] = json.loads(
                    self._scrape_replica(snap, "/debug/efficiency"))
            except Exception as e:
                logger.warning(
                    f"router: efficiency scrape of {snap.id} failed: {e!r}")
                skipped.append(snap.id)
        fed = useful = 0
        wasted: Dict[str, int] = {}
        for doc in docs.values():
            totals = ((doc.get("ledger") or {}).get("totals")) or {}
            fed += totals.get("fed", 0)
            useful += totals.get("useful", 0)
            for kind in WASTE_KINDS:
                if totals.get(kind):
                    wasted[kind] = wasted.get(kind, 0) + totals[kind]
        return {
            "tier": "router",
            "replicas": docs,
            "skipped": skipped,
            "fleet": {
                "fed_tokens": fed,
                "useful_tokens": useful,
                "goodput_ratio": round(useful / fed, 6) if fed else 1.0,
                "wasted_tokens": wasted,
            },
        }

    def fleet_usage(self) -> Dict:
        """Router-tier ``GET /fleet/usage``: every live replica's rolling
        usage aggregate plus a fleet sum per tenant/adapter. Same degrade
        contract as the other fleet planes: a failed scrape lands the replica
        in ``skipped`` and shrinks the fold — never a 500. NOTE this is the
        *rolling* (per-replica-lifetime) view: a request that failed over
        mid-stream may appear on two replicas; the offline
        ``tools/usage_report.py`` merge over the durable ledgers dedups by
        record id and is the billing-authoritative number."""
        docs: Dict[str, Dict] = {}
        skipped: List[str] = []
        for snap in self.pool.snapshots():
            if snap.state == DOWN:
                skipped.append(snap.id)
                continue
            try:
                docs[snap.id] = json.loads(
                    self._scrape_replica(snap, "/debug/usage"))
            except Exception as e:
                logger.warning(
                    f"router: usage scrape of {snap.id} failed: {e!r}")
                skipped.append(snap.id)
        return {
            "tier": "router",
            "replicas": docs,
            "skipped": skipped,
            "fleet": merge_aggregates(docs.values()),
        }

    def admin_adapters_fleet(self, payload: dict) -> Tuple[int, Dict]:
        """POST /admin/adapters at the router: fan the adapter op (load /
        unload / list) out to every live replica so one call changes the
        whole fleet's adapter catalog. Best-effort per replica (the
        drain-propagation contract): a DOWN replica is skipped, a failed or
        rejected propagation is reported per replica — the call itself
        always answers 200 with the outcome map, because partial application
        is the *expected* steady state under churn (a replica that missed
        the load will 404 per request and the client retries elsewhere)."""
        results: Dict[str, Dict] = {}
        skipped: List[str] = []

        def push(snap):
            conn = http.client.HTTPConnection(snap.host, snap.port, timeout=10)
            try:
                conn.request("POST", "/admin/adapters",
                             body=json.dumps(payload).encode(),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = resp.read().decode()
            finally:
                conn.close()
            try:
                doc = json.loads(body)
            except ValueError:
                doc = {"raw": body[:512]}
            return resp.status, doc

        live = []
        for snap in self.pool.snapshots():
            if snap.state == DOWN:
                skipped.append(snap.id)
            else:
                live.append(snap)
        if live:
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(8, len(live))) as pool:
                futures = {pool.submit(push, s): s for s in live}
                for fut in concurrent.futures.as_completed(futures):
                    snap = futures[fut]
                    try:
                        status, doc = fut.result()
                        results[snap.id] = {"status": status,
                                            "ok": status == 200,
                                            "response": doc}
                    except Exception as e:
                        logger.warning(
                            f"router: adapter op on {snap.id} failed: {e!r}")
                        results[snap.id] = {"status": None, "ok": False,
                                            "error": repr(e)}
        ok = sorted(r for r, d in results.items() if d["ok"])
        failed = sorted(r for r, d in results.items() if not d["ok"])
        return 200, {"op": payload.get("op", "list"), "replicas": results,
                     "skipped": skipped, "ok": ok, "failed": failed}

    # ------------------------------------------------------------- weight rollout
    def rollout_status(self) -> Optional[Dict]:
        """Point-in-time copy of the current/last rollout's state doc (None
        before the first rollout). Served on GET /admin/weights/rollout and
        embedded in GET /replicas for mixed-version-fleet visibility."""
        with self._rollout_lock:
            return dict(self._rollout) if self._rollout is not None else None

    def _rollout_set(self, **kw):
        with self._rollout_lock:
            if self._rollout is not None:
                self._rollout.update(kw)

    def _rollout_append(self, key: str, value):
        with self._rollout_lock:
            if self._rollout is not None:
                self._rollout[key].append(value)

    def admin_weights_rollout(self, payload: dict) -> Tuple[int, Dict]:
        """POST /admin/weights/rollout: rolling fleet weight update, one
        replica at a time — drain → swap (replica-side validate + canary +
        all-or-nothing install) → un-drain → health-gated rejoin → next. The
        first failure aborts the whole rollout and rolls already-swapped
        replicas back (see :meth:`_abort_rollout`). ::

            {"ckpt_dir": str, "version"?, "rollback_ckpt_dir"?,
             "canary_digest"?, "mode"?: "finish_old"|"pause_resume",
             "drain_deadline_s"?, "rejoin_timeout_s"?, "swap_timeout_s"?,
             "wait"?: bool}

        Asynchronous by default (poll GET /admin/weights/rollout);
        ``wait=true`` blocks until the rollout lands or aborts (409)."""
        ckpt_dir = payload.get("ckpt_dir")
        if not ckpt_dir or not isinstance(ckpt_dir, str):
            return 400, {"error": {"message": "missing required field 'ckpt_dir'",
                                   "type": "invalid_request", "code": 400}}
        version = str(payload.get("version")
                      or os.path.basename(os.path.normpath(ckpt_dir)))
        try:
            plan = {
                "version": version,
                "ckpt_dir": ckpt_dir,
                "rollback_ckpt_dir": payload.get("rollback_ckpt_dir"),
                "canary_digest": payload.get("canary_digest"),
                "mode": payload.get("mode"),
                "drain_deadline_s": float(payload.get("drain_deadline_s", 30.0)),
                "rejoin_timeout_s": float(payload.get("rejoin_timeout_s", 30.0)),
                "swap_timeout_s": float(payload.get("swap_timeout_s", 120.0)),
            }
        except (TypeError, ValueError) as e:
            return 400, {"error": {"message": f"bad rollout parameter: {e}",
                                   "type": "invalid_request", "code": 400}}
        # target set fixed at submission: live, non-draining replicas in
        # snapshot order (a replica joining mid-rollout is NOT picked up —
        # it should be provisioned from the new checkpoint anyway)
        targets = [s for s in self.pool.snapshots()
                   if s.state != DOWN and not s.draining]
        if not targets:
            return 409, {"error": {"message": "no live replica to roll out to",
                                   "type": "rollout_refused", "code": 409}}
        state = {
            "version": version, "ckpt_dir": ckpt_dir,
            "rollback_ckpt_dir": plan["rollback_ckpt_dir"],
            "status": "running", "replicas": [s.id for s in targets],
            "completed": [], "skipped": [], "rolled_back": [],
            "rollback_failed": [], "rollback_skipped": False,
            "current": None, "abort_reason": None, "error": None,
            "wall_s": None,
        }
        with self._rollout_lock:
            if self._rollout is not None and self._rollout.get("status") == "running":
                return 409, {"error": {
                    "message": f"a rollout to {self._rollout['version']!r} is "
                               "already running",
                    "type": "rollout_in_progress", "code": 409}}
            self._rollout = state
        if payload.get("wait"):
            self._run_rollout(state, plan, targets)
            final = self.rollout_status()
            return (200 if final["status"] == "done" else 409), {"rollout": final}
        t = threading.Thread(target=self._run_rollout,
                             args=(state, plan, targets),
                             daemon=True, name="weights-rollout")
        self._rollout_thread = t
        t.start()
        return 200, {"rollout": self.rollout_status()}

    def _post_replica_json(self, host: str, port: int, path: str, doc: dict,
                           timeout_s: float = 30.0) -> Tuple[int, Dict]:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        try:
            conn.request("POST", path, body=json.dumps(doc).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        try:
            body = json.loads(raw or b"{}")
        except ValueError:
            body = {"raw": raw[:512].decode("utf-8", "replace")}
        return resp.status, body

    def _undrain_replica(self, rid: str):
        """Rejoin plumbing: clear the router-side drain AND reopen the
        replica's own admission gate (drain propagation's mirror image)."""
        try:
            self.pool.cancel_drain(rid)
        except KeyError:
            return
        replica = self.pool.get(rid)
        if replica is not None:
            try:
                self._post_replica_json(replica.host, replica.port,
                                        "/admin/drain", {"undo": True},
                                        timeout_s=10.0)
            except _UPSTREAM_ERRORS + (ValueError,) as e:
                logger.warning(f"router: undrain propagation to {rid} failed: {e!r}")

    def _rollout_step(self, snap: ReplicaSnapshot, plan: Dict) -> Dict:
        """Drain → swap → un-drain → health-gated rejoin for ONE replica.
        Raises :class:`_RolloutFailure` on any failure; the caller owns the
        fleet-level abort. The replica side is all-or-nothing (validated
        checkpoint, quiesced install, canary, rollback-on-failure), so a
        raise here means this replica still serves its OLD weights."""
        rid, version = snap.id, plan["version"]
        try:
            _F_ROLLOUT.fire(replica=rid)
        except InjectedFault as e:
            raise _RolloutFailure("swap_failed",
                                  f"injected rollout fault on {rid}: {e!r}",
                                  replica=rid)
        replica = self.pool.get(rid)
        if replica is None:
            raise _RolloutFailure("swap_failed",
                                  f"replica {rid} left the pool mid-rollout",
                                  replica=rid)
        # drain both tiers synchronously (we are on the rollout thread): the
        # policy stops offering the replica, direct traffic 503s, in-flight
        # streams finish — the swap then quiesces an already-quiet engine
        self.pool.start_drain(rid, deadline_s=plan["drain_deadline_s"])
        self._propagate_drain(replica.host, replica.port, plan["drain_deadline_s"])
        deadline = time.time() + plan["drain_deadline_s"] + 10.0
        while not (self.pool.drain_status(rid) or {}).get("drained"):
            if time.time() >= deadline:
                raise _RolloutFailure(
                    "drain_timeout",
                    f"{rid} still has live streams past its drain deadline",
                    replica=rid)
            time.sleep(0.05)
        body = {"ckpt_dir": plan["ckpt_dir"], "version": version,
                "timeout_s": plan["swap_timeout_s"]}
        if plan["canary_digest"] is not None:
            body["canary_digest"] = plan["canary_digest"]
        if plan["mode"] is not None:
            body["mode"] = plan["mode"]
        try:
            status, doc = self._post_replica_json(
                replica.host, replica.port, "/admin/weights", body,
                timeout_s=plan["swap_timeout_s"] + 30.0)
        except _UPSTREAM_ERRORS + (ValueError,) as e:
            raise _RolloutFailure("swap_failed",
                                  f"swap POST to {rid} failed: {e!r}",
                                  replica=rid)
        if status != 200 or not doc.get("ok"):
            raise _RolloutFailure(
                "swap_failed",
                f"{rid} refused/failed the swap (HTTP {status}): "
                f"{json.dumps(doc)[:512]}",
                replica=rid)
        self._undrain_replica(rid)
        # rejoin gate: back in rotation only once /health is good AND reports
        # the target version — a replica that silently reverted (process
        # restart onto old weights) must not count as converged
        deadline = time.time() + plan["rejoin_timeout_s"]
        while True:
            self.pool.probe_one(rid)
            replica = self.pool.get(rid)
            cur = replica.snapshot() if replica is not None else None
            if (cur is not None and cur.state in (HEALTHY, RECOVERING)
                    and cur.weights_version == version):
                break
            if time.time() >= deadline:
                raise _RolloutFailure(
                    "rejoin_timeout",
                    f"{rid} did not rejoin healthy on {version!r} "
                    f"(state={cur.state if cur else None}, "
                    f"weights_version={cur.weights_version if cur else None})",
                    replica=rid)
            time.sleep(0.05)
        return doc

    def _run_rollout(self, state: Dict, plan: Dict, targets: List[ReplicaSnapshot]):
        """The rollout thread body: replicas one at a time, abort-and-rollback
        on the first failure. ``state`` is the live status doc (shared with
        :meth:`rollout_status` under the rollout lock)."""
        version, t0 = plan["version"], time.time()
        RECORDER.record("rollout.start", version=version, replicas=len(targets))
        logger.warning(f"router: weight rollout to {version!r} starting "
                       f"({len(targets)} replica(s))")
        swapped: List[Tuple[str, Optional[str]]] = []  # (rid, pre-swap version)
        try:
            for snap in targets:
                if snap.weights_version == version:
                    self._rollout_append("skipped", snap.id)
                    continue
                self._rollout_set(current=snap.id)
                step_t0 = time.time()
                doc = self._rollout_step(snap, plan)
                swapped.append((snap.id, snap.weights_version))
                if plan["canary_digest"] is None:
                    # the first swapped replica becomes the canary reference:
                    # every later replica must reproduce its probe output
                    # bit-for-bit or roll back
                    plan["canary_digest"] = doc.get("canary_digest")
                self._rollout_append("completed", snap.id)
                RECORDER.record("rollout.replica", replica=snap.id,
                                wall_s=round(time.time() - step_t0, 3))
        except _RolloutFailure as e:
            self._abort_rollout(state, plan, e, swapped)
            return
        wall_s = round(time.time() - t0, 3)
        self._rollout_set(status="done", current=None, wall_s=wall_s)
        RECORDER.record("rollout.done", version=version, wall_s=wall_s)
        logger.warning(f"router: weight rollout to {version!r} done in {wall_s}s")

    def _abort_rollout(self, state: Dict, plan: Dict, failure: "_RolloutFailure",
                       swapped: List[Tuple[str, Optional[str]]]):
        """First failure aborts the WHOLE rollout: the failed replica is
        un-drained (the replica-side swap is all-or-nothing, so it still
        serves its old weights), and every already-swapped replica is rolled
        back via ``rollback_ckpt_dir`` — a replica releases its retained old
        params the moment its canary passes, so fleet-level rollback must
        reload the old bytes from disk. Without a ``rollback_ckpt_dir`` the
        swapped replicas stay on the new version (reported as
        ``rollback_skipped``) — a mixed fleet the operator must resolve."""
        version, reason, failed = plan["version"], failure.reason, failure.replica
        logger.warning(
            f"router: rollout to {version!r} aborted at {failed} ({reason}): "
            f"{failure} — rolling back {len(swapped)} swapped replica(s)")
        RECORDER.record("rollout.abort", reason=reason, replica=failed,
                        version=version)
        if failed is not None:
            self._undrain_replica(failed)
        rolled_back: List[str] = []
        rollback_failed: List[str] = []
        if swapped and plan.get("rollback_ckpt_dir"):
            # newest swap first: converge the fleet back from the rollout's
            # leading edge (no drain needed — the replica-side swap quiesces)
            for rid, prev_version in reversed(swapped):
                replica = self.pool.get(rid)
                body = {"ckpt_dir": plan["rollback_ckpt_dir"]}
                if prev_version is not None:
                    body["version"] = prev_version
                status, doc = None, {}
                if replica is not None:
                    try:
                        status, doc = self._post_replica_json(
                            replica.host, replica.port, "/admin/weights", body,
                            timeout_s=plan["swap_timeout_s"] + 30.0)
                    except _UPSTREAM_ERRORS + (ValueError,) as e:
                        doc = {"error": repr(e)}
                if status == 200 and doc.get("ok"):
                    rolled_back.append(rid)
                else:
                    logger.warning(f"router: rollback of {rid} failed: "
                                   f"{json.dumps(doc)[:256]}")
                    rollback_failed.append(rid)
            if rollback_failed:
                RECORDER.record("rollout.abort", reason="rollback_failed",
                                version=version, replicas=len(rollback_failed))
        elif swapped:
            self._rollout_set(rollback_skipped=True)
            logger.warning(
                "router: no rollback_ckpt_dir — already-swapped replicas "
                f"{[r for r, _ in swapped]} stay on {version!r}")
        self._rollout_set(status="aborted", current=None, abort_reason=reason,
                          error=str(failure), rolled_back=rolled_back,
                          rollback_failed=rollback_failed)
        self.postmortem.dump("rollout_abort", detail={
            "version": version, "reason": reason, "failed_replica": failed,
            "error": str(failure), "rolled_back": rolled_back,
            "rollback_failed": rollback_failed,
            "completed": list(state.get("completed", []))})

    @staticmethod
    def _fold_stage_series(parsed: Dict[str, Dict]) -> Dict:
        """Fleet fold of the per-stage gauges disaggregated replicas expose
        (`paddlenlp_serving_stage_kv_utilization` / `_stage_queue_depth`):
        worst + mean per stage across replicas. Empty for uniform fleets."""
        folds = {"kv_utilization": "paddlenlp_serving_stage_kv_utilization",
                 "queue_depth": "paddlenlp_serving_stage_queue_depth"}
        out: Dict[str, Dict] = {}
        for key, fam_name in folds.items():
            per_stage: Dict[str, list] = {}
            for fams in parsed.values():
                fam = fams.get(fam_name)
                if fam is None:
                    continue
                for (_sample, labels), v in fam.samples.items():
                    stage = dict(labels).get("stage")
                    if stage:
                        per_stage.setdefault(stage, []).append(v)
            for stage, vals in per_stage.items():
                doc = out.setdefault(stage, {})
                doc[f"{key}_max"] = max(vals)
                doc[f"{key}_mean"] = sum(vals) / len(vals)
        return {k: out[k] for k in sorted(out)}

    # ------------------------------------------------------------- postmortem
    def _postmortem_health(self) -> Dict:
        """Router-tier bundle health: pool snapshots + drain status + the
        router's own open forwards — the placement facts behind the decision
        events in the trail."""
        return {
            "policy": getattr(self.policy, "name", type(self.policy).__name__),
            "replicas": self.admin_list_replicas()["replicas"],
            "hedges_inflight": self._hedges_inflight,  # lock-ok: point-in-time snapshot for a diagnostic dump
        }

    def _postmortem_config(self) -> Dict:
        return {
            "max_attempts": self.max_attempts,
            "hedge_after_s": self.hedge_after_s,
            "max_hedges_inflight": self.max_hedges_inflight,
            "trace_sample_every": self.trace_sample_every,
            "upstream_timeout_s": self.upstream_timeout_s,
            "slo_objectives": dataclasses.asdict(self.slo.objectives),
        }

    def _on_fast_burn(self, kind: str, burn_rate: float, window: str):
        """SLO fast-burn trigger (wired into the tracker at construction): a
        shortest-window burn past the page-now threshold snapshots the fleet
        state that produced it — and pushes a brownout floor to the replicas,
        so the fleet starts degrading selectively (shed best-effort first)
        instead of timing out uniformly while the autoscaler catches up. The
        dumper rate-limits, so a sustained burn costs one bundle per window,
        not one per /fleet/slo scrape."""
        self.postmortem.dump("slo_fast_burn", detail={
            "kind": kind, "burn_rate": burn_rate, "window": window})
        if self.brownout_push_level:
            self.push_brownout(self.brownout_push_level, reason="slo_fast_burn")

    def push_brownout(self, level: int, reason: str = "slo_fast_burn",
                      min_interval_s: float = 10.0) -> bool:
        """Push a brownout floor to every live replica (best-effort,
        off-thread — the same propagation channel drains use). Returns False
        when suppressed by the rate limit."""
        now = time.time()
        with self._brownout_push_lock:
            if now - self._last_brownout_push_t < min_interval_s:
                return False
            self._last_brownout_push_t = now
        targets = [(s.host, s.port) for s in self.pool.snapshots()
                   if s.state != DOWN and not s.draining]
        logger.warning(
            f"router: pushing brownout level {level} ({reason}) to "
            f"{len(targets)} replica(s)")
        for host, port in targets:
            # pool.push_brownout is the shared /admin/brownout client (the
            # autoscaler's max-envelope handoff uses the same one)
            threading.Thread(
                target=pool_push_brownout, args=(host, port, level),
                kwargs={"reason": reason}, daemon=True,
                name=f"brownout-push-{host}:{port}").start()
        return True

    # ------------------------------------------------------------- trace stitch
    def stitched_trace(self, trace_id: str) -> Dict:
        """One request's two-tier timeline: the router's spans plus the owning
        replica's, clock-skew-corrected onto the router's timeline and merged
        into a single multi-process Chrome trace. Falls back to the
        router-only view when the owner is unknown/unreachable (the stitch
        degrades, it never 500s)."""
        router_events = self.tracer.chrome_trace(
            self.tracer.snapshot(trace=trace_id))["traceEvents"]
        tiers = [{"name": "router", "events": router_events,
                  "offset_s": 0.0, "dropped": self.tracer.dropped}]
        with self._live_lock:
            owner_id = self._trace_owner.get(trace_id)
        owner = self.pool.get(owner_id) if owner_id is not None else None
        stitch_error = None
        if owner is not None:
            try:
                raw = self._scrape_replica(
                    owner.snapshot(), f"/debug/trace?trace={quote(trace_id)}")
                doc = json.loads(raw)
                tiers.append({
                    "name": owner_id,
                    "events": doc.get("traceEvents", []),
                    "offset_s": self.pool.clock_offset(owner_id),
                    "dropped": doc.get("otherData", {}).get("dropped_spans", 0),
                })
            except Exception as e:
                logger.warning(f"router: trace fetch from {owner_id} failed: {e!r}")
                stitch_error = repr(e)
        merged = merge_chrome_traces(tiers)
        merged["otherData"]["trace"] = trace_id
        merged["otherData"]["replica"] = owner_id
        if stitch_error is not None:
            merged["otherData"]["stitch_error"] = stitch_error
        return merged

    # ------------------------------------------------------------- forwarding
    def _handle_completion(self, handler, payload: dict, chat: bool = False):
        rid = f"rtr-{next(self._ids)}"
        # the head-based sampling decision: made once here, pinned on the
        # router's tracer, and propagated to the replica in the traceparent
        # header — every tier then agrees without re-deciding
        sampled = trace_sampled(rid, self.trace_sample_every)
        if self.trace_sample_every > 1:
            self.tracer.mark_trace(rid, sampled)
        state = _RelayState(rid, bool(payload.get("stream")), sampled=sampled,
                            upstream_path="/v1/chat/completions" if chat
                            else "/v1/completions")
        prompt = payload.get("prompt")
        if chat and prompt is None:
            # chat has no top-level prompt; the first message's content is the
            # shared conversation head — exactly the span prefix affinity
            # should co-locate when no conversation key pins harder
            msgs = payload.get("messages")
            if isinstance(msgs, list) and msgs and isinstance(msgs[0], dict):
                prompt = msgs[0].get("content")
        adapter_id = payload.get("adapter_id")
        adapter_id = str(adapter_id) if adapter_id is not None else None
        conversation = payload.get("conversation")
        conversation = str(conversation) if conversation is not None else None
        body = json.dumps(payload).encode()
        exclude: set = set()

        with use_trace(rid):
            self._relay_attempts(handler, state, payload, prompt, body, exclude,
                                 adapter_id=adapter_id, conversation=conversation)

    def _relay_attempts(self, handler, state: _RelayState, payload: dict,
                        prompt, body: bytes, exclude: set,
                        adapter_id: Optional[str] = None,
                        conversation: Optional[str] = None):
        while state.attempts < self.max_attempts:
            candidates = self._candidates(prompt, exclude, state, adapter_id,
                                          conversation)
            if not candidates:
                break
            cand = candidates[0]
            state.attempts += 1
            # hedging applies to token-less attempts (streams that relayed
            # nothing yet; batch requests always, nothing reaches the client
            # before the whole body) with somewhere to hedge TO. A browned-out
            # fleet (level >= 2 on either leg) suppresses the race: a hedge is
            # deliberate extra load, exactly what the brownout ladder is
            # shedding. Counted once per REQUEST at candidate selection
            # (whether or not the race would have fired) — unlike "capped",
            # which counts at hedge-fire time
            hedge_cand = candidates[1] if (
                self.hedge_after_s is not None
                and state.tokens_relayed == 0 and len(candidates) > 1) else None
            if hedge_cand is not None and max(cand.brownout_level,
                                              hedge_cand.brownout_level) >= 2:
                hedge_cand = None
                if state.attempts == 1:
                    self.metrics.hedges.inc(outcome="brownout")
            state.replica_id = cand.id
            state.weights_version = cand.weights_version
            # a fresh attempt must not inherit the previous replica's
            # completion id: replicas mint cmpl-N independently, and a stale
            # cid paired with the NEW replica would abort a stranger's request
            state.upstream_cid = None
            with self._live_lock:
                self._active.add(state)
            try:
                if hedge_cand is not None:
                    # the hedged attempt owns both legs' inflight accounting
                    # and may re-attribute the attempt to the hedge replica
                    hedged = (self._attempt_stream_hedged if state.stream
                              else self._attempt_batch_hedged)
                    outcome, cand = hedged(
                        handler, state, cand, hedge_cand, body, exclude)
                else:
                    self._inflight_delta(cand.id, +1)
                    try:
                        if state.stream:
                            outcome = self._attempt_stream(handler, state, cand, body)
                        else:
                            outcome = self._attempt_batch(handler, state, cand, body)
                    finally:
                        self._inflight_delta(cand.id, -1)
            finally:
                with self._live_lock:
                    self._active.discard(state)
            if outcome == "done":
                return
            if outcome == "reroute":
                # nothing relayed; 429/503/connect failure — next candidate
                exclude.add(cand.id)
                self.metrics.rerouted.inc()
                RECORDER.record("router.reroute", trace=state.rid,
                                replica=cand.id, attempt=state.attempts)
                self.tracer.instant("reroute", cat="router", trace=state.rid,
                                    replica=cand.id)
                continue
            if outcome == "failover":
                # accepted then failed pre-token: transparent resubmission. A
                # drain-evicted stream takes this same path, but its replica
                # is leaving on purpose — demoting it would lie to the pool
                exclude.add(cand.id)
                if not self.pool.is_draining(cand.id):
                    self.pool.note_forward_failure(cand.id)
                self.metrics.failovers.inc()
                RECORDER.record("router.failover", trace=state.rid,
                                replica=cand.id, attempt=state.attempts)
                self.tracer.add_span("failover", self.tracer.epoch_time(state.arrival_t),
                                     time.perf_counter() - state.arrival_t, cat="router",
                                     trace=state.rid, replica=cand.id,
                                     attempt=state.attempts)
                continue
            if outcome == "midstream_failed":
                self._terminate_midstream(handler, state, cand, payload)
                return
            if outcome == "client_gone":
                self._finish(state, cand.id, "client_gone")
                return

        # candidates/attempts exhausted
        self._reject_exhausted(handler, state, payload)

    def _reject_exhausted(self, handler, state: _RelayState, payload: dict):
        retry_after = max(1, int(round(self.pool.retry_after_hint())))
        if state.headers_sent:
            # SSE already open: a status line now would corrupt the stream —
            # same in-band contract as a mid-stream replica failure
            self._terminate_midstream(handler, state, None, payload)
            return
        self._finish(state, "none", "rejected")
        try:
            handler._send_error_json(
                503, "no replica available for this request; retry shortly",
                "no_replica_available", headers={"Retry-After": retry_after})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _forward_headers(self, state: _RelayState) -> Dict[str, str]:
        """Per-forward headers: the traceparent contract. The parent span id
        names the router's request span (``<rid>@router``) so the replica's
        stitched spans can point back at the tier that placed them."""
        return {
            "Content-Type": "application/json",
            TRACEPARENT_HEADER: format_traceparent(
                state.rid, f"{state.rid}@router", state.sampled),
        }

    # ------------------------------------------------------------- failure plane
    def _apply_failure(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                       failure: Tuple) -> str:
        """Apply one classified upstream failure in *attempt* context and
        return the outcome for the caller's switch. Demotion on "failover"
        is deliberately left to that switch (it owns exclusion + the
        failover span); only re-route-class replica faults demote here."""
        kind, payload = failure
        d = _classify_upstream_failure(kind, payload)
        if kind == "connect_failed":
            logger.warning(f"router: forward to {cand.id} failed: {payload!r}")
        elif d.status is not None and d.outcome == "failover":
            logger.warning(f"router: {cand.id} answered {d.status}")
        if d.is_degraded:
            self.pool.note_degraded(cand.id, retry_after_s=d.retry_after_s())
        if d.outcome == "reroute":
            # a drain-deadline eviction lands here too — a deliberately
            # leaving replica must not be demoted as if it had failed
            if d.replica_fault and not self.pool.is_draining(cand.id):
                self.pool.note_forward_failure(cand.id)
            return "reroute"
        if d.outcome == "failover":
            return "failover"
        # relay: the replica judged the request itself bad (400/413) — relay
        # verbatim, another replica would say the same … unless SSE headers
        # already went out, in which case a status line would corrupt the
        # stream and the only move left is trying elsewhere
        if state.headers_sent:
            return "failover"
        self._finish(state, cand.id, "error")
        self._relay_raw(handler, d.status, d.raw)
        return "done"

    # ------------------------------------------------------------- batch leg
    def _attempt_batch(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                       body: bytes) -> str:
        conn = http.client.HTTPConnection(cand.host, cand.port,
                                          timeout=self.upstream_timeout_s)
        # registered for drain eviction like the stream leg: nothing has been
        # relayed until the whole body arrives, so a forced close simply
        # re-routes the request to a survivor
        state.upstream_conn = conn
        try:
            try:
                _F_FORWARD.fire(replica=cand.id)
                conn.request("POST", state.upstream_path, body=body,
                             headers=self._forward_headers(state))
                resp = conn.getresponse()
                state.upstream_resp = resp
                raw = resp.read()
            except _UPSTREAM_ERRORS as e:
                return self._apply_failure(handler, state, cand, ("connect_failed", e))
            if resp.status != 200:
                return self._apply_failure(handler, state, cand, (
                    "status", (resp.status, raw, resp.getheader("Retry-After"))))
            try:
                doc = json.loads(raw)
                finish = (doc.get("choices") or [{}])[0].get("finish_reason")
            except (ValueError, AttributeError, IndexError):
                doc, finish = None, None
            if doc is None or finish == "engine_error":
                # the replica accepted then failed it (or returned junk);
                # nothing reached the client — resubmit elsewhere
                return self._apply_failure(handler, state, cand, ("engine_error", None))
            doc["id"] = state.rid
            doc["replica"] = cand.id
            self._finish(state, cand.id, "ok")
            self._relay_raw(handler, 200, json.dumps(doc).encode())
            return "done"
        finally:
            state.upstream_conn = None
            state.upstream_resp = None
            try:
                conn.close()
            except Exception:
                pass  # may race the drain enforcer's forced close

    def _relay_raw(self, handler, status: int, raw: bytes):
        try:
            handler._send_raw(status, raw, "application/json")
        except (BrokenPipeError, ConnectionResetError):
            logger.debug("router: client disconnected before response relay")

    # ------------------------------------------------------------- stream leg
    def _attempt_stream(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                        body: bytes) -> str:
        conn = http.client.HTTPConnection(cand.host, cand.port,
                                          timeout=self.upstream_timeout_s)
        # published for the drain enforcer: a past-deadline drain closes this
        # connection to break the relay read into a pre-token failover
        state.upstream_conn = conn
        try:
            try:
                _F_FORWARD.fire(replica=cand.id)
                conn.request("POST", state.upstream_path, body=body,
                             headers=self._forward_headers(state))
                resp = conn.getresponse()
                state.upstream_resp = resp
            except _UPSTREAM_ERRORS as e:
                return self._apply_failure(handler, state, cand, ("connect_failed", e))
            if resp.status != 200:
                raw = resp.read()
                return self._apply_failure(handler, state, cand, (
                    "status", (resp.status, raw, resp.getheader("Retry-After"))))
            return self._relay_sse(handler, state, cand, _read_sse_events(resp))
        finally:
            state.upstream_conn = None
            state.upstream_resp = None
            try:
                conn.close()
            except Exception:
                # closing a connection the drain enforcer already tore down
                # can trip http.client's own (unsynchronized) close path
                pass

    def _relay_sse(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                   events) -> str:
        """Relay one upstream SSE leg, already parsed into
        ``("event"|"done"|"broke", payload)`` items (:func:`_read_sse_events`
        for a plain leg, the committed-leg queue for a hedged one). Returns
        done / failover / midstream_failed / client_gone."""
        if not state.headers_sent:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("Connection", "close")
            handler.end_headers()
            state.headers_sent = True

        def close_out() -> str:
            # terminal bookkeeping BEFORE the final client write: the moment
            # the client sees [DONE], every router-side counter/span must
            # already reflect this request — a client asserting on /metrics
            # right after its stream closes must never observe the old value.
            # A client that vanishes on this very last write already received
            # the entire stream, so "ok"/"error" (not client_gone) stands.
            self._finish(state, cand.id, "ok" if state.finished else "error")
            try:
                handler.wfile.write(b"data: [DONE]\n\n")
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass
            return "done"

        for kind, payload in events:
            if kind == "done":
                # the terminal chunk was already relayed on a previous item
                return close_out()
            if kind == "broke" or payload.get("object") == "error":
                # transport drop / close without [DONE] / upstream's in-band
                # internal error — all the same disposition
                if kind == "broke" and payload is not None:
                    logger.warning(f"router: stream from {cand.id} broke: {payload!r}")
                if state.finished:
                    # the client already has its terminal chunk; only [DONE]
                    # was lost — close out the stream ourselves
                    return close_out()
                return "failover" if state.tokens_relayed == 0 else "midstream_failed"
            ev = payload
            upstream_cid = ev.get("id")
            if upstream_cid:
                state.upstream_cid = str(upstream_cid)
                self._track(state, cand.id, str(upstream_cid))
            choice = (ev.get("choices") or [{}])[0]
            finish = choice.get("finish_reason")
            if finish == "engine_error":
                # the replica's supervisor gave up on this request: pre-token
                # it is ours to retry elsewhere, mid-stream it becomes the
                # router-level replica_error terminal
                return "failover" if state.tokens_relayed == 0 else "midstream_failed"
            ev["id"] = state.rid
            if finish:
                ev["replica"] = cand.id
            try:
                handler.wfile.write(f"data: {json.dumps(ev)}\n\n".encode())
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                logger.debug(f"router: client left stream {state.rid}; aborting upstream")
                self._abort_upstream(state, cand)
                return "client_gone"
            if finish:
                state.finished = True
            elif "token" in choice:
                state.tokens_relayed += 1
        # iterator exhausted without a terminal item (defensive)
        return "failover" if state.tokens_relayed == 0 else "midstream_failed"

    # ------------------------------------------------------------- hedged leg
    def _attempt_stream_hedged(self, handler, state: _RelayState,
                               cand: ReplicaSnapshot, hedge_cand: ReplicaSnapshot,
                               body: bytes, exclude: set):
        """One hedged stream attempt. The primary forward starts immediately;
        when no leg has produced a first event within ``hedge_after_s`` a
        shadow forward races it on ``hedge_cand`` (bounded by the
        in-flight-hedge cap). Each leg's reader thread parses its SSE stream
        into a shared queue; the first leg to produce a *usable* event (a
        token or a clean terminal — not an engine_error) is **committed** and
        relays through the ordinary SSE path, and the loser is torn down
        (socket closed + ``/v1/abort`` when its upstream id is known). Nothing
        reaches the client before commit, so a losing leg is invisible.

        Returns ``(outcome, replica)`` — ``replica`` is the leg the outcome
        belongs to, so the caller's exclusion/health bookkeeping follows the
        replica that actually failed or served.

        NOTE: :meth:`_attempt_batch_hedged` is this method's batch twin —
        same race scaffolding over whole responses instead of SSE events; the
        two are kept in deliberate lockstep, change both or neither."""
        # bounded: the committed leg's reader is paced by how fast the client
        # drains (TCP backpressure all the way to the replica) instead of
        # buffering a whole generation in router memory for a slow client
        q: "queue.Queue" = queue.Queue(maxsize=64)
        legs = {0: cand, 1: hedge_cand}
        conns: Dict[int, object] = {}
        resps: Dict[int, object] = {}
        cids: Dict[int, Optional[str]] = {0: None, 1: None}
        abandoned: Dict[int, bool] = {}

        def put_item(leg: int, kind: str, payload) -> bool:
            """Bounded put with liveness: blocks while the queue is full
            (backpressure) but re-checks abandonment each second so a
            torn-down loser's reader exits instead of wedging on a queue
            nobody will drain."""
            while not abandoned.get(leg):
                try:
                    q.put((leg, kind, payload), timeout=1.0)
                    return True
                except queue.Full:
                    continue
            return False

        def reader(leg: int, snap: ReplicaSnapshot):
            conn = http.client.HTTPConnection(snap.host, snap.port,
                                              timeout=self.upstream_timeout_s)
            conns[leg] = conn
            if leg == 0:
                # published pre-commit so a drain-deadline eviction of the
                # (token-less, primary-pinned) stream can break this leg too;
                # the commit re-points these at the winning leg
                state.upstream_conn = conn
            try:
                try:
                    _F_FORWARD.fire(replica=snap.id)
                    conn.request("POST", state.upstream_path, body=body,
                                 headers=self._forward_headers(state))
                    resp = conn.getresponse()
                    resps[leg] = resp
                    if leg == 0:
                        state.upstream_resp = resp
                except _UPSTREAM_ERRORS as e:
                    put_item(leg, "connect_failed", e)
                    return
                if resp.status != 200:
                    try:
                        raw = resp.read()
                    except _UPSTREAM_ERRORS:
                        raw = b""
                    put_item(leg, "status",
                             (resp.status, raw, resp.getheader("Retry-After")))
                    return
                for kind, payload in _read_sse_events(resp):
                    if not put_item(leg, kind, payload):
                        return  # loser: closing the conn frees the replica
                    if kind != "event":
                        return
            finally:
                conn.close()

        self._inflight_delta(cand.id, +1)
        hedge_started = False
        hedge_capped = False
        hedge_fired_t = 0.0  # perf_counter at shadow launch (hedge_race phase)
        committed: Optional[int] = None
        first_item = None  # the committing ("event", ev) item
        failures: Dict[int, Tuple[str, object]] = {}
        threading.Thread(target=reader, args=(0, cand), daemon=True,
                         name=f"hedge-primary-{state.rid}").start()
        hedge_deadline = time.perf_counter() + float(self.hedge_after_s)
        try:
            while committed is None:
                deciding = not hedge_started and not hedge_capped
                timeout = (max(hedge_deadline - time.perf_counter(), 0.001)
                           if deciding else self.upstream_timeout_s)
                try:
                    leg, kind, payload = q.get(timeout=timeout)
                except queue.Empty:
                    if deciding and time.perf_counter() >= hedge_deadline:
                        # latency budget blown with no first event: hedge
                        if self._try_start_hedge():
                            hedge_started = True
                            hedge_fired_t = time.perf_counter()
                            RECORDER.record("router.hedge_fire", trace=state.rid,
                                            replica=hedge_cand.id)
                            self.tracer.instant("hedge", cat="router",
                                                trace=state.rid, outcome="fired",
                                                replica=hedge_cand.id)
                            self._inflight_delta(hedge_cand.id, +1)
                            threading.Thread(
                                target=reader, args=(1, hedge_cand), daemon=True,
                                name=f"hedge-shadow-{state.rid}").start()
                        else:
                            hedge_capped = True
                            self.metrics.hedges.inc(outcome="capped")
                            self.tracer.instant("hedge", cat="router",
                                                trace=state.rid, outcome="capped")
                        continue
                    if deciding:
                        continue  # spurious early wake
                    # silence past the upstream timeout: every racing leg is
                    # wedged — treat them as broken AND tear them down like
                    # hedge losers, or their readers would stay blocked for
                    # another full upstream timeout while both replicas keep
                    # generating the orphaned request
                    for wedged in (0, 1) if hedge_started else (0,):
                        failures.setdefault(wedged, ("broke", None))
                        abandoned[wedged] = True
                        _force_close(conns.get(wedged), resps.get(wedged))
                    break
                if kind == "event":
                    ev = payload
                    if ev.get("id"):
                        cids[leg] = str(ev["id"])
                    choice = (ev.get("choices") or [{}])[0]
                    if ev.get("object") == "error" \
                            or choice.get("finish_reason") == "engine_error":
                        failures[leg] = ("engine_error", None)
                    else:
                        committed = leg
                        first_item = ("event", ev)
                        break
                else:
                    failures[leg] = (kind, payload)
                if 0 in failures and not hedge_started:
                    # primary failed inside the hedge budget: nothing to race —
                    # the ordinary candidate walk owns the resubmission
                    return (self._apply_failure(handler, state, cand,
                                                failures[0]), cand)
                if 0 in failures and 1 in failures:
                    break
                # one leg died but the other is still racing: keep waiting

            if committed is None:
                # every started leg is dead; attribute the attempt to the
                # primary, book the shadow's failure separately
                if hedge_started:
                    self.metrics.hedges.inc(outcome="failed")
                    self.tracer.instant("hedge", cat="router", trace=state.rid,
                                        outcome="failed")
                    if 1 in failures:
                        self._note_dead_leg(hedge_cand, failures[1], exclude)
                return (self._apply_failure(
                    handler, state, cand, failures.get(0, ("broke", None))), cand)

            committed_cand = legs[committed]
            loser = 1 - committed
            if loser == 0 or hedge_started:  # the loser leg actually ran
                if loser in failures:
                    self._note_dead_leg(legs[loser], failures[loser], exclude)
                else:
                    # still racing: tear it down — the closed socket stops its
                    # reader, the explicit abort frees replica-side slot/KV
                    # (a leg with no event yet has no id to abort by; the
                    # replica notices the disconnect on its first write)
                    abandoned[loser] = True
                    RECORDER.record("router.hedge_abort", trace=state.rid,
                                    replica=legs[loser].id)
                    _force_close(conns.get(loser), resps.get(loser))
                    if cids[loser] is not None:
                        self._abort_replica_request(
                            legs[loser].host, legs[loser].port, cids[loser])
            if hedge_started:
                label = "hedge_won" if committed == 1 else "primary_won"
                self.metrics.hedges.inc(outcome=label)
                RECORDER.record("router.hedge_commit", trace=state.rid,
                                replica=committed_cand.id, outcome=label)
                # the hedge-race phase: time between firing the shadow and the
                # first usable event — the latency the race bought (or not)
                self.metrics.latency_attribution.observe(
                    time.perf_counter() - hedge_fired_t, phase="hedge_race")
                self.tracer.instant("hedge", cat="router", trace=state.rid,
                                    outcome=label, replica=committed_cand.id)
            state.replica_id = committed_cand.id
            state.upstream_conn = conns.get(committed)
            state.upstream_resp = resps.get(committed)

            def committed_events():
                yield first_item
                while True:
                    try:
                        lg, kind, payload = q.get(timeout=self.upstream_timeout_s)
                    except queue.Empty:
                        yield ("broke", None)
                        return
                    if lg != committed:
                        continue
                    yield (kind, payload)
                    if kind != "event":
                        return

            return (self._relay_sse(handler, state, committed_cand,
                                    committed_events()), committed_cand)
        finally:
            # whatever happened, no reader may stay blocked on the queue once
            # nobody drains it (put_item re-checks this within a second)
            abandoned[0] = abandoned[1] = True
            state.upstream_conn = None
            state.upstream_resp = None
            self._inflight_delta(cand.id, -1)
            if hedge_started:
                self._inflight_delta(hedge_cand.id, -1)
                self._release_hedge()

    def _attempt_batch_hedged(self, handler, state: _RelayState,
                              cand: ReplicaSnapshot, hedge_cand: ReplicaSnapshot,
                              body: bytes, exclude: set):
        """One hedged *batch* attempt — the same loser-abort race as the
        stream path, over whole responses instead of SSE events. The primary
        forward starts immediately; if no leg has produced its response
        within ``hedge_after_s`` a shadow races it on ``hedge_cand`` (bounded
        by the same in-flight cap, counted in the same
        ``hedges_total{outcome}``). The first leg to return a *usable* 200
        (parseable, not an in-band ``engine_error``) is committed and relayed
        under the router's id; the loser's socket is force-closed — a batch
        loser has no upstream id to abort by until its body arrives, which is
        exactly what we are not waiting for, so the replica frees the request
        when its final write hits the dead connection.

        Returns ``(outcome, replica)`` like the stream twin
        (:meth:`_attempt_stream_hedged`) — the race scaffolding is kept in
        deliberate lockstep with it; change both or neither."""
        q: "queue.Queue" = queue.Queue()  # ≤1 item per leg: no bound needed
        legs = {0: cand, 1: hedge_cand}
        conns: Dict[int, object] = {}
        resps: Dict[int, object] = {}

        def reader(leg: int, snap: ReplicaSnapshot):
            conn = http.client.HTTPConnection(snap.host, snap.port,
                                              timeout=self.upstream_timeout_s)
            conns[leg] = conn
            if leg == 0:
                # published pre-commit for drain-deadline eviction, exactly
                # like the stream primary
                state.upstream_conn = conn
            try:
                try:
                    _F_FORWARD.fire(replica=snap.id)
                    conn.request("POST", state.upstream_path, body=body,
                                 headers=self._forward_headers(state))
                    resp = conn.getresponse()
                    resps[leg] = resp
                    if leg == 0:
                        state.upstream_resp = resp
                    raw = resp.read()
                except _UPSTREAM_ERRORS as e:
                    q.put((leg, "connect_failed", e))
                    return
                q.put((leg, "response",
                       (resp.status, raw, resp.getheader("Retry-After"))))
            finally:
                conn.close()

        self._inflight_delta(cand.id, +1)
        hedge_started = False
        hedge_capped = False
        hedge_fired_t = 0.0  # perf_counter at shadow launch (hedge_race phase)
        committed = None  # (leg, parsed response doc)
        failures: Dict[int, Tuple[str, object]] = {}
        threading.Thread(target=reader, args=(0, cand), daemon=True,
                         name=f"hedge-batch-primary-{state.rid}").start()
        hedge_deadline = time.perf_counter() + float(self.hedge_after_s)
        try:
            while committed is None:
                deciding = not hedge_started and not hedge_capped
                timeout = (max(hedge_deadline - time.perf_counter(), 0.001)
                           if deciding else self.upstream_timeout_s)
                try:
                    leg, kind, payload = q.get(timeout=timeout)
                except queue.Empty:
                    if deciding and time.perf_counter() >= hedge_deadline:
                        if self._try_start_hedge():
                            hedge_started = True
                            hedge_fired_t = time.perf_counter()
                            RECORDER.record("router.hedge_fire", trace=state.rid,
                                            replica=hedge_cand.id)
                            self.tracer.instant("hedge", cat="router",
                                                trace=state.rid, outcome="fired",
                                                replica=hedge_cand.id)
                            self._inflight_delta(hedge_cand.id, +1)
                            threading.Thread(
                                target=reader, args=(1, hedge_cand), daemon=True,
                                name=f"hedge-batch-shadow-{state.rid}").start()
                        else:
                            hedge_capped = True
                            self.metrics.hedges.inc(outcome="capped")
                            self.tracer.instant("hedge", cat="router",
                                                trace=state.rid, outcome="capped")
                        continue
                    if deciding:
                        continue  # spurious early wake
                    # silence past the upstream timeout: every racing leg is
                    # wedged — tear them down so the replicas notice
                    for wedged in (0, 1) if hedge_started else (0,):
                        failures.setdefault(wedged, ("broke", None))
                        _force_close(conns.get(wedged), resps.get(wedged))
                    break
                if kind == "response":
                    status, raw, retry_after = payload
                    if status == 200:
                        try:
                            doc = json.loads(raw)
                            finish = (doc.get("choices") or [{}])[0].get("finish_reason")
                        except (ValueError, AttributeError, IndexError):
                            doc, finish = None, None
                        if doc is not None and finish != "engine_error":
                            committed = (leg, doc)
                            break
                        failures[leg] = ("engine_error", None)
                    else:
                        failures[leg] = ("status", (status, raw, retry_after))
                else:
                    failures[leg] = (kind, payload)
                if 0 in failures and not hedge_started:
                    # primary failed inside the hedge budget: nothing to race
                    return (self._apply_failure(handler, state, cand,
                                                failures[0]), cand)
                if 0 in failures and 1 in failures:
                    break

            if committed is None:
                if hedge_started:
                    self.metrics.hedges.inc(outcome="failed")
                    self.tracer.instant("hedge", cat="router", trace=state.rid,
                                        outcome="failed")
                    if 1 in failures:
                        self._note_dead_leg(hedge_cand, failures[1], exclude)
                return (self._apply_failure(
                    handler, state, cand, failures.get(0, ("broke", None))), cand)

            win_leg, doc = committed
            committed_cand = legs[win_leg]
            loser = 1 - win_leg
            if loser == 0 or hedge_started:  # the loser leg actually ran
                if loser in failures:
                    self._note_dead_leg(legs[loser], failures[loser], exclude)
                else:
                    # still generating: closing its socket is the abort — the
                    # replica frees slot + KV when its response write fails
                    RECORDER.record("router.hedge_abort", trace=state.rid,
                                    replica=legs[loser].id)
                    _force_close(conns.get(loser), resps.get(loser))
            if hedge_started:
                label = "hedge_won" if win_leg == 1 else "primary_won"
                self.metrics.hedges.inc(outcome=label)
                RECORDER.record("router.hedge_commit", trace=state.rid,
                                replica=committed_cand.id, outcome=label)
                self.metrics.latency_attribution.observe(
                    time.perf_counter() - hedge_fired_t, phase="hedge_race")
                self.tracer.instant("hedge", cat="router", trace=state.rid,
                                    outcome=label, replica=committed_cand.id)
            state.replica_id = committed_cand.id
            doc["id"] = state.rid
            doc["replica"] = committed_cand.id
            self._finish(state, committed_cand.id, "ok")
            self._relay_raw(handler, 200, json.dumps(doc).encode())
            return ("done", committed_cand)
        finally:
            state.upstream_conn = None
            state.upstream_resp = None
            self._inflight_delta(cand.id, -1)
            if hedge_started:
                self._inflight_delta(hedge_cand.id, -1)
                self._release_hedge()

    def _note_dead_leg(self, cand: ReplicaSnapshot, failure: Tuple, exclude: set):
        """Health/metrics bookkeeping for a hedged leg that died while the
        OTHER leg carried the request: same classification as every attempt
        (:func:`_classify_upstream_failure`), dead-leg application — the
        outcome switch never sees this leg, so exclusion, the re-route/
        failover counters and the replica-fault demotion apply here."""
        kind, payload = failure
        d = _classify_upstream_failure(kind, payload)
        exclude.add(cand.id)
        if d.is_degraded:
            self.pool.note_degraded(cand.id, retry_after_s=d.retry_after_s())
        if d.replica_fault and not self.pool.is_draining(cand.id):
            self.pool.note_forward_failure(cand.id)
        if d.outcome == "reroute":
            self.metrics.rerouted.inc()
        else:
            self.metrics.failovers.inc()

    def _abort_upstream(self, state: _RelayState, cand: ReplicaSnapshot):
        with self._live_lock:
            owner = self._live.get(state.rid)
        if owner is not None and owner[0] == cand.id:
            self.abort(state.rid)

    def _midstream_disposition(self, state: _RelayState,
                               cand: Optional[ReplicaSnapshot]) -> str:
        """The router-level disposition for a stream that died AFTER tokens
        were relayed. Continuing it elsewhere would re-emit divergent tokens,
        so it always terminates in-band — the split is only over *why*:

        - ``replica_error``: the replica failed; the fleet still serves the
          version this stream was generating under (an ordinary retry
          regenerates equivalently).
        - ``version_skew``: a fleet weight rollout moved the surviving
          candidates (or the pinned replica itself) to a DIFFERENT weights
          version than the one the relayed tokens came from — a silent resume
          would splice two models' outputs into one stream. The refusal is
          recorded (``router.version_skew``) so a rollout postmortem shows
          which streams it cost."""
        if state.tokens_relayed == 0 or state.weights_version is None:
            return "replica_error"
        versions = {s.weights_version for s in self.pool.snapshots()
                    if s.state != DOWN and s.weights_version is not None}
        if versions and state.weights_version not in versions:
            RECORDER.record("router.version_skew", trace=state.rid,
                            replica=state.replica_id,
                            version=state.weights_version)
            self.metrics.version_skew_terminations.inc()
            return "version_skew"
        return "replica_error"

    def _terminate_midstream(self, handler, state: _RelayState,
                             cand: Optional[ReplicaSnapshot], payload: dict):
        """In-band terminal for a stream whose replica died after tokens were
        relayed (PR 3's engine_error contract, one level up): final chunk with
        ``finish_reason="replica_error"`` (``"version_skew"`` when a weight
        rollout made resumption impossible — see
        :meth:`_midstream_disposition`) + usage covering what the client
        actually received, then [DONE] — never a mid-stream connection reset."""
        replica_id = cand.id if cand is not None else "none"
        if cand is not None:
            self.pool.note_forward_failure(cand.id)
        finish_reason = self._midstream_disposition(state, cand)
        prompt = payload.get("prompt")
        self._finish(state, replica_id, finish_reason)
        try:
            usage = {"completion_tokens": state.tokens_relayed}
            if isinstance(prompt, (list, tuple)):
                # for a string prompt the router cannot know the token count
                # (no tokenizer); omit rather than emit a null the client's
                # usage accounting would trip over
                usage["prompt_tokens"] = len(prompt)
                usage["total_tokens"] = len(prompt) + state.tokens_relayed
            final = {"id": state.rid, "object": "text_completion.chunk",
                     "replica": replica_id,
                     "choices": [{"index": 0, "finish_reason": finish_reason}],
                     "usage": usage}
            handler.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
            handler.wfile.write(b"data: [DONE]\n\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass

    # ------------------------------------------------------------- lifecycle
    def start_in_thread(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start poller + HTTP without blocking; returns the bound port."""
        self.pool.start()
        self._httpd = self._make_httpd(host, port)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="router-http")
        t.start()
        bound = self._httpd.server_address[1]
        logger.info(f"router on {host}:{bound} fronting {len(self.pool)} replicas "
                    f"(policy={getattr(self.policy, 'name', '?')})")
        return bound

    def run(self, host: str = "0.0.0.0", port: int = 8010):
        self.pool.start()
        self._httpd = self._make_httpd(host, port)
        logger.info(f"router on {host}:{port} fronting {len(self.pool)} replicas")
        try:
            self._httpd.serve_forever()
        finally:
            self.shutdown()

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        self.pool.stop()
