"""HTTP front tier: health-aware forwarding with cross-replica failover.

``RouterServer`` sits in front of N ``ServingServer`` replicas and owns three
concerns the replicas cannot solve alone:

- **placement** — every request gets an ordered candidate list from the
  routing policy (least-loaded or prefix-affinity) over live pool snapshots;
- **re-routing** — a replica 429 (window full) / 503 (draining or its engine
  supervisor's circuit breaker) or a connect failure moves the request to the
  next candidate *before anything reaches the client*
  (``paddlenlp_router_rerouted_total``);
- **failover** — when a replica fails a request it had already accepted
  (transport drop mid-stream, or an in-band ``finish_reason="engine_error"``
  terminal), the router splits on whether the client has seen tokens:

  - **no tokens emitted** → the request is transparently resubmitted to the
    next healthy replica with the failed one excluded (bounded by
    ``max_attempts``; the client's SSE connection and the router-side timing
    anchors are preserved — the stream just pauses), counted in
    ``paddlenlp_router_failovers_total``;
  - **mid-stream** → regenerating would re-emit divergent tokens, so the
    stream finishes **in-band** with ``finish_reason="replica_error"`` and a
    usage block covering what was actually relayed — exactly the engine-loop
    supervisor's ``engine_error`` contract, one level up.

Upstream completion ids are rewritten to the router's own ``rtr-N`` ids so a
failover is invisible to the client; ``POST /v1/abort`` is routed back to
whichever replica currently owns the stream. The router's own observability
plane (``/metrics``, ``/health``, ``/debug/trace``) rides on the shared
registry/tracer machinery.

**Fleet observability.** The router is where per-process planes become one:

- every forward carries a traceparent-style header (trace id + parent span id
  + sampled flag), and the replica adopts the ``rtr-N`` id instead of minting
  its own — ``GET /debug/trace?trace=rtr-N`` then fetches the owning replica's
  spans and stitches them with the router's into one multi-process Chrome
  trace, correcting clock skew with the offset the health poller estimates
  from probe-RTT midpoints;
- the 1-in-N trace sampling decision (``trace_sample_every``) is made ONCE
  here, by deterministic hash of the trace id, and propagated in the header —
  unsampled requests take the tracer's no-op path in every tier;
- ``GET /fleet/metrics`` merges the replicas' expositions (re-labeled
  ``{replica="..."}``), and ``GET /fleet/slo`` computes multi-window
  availability + TTFT burn rates over the federated counters
  (``observability/slo.py``), exposed as ``paddlenlp_slo_*`` on the router's
  own ``/metrics``.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import http.client
import itertools
import json
import threading
import time
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, quote, urlsplit

from ...observability.exporter import route_observability
from ...observability.slo import (
    DEFAULT_WINDOWS_S,
    SLOInputs,
    SLOObjectives,
    SLOTracker,
    slo_inputs_from_families,
)
from ...observability.tracer import (
    TRACEPARENT_HEADER,
    TRACER,
    SpanTracer,
    format_traceparent,
    merge_chrome_traces,
    trace_sampled,
    use_trace,
)
from ...utils.faults import FaultPoint, InjectedFault
from ...utils.log import logger
from ..httputil import JsonRequestHandler
from ..metrics import REGISTRY, MetricsRegistry
from ...observability.prometheus import parse_prometheus_text
from .metrics import RouterMetrics, federate_families
from .policy import resolve_policy
from .pool import DEGRADED, DOWN, HEALTHY, RECOVERING, ReplicaPool, ReplicaSnapshot

__all__ = ["RouterServer"]

MAX_BODY_BYTES = 8 << 20

_F_FORWARD = FaultPoint("router.forward")

#: transport-level failures on the upstream leg; InjectedFault rides along so
#: the router.forward fault point is handled exactly like a real socket error
_UPSTREAM_ERRORS = (OSError, http.client.HTTPException, InjectedFault)


class _RelayState:
    """Per-request relay bookkeeping shared across forward attempts. One
    instance per client request, touched only by that request's handler
    thread — no locking needed."""

    __slots__ = ("rid", "stream", "headers_sent", "tokens_relayed", "arrival_t",
                 "attempts", "finished", "sampled")

    def __init__(self, rid: str, stream: bool, sampled: bool = True):
        self.rid = rid
        self.stream = stream
        self.headers_sent = False
        self.tokens_relayed = 0
        self.arrival_t = time.perf_counter()  # original timing anchor
        self.attempts = 0
        self.finished = False  # a finish_reason chunk was relayed to the client
        self.sampled = sampled  # head-based trace sampling decision


class RouterServer:
    """Multi-replica front tier over the replica pool + routing policy."""

    def __init__(self, replicas=(), pool: Optional[ReplicaPool] = None,
                 policy="least_loaded", registry: Optional[MetricsRegistry] = None,
                 max_attempts: int = 3, max_body_bytes: int = MAX_BODY_BYTES,
                 poll_interval_s: float = 1.0, probe_timeout_s: float = 2.0,
                 upstream_timeout_s: float = 600.0,
                 trace_sample_every: int = 1,
                 tracer: Optional[SpanTracer] = None,
                 slo_objectives: Optional[SLOObjectives] = None,
                 slo_windows_s: Sequence[float] = DEFAULT_WINDOWS_S,
                 scrape_timeout_s: float = 5.0):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if trace_sample_every < 1:
            raise ValueError("trace_sample_every must be >= 1")
        self.registry = registry or REGISTRY
        # a private tracer keeps router spans out of in-process replicas' rings
        # (the launcher passes one); a dedicated router process uses the global
        self.tracer = tracer if tracer is not None else TRACER
        self.trace_sample_every = trace_sample_every
        self.scrape_timeout_s = scrape_timeout_s
        self.metrics = RouterMetrics(self.registry)
        self.slo = SLOTracker(objectives=slo_objectives, windows_s=slo_windows_s,
                              registry=self.registry)
        self.pool = pool if pool is not None else ReplicaPool(
            metrics=self.metrics, poll_interval_s=poll_interval_s,
            probe_timeout_s=probe_timeout_s, tracer=self.tracer)
        if self.pool.metrics is None:
            self.pool.metrics = self.metrics
        for spec in replicas:
            self.pool.add(spec[0], int(spec[1]), *spec[2:3])
        self.policy = resolve_policy(policy)
        self.max_attempts = max_attempts
        self.max_body_bytes = max_body_bytes
        self.upstream_timeout_s = upstream_timeout_s
        self._ids = itertools.count()
        self._live: Dict[str, Tuple[str, str]] = {}  # rid -> (replica_id, upstream cid)
        self._live_lock = threading.Lock()
        # trace id -> owning replica, SURVIVING request finish (stitching a
        # trace is most useful after the request completed); bounded LRU
        self._trace_owner: "OrderedDict[str, str]" = OrderedDict()
        self._trace_owner_cap = 1024
        # router-side in-flight per replica: the poller's inflight reading is
        # up to a poll interval stale, so a burst arriving between polls would
        # all see the same "least-loaded" replica — forwards the router itself
        # has open are folded into the score instead
        self._forward_inflight: Dict[str, int] = {}
        self._inflight_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None

    # ------------------------------------------------------------- routing
    def _candidates(self, prompt, exclude: set, state: _RelayState) -> List[ReplicaSnapshot]:
        """One routing decision: snapshot the pool, let the policy order it.
        Re-run per attempt so health transitions observed mid-request (a
        candidate marked DOWN by the poller) are honored immediately."""
        t0 = time.perf_counter()
        with self.tracer.span("route", cat="router", trace=state.rid,
                              attempt=state.attempts, excluded=len(exclude)) as sp:
            snaps = self._adjusted_snapshots()
            candidates = self.policy.select(snaps, prompt=prompt,
                                            exclude=frozenset(exclude))
            sp.set(candidates=[c.id for c in candidates[:4]])
        self.metrics.route_decision.observe(time.perf_counter() - t0)
        return candidates

    def _adjusted_snapshots(self) -> List[ReplicaSnapshot]:
        with self._inflight_lock:
            fly = {k: v for k, v in self._forward_inflight.items() if v > 0}
        if not fly:
            return self.pool.snapshots()
        return [dataclasses.replace(s, inflight=s.inflight + fly.get(s.id, 0))
                for s in self.pool.snapshots()]

    def _inflight_delta(self, replica_id: str, delta: int):
        with self._inflight_lock:
            self._forward_inflight[replica_id] = \
                self._forward_inflight.get(replica_id, 0) + delta

    def _finish(self, state: _RelayState, replica_id: str, outcome: str):
        self.metrics.requests.inc(replica=replica_id, outcome=outcome)
        # NOT named "request": that name is the engine loop's per-request
        # timeline span, and /debug/trace consumers select by name
        self.tracer.add_span("router_request", self.tracer.epoch_time(state.arrival_t),
                             time.perf_counter() - state.arrival_t, cat="router",
                             trace=state.rid, replica=replica_id, outcome=outcome,
                             attempts=state.attempts, tokens=state.tokens_relayed)
        if replica_id != "none":
            self._note_owner(state.rid, replica_id)
        with self._live_lock:
            self._live.pop(state.rid, None)

    def _note_owner(self, rid: str, replica_id: str):
        with self._live_lock:
            self._trace_owner[rid] = replica_id
            self._trace_owner.move_to_end(rid)
            while len(self._trace_owner) > self._trace_owner_cap:
                self._trace_owner.popitem(last=False)

    def _track(self, state: _RelayState, replica_id: str, upstream_cid: str):
        with self._live_lock:
            self._live[state.rid] = (replica_id, upstream_cid)
        self._note_owner(state.rid, replica_id)

    # ------------------------------------------------------------- abort
    def abort(self, rid: str) -> bool:
        """Route a client abort to whichever replica owns the stream now."""
        with self._live_lock:
            owner = self._live.get(rid)
        if owner is None:
            return False
        replica_id, upstream_cid = owner
        replica = self.pool.get(replica_id)
        if replica is None:
            return False
        try:
            conn = http.client.HTTPConnection(replica.host, replica.port, timeout=10)
            try:
                conn.request("POST", "/v1/abort",
                             body=json.dumps({"id": upstream_cid}).encode(),
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                body = json.loads(resp.read() or b"{}")
            finally:
                conn.close()
            return bool(body.get("cancelled"))
        except _UPSTREAM_ERRORS + (ValueError,) as e:
            logger.warning(f"router: abort of {rid} on {replica_id} failed: {e!r}")
            return False

    # ------------------------------------------------------------- http plumbing
    def _make_httpd(self, host: str, port: int) -> ThreadingHTTPServer:
        router = self

        class Handler(JsonRequestHandler):
            log_prefix = "router"

            @property
            def max_body_bytes(self):  # live read: the cap is router-tunable
                return router.max_body_bytes

            def do_GET(self):
                try:
                    parts = urlsplit(self.path)
                    stitch_trace = None
                    if parts.path == "/debug/trace":
                        query = parse_qs(parts.query)
                        # a since_ts cursor means an incremental scrape of the
                        # router's own ring (route_observability contract) —
                        # only plain ?trace= requests pay for a two-tier stitch
                        if "since_ts" not in query:
                            stitch_trace = query.get("trace", [None])[0]
                    if stitch_trace is not None:
                        # two-tier stitch when the owning replica is known;
                        # falls back to the router-only timeline otherwise
                        doc = router.stitched_trace(stitch_trace)
                        self._send_raw(200, json.dumps(doc).encode(), "application/json")
                        return
                    if parts.path == "/fleet/metrics":
                        text, _skipped = router.fleet_metrics()
                        self._send_raw(200, text.encode(),
                                       "text/plain; version=0.0.4; charset=utf-8")
                        return
                    if parts.path == "/fleet/slo":
                        self._send_json(200, router.fleet_slo())
                        return
                    routed = route_observability(self.path, router.registry, router.tracer)
                    if routed is not None:
                        self._send_raw(routed[0], routed[2], routed[1])
                    elif self.path == "/health":
                        status, code = router.health_status()
                        self._send_json(code, {
                            "status": status,
                            "policy": getattr(router.policy, "name", type(router.policy).__name__),
                            "replicas": [s.to_dict() for s in router.pool.snapshots()],
                        })
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    logger.debug("router: client disconnected during GET")

            def do_POST(self):
                try:
                    if self.path == "/v1/completions":
                        payload = self._read_body()
                        if payload is not None:
                            router._handle_completion(self, payload)
                    elif self.path == "/v1/abort":
                        payload = self._read_body()
                        if payload is not None:
                            ok = router.abort(str(payload.get("id", "")))
                            self._send_json(200, {"id": payload.get("id"), "cancelled": ok})
                    else:
                        self._send_error_json(404, f"no route {self.path}", "not_found")
                except (BrokenPipeError, ConnectionResetError):
                    logger.debug("router: client disconnected during POST")
                except Exception as e:
                    logger.warning(f"router: error on {self.path}: {e!r}")
                    try:
                        self._send_error_json(500, str(e), "internal_error")
                    except (BrokenPipeError, ConnectionResetError):
                        pass

        httpd = ThreadingHTTPServer((host, port), Handler)
        httpd.daemon_threads = True
        return httpd

    def health_status(self) -> Tuple[str, int]:
        states = {s.state for s in self.pool.snapshots()}
        if states & {HEALTHY, RECOVERING}:
            return "ok", 200
        if DEGRADED in states:
            # still routable — the breaker may lift between poll and forward
            return "degraded", 200
        return "unhealthy", 503

    # ------------------------------------------------------------- fleet planes
    def _scrape_replica(self, snap: ReplicaSnapshot, path: str) -> str:
        conn = http.client.HTTPConnection(snap.host, snap.port,
                                          timeout=self.scrape_timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            text = resp.read().decode()
        finally:
            conn.close()
        if resp.status != 200:
            raise RuntimeError(f"{snap.id}{path}: HTTP {resp.status}")
        return text

    def fleet_families(self) -> Tuple[Dict[str, Dict], List[str]]:
        """Scrape + parse every non-DOWN replica's ``/metrics``. Returns
        ``({replica_id: parsed families}, [skipped ids])`` — a dead,
        unreachable, or unparseable replica shrinks the merge, it never fails
        it (partial fleet data beats no fleet data during exactly the
        incidents you scrape during). Scrapes run concurrently: one wedged
        replica that the poller hasn't demoted yet costs the whole merge one
        scrape timeout, not a timeout per bad replica."""
        out: Dict[str, Dict] = {}
        skipped: List[str] = []

        def scrape(snap):
            return snap.id, parse_prometheus_text(
                self._scrape_replica(snap, "/metrics"))

        live = []
        for snap in self.pool.snapshots():
            if snap.state == DOWN:
                skipped.append(snap.id)
            else:
                live.append(snap)
        if not live:
            return out, skipped
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=min(8, len(live))) as pool:
            futures = {pool.submit(scrape, s): s for s in live}
            for fut in concurrent.futures.as_completed(futures):
                snap = futures[fut]
                try:
                    rid, fams = fut.result()
                    out[rid] = fams
                except Exception as e:
                    logger.warning(f"router: fleet scrape of {snap.id} failed: {e!r}")
                    self.metrics.fleet_scrape_errors.inc(replica=snap.id)
                    skipped.append(snap.id)
        return out, skipped

    def fleet_metrics(self) -> Tuple[str, List[str]]:
        """Federated exposition: every replica's samples under one scrape,
        re-labeled ``{replica="..."}``."""
        parsed, skipped = self.fleet_families()
        return federate_families(parsed), skipped

    def fleet_slo(self) -> Dict:
        """Scrape → fold → burn rates. Each call is one SLO observation; the
        tracker's history turns successive scrapes into windowed rates."""
        parsed, skipped = self.fleet_families()
        inputs = SLOInputs()
        for fams in parsed.values():
            inputs = inputs + slo_inputs_from_families(fams, self.slo.objectives)
        now = time.time()
        self.slo.observe(inputs, now=now)
        report = self.slo.report(now=now)
        report["replicas"] = sorted(parsed)
        report["skipped"] = skipped
        return report

    # ------------------------------------------------------------- trace stitch
    def stitched_trace(self, trace_id: str) -> Dict:
        """One request's two-tier timeline: the router's spans plus the owning
        replica's, clock-skew-corrected onto the router's timeline and merged
        into a single multi-process Chrome trace. Falls back to the
        router-only view when the owner is unknown/unreachable (the stitch
        degrades, it never 500s)."""
        router_events = self.tracer.chrome_trace(
            self.tracer.snapshot(trace=trace_id))["traceEvents"]
        tiers = [{"name": "router", "events": router_events,
                  "offset_s": 0.0, "dropped": self.tracer.dropped}]
        with self._live_lock:
            owner_id = self._trace_owner.get(trace_id)
        owner = self.pool.get(owner_id) if owner_id is not None else None
        stitch_error = None
        if owner is not None:
            try:
                raw = self._scrape_replica(
                    owner.snapshot(), f"/debug/trace?trace={quote(trace_id)}")
                doc = json.loads(raw)
                tiers.append({
                    "name": owner_id,
                    "events": doc.get("traceEvents", []),
                    "offset_s": self.pool.clock_offset(owner_id),
                    "dropped": doc.get("otherData", {}).get("dropped_spans", 0),
                })
            except Exception as e:
                logger.warning(f"router: trace fetch from {owner_id} failed: {e!r}")
                stitch_error = repr(e)
        merged = merge_chrome_traces(tiers)
        merged["otherData"]["trace"] = trace_id
        merged["otherData"]["replica"] = owner_id
        if stitch_error is not None:
            merged["otherData"]["stitch_error"] = stitch_error
        return merged

    # ------------------------------------------------------------- forwarding
    def _handle_completion(self, handler, payload: dict):
        rid = f"rtr-{next(self._ids)}"
        # the head-based sampling decision: made once here, pinned on the
        # router's tracer, and propagated to the replica in the traceparent
        # header — every tier then agrees without re-deciding
        sampled = trace_sampled(rid, self.trace_sample_every)
        if self.trace_sample_every > 1:
            self.tracer.mark_trace(rid, sampled)
        state = _RelayState(rid, bool(payload.get("stream")), sampled=sampled)
        prompt = payload.get("prompt")
        body = json.dumps(payload).encode()
        exclude: set = set()

        with use_trace(rid):
            self._relay_attempts(handler, state, payload, prompt, body, exclude)

    def _relay_attempts(self, handler, state: _RelayState, payload: dict,
                        prompt, body: bytes, exclude: set):
        while state.attempts < self.max_attempts:
            candidates = self._candidates(prompt, exclude, state)
            if not candidates:
                break
            cand = candidates[0]
            state.attempts += 1
            self._inflight_delta(cand.id, +1)
            try:
                if state.stream:
                    outcome = self._attempt_stream(handler, state, cand, body)
                else:
                    outcome = self._attempt_batch(handler, state, cand, body)
            finally:
                self._inflight_delta(cand.id, -1)
            if outcome == "done":
                return
            if outcome == "reroute":
                # nothing relayed; 429/503/connect failure — next candidate
                exclude.add(cand.id)
                self.metrics.rerouted.inc()
                self.tracer.instant("reroute", cat="router", trace=state.rid,
                                    replica=cand.id)
                continue
            if outcome == "failover":
                # accepted then failed pre-token: transparent resubmission
                exclude.add(cand.id)
                self.pool.note_forward_failure(cand.id)
                self.metrics.failovers.inc()
                self.tracer.add_span("failover", self.tracer.epoch_time(state.arrival_t),
                                     time.perf_counter() - state.arrival_t, cat="router",
                                     trace=state.rid, replica=cand.id,
                                     attempt=state.attempts)
                continue
            if outcome == "midstream_failed":
                self._terminate_midstream(handler, state, cand, payload)
                return
            if outcome == "client_gone":
                self._finish(state, cand.id, "client_gone")
                return

        # candidates/attempts exhausted
        self._reject_exhausted(handler, state, payload)

    def _reject_exhausted(self, handler, state: _RelayState, payload: dict):
        retry_after = max(1, int(round(self.pool.retry_after_hint())))
        if state.headers_sent:
            # SSE already open: a status line now would corrupt the stream —
            # same in-band contract as a mid-stream replica failure
            self._terminate_midstream(handler, state, None, payload)
            return
        self._finish(state, "none", "rejected")
        try:
            handler._send_error_json(
                503, "no replica available for this request; retry shortly",
                "no_replica_available", headers={"Retry-After": retry_after})
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _forward_headers(self, state: _RelayState) -> Dict[str, str]:
        """Per-forward headers: the traceparent contract. The parent span id
        names the router's request span (``<rid>@router``) so the replica's
        stitched spans can point back at the tier that placed them."""
        return {
            "Content-Type": "application/json",
            TRACEPARENT_HEADER: format_traceparent(
                state.rid, f"{state.rid}@router", state.sampled),
        }

    # ------------------------------------------------------------- batch leg
    def _attempt_batch(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                       body: bytes) -> str:
        conn = http.client.HTTPConnection(cand.host, cand.port,
                                          timeout=self.upstream_timeout_s)
        try:
            try:
                _F_FORWARD.fire(replica=cand.id)
                conn.request("POST", "/v1/completions", body=body,
                             headers=self._forward_headers(state))
                resp = conn.getresponse()
                raw = resp.read()
            except _UPSTREAM_ERRORS as e:
                logger.warning(f"router: forward to {cand.id} failed: {e!r}")
                self.pool.note_forward_failure(cand.id)
                return "reroute"
            if resp.status in (429, 503):
                self._note_reject(cand, resp)
                return "reroute"
            if resp.status >= 500:
                # replica-internal failure (api.py maps unexpected exceptions
                # to 500): the request was accepted then failed — another
                # replica may well serve it
                logger.warning(f"router: {cand.id} answered {resp.status}")
                return "failover"
            if resp.status != 200:
                # the replica judged the request itself bad (400/413): relay
                # verbatim — another replica would say the same thing
                self._finish(state, cand.id, "error")
                self._relay_raw(handler, resp.status, raw)
                return "done"
            try:
                doc = json.loads(raw)
                finish = (doc.get("choices") or [{}])[0].get("finish_reason")
            except (ValueError, AttributeError, IndexError):
                doc, finish = None, None
            if doc is None or finish == "engine_error":
                # the replica accepted then failed it (or returned junk);
                # nothing reached the client — resubmit elsewhere
                return "failover"
            doc["id"] = state.rid
            doc["replica"] = cand.id
            self._finish(state, cand.id, "ok")
            self._relay_raw(handler, 200, json.dumps(doc).encode())
            return "done"
        finally:
            conn.close()

    def _note_reject(self, cand: ReplicaSnapshot, resp):
        retry_after = resp.getheader("Retry-After")
        if resp.status == 503:
            self.pool.note_degraded(
                cand.id, retry_after_s=float(retry_after) if retry_after else None)

    def _relay_raw(self, handler, status: int, raw: bytes):
        try:
            handler._send_raw(status, raw, "application/json")
        except (BrokenPipeError, ConnectionResetError):
            logger.debug("router: client disconnected before response relay")

    # ------------------------------------------------------------- stream leg
    def _attempt_stream(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                        body: bytes) -> str:
        conn = http.client.HTTPConnection(cand.host, cand.port,
                                          timeout=self.upstream_timeout_s)
        try:
            try:
                _F_FORWARD.fire(replica=cand.id)
                conn.request("POST", "/v1/completions", body=body,
                             headers=self._forward_headers(state))
                resp = conn.getresponse()
            except _UPSTREAM_ERRORS as e:
                logger.warning(f"router: forward to {cand.id} failed: {e!r}")
                self.pool.note_forward_failure(cand.id)
                return "reroute"
            if resp.status in (429, 503):
                self._note_reject(cand, resp)
                resp.read()
                return "reroute"
            if resp.status >= 500:
                # replica-internal failure: accepted then failed, retryable
                logger.warning(f"router: {cand.id} answered {resp.status}")
                resp.read()
                return "failover"
            if resp.status != 200:
                raw = resp.read()
                if state.headers_sent:
                    return "failover"  # can't restate the status; try elsewhere
                self._finish(state, cand.id, "error")
                self._relay_raw(handler, resp.status, raw)
                return "done"
            return self._relay_sse(handler, state, cand, resp)
        finally:
            conn.close()

    def _relay_sse(self, handler, state: _RelayState, cand: ReplicaSnapshot,
                   resp) -> str:
        """Relay one upstream SSE leg. Returns done / failover /
        midstream_failed / client_gone."""
        if not state.headers_sent:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("Connection", "close")
            handler.end_headers()
            state.headers_sent = True

        def upstream_broke() -> str:
            if state.finished:
                # the client already has its terminal chunk; only [DONE] was
                # lost — close out the stream ourselves
                try:
                    handler.wfile.write(b"data: [DONE]\n\n")
                    handler.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    return "client_gone"
                self._finish(state, cand.id, "ok")
                return "done"
            return "failover" if state.tokens_relayed == 0 else "midstream_failed"

        while True:
            try:
                line = resp.readline()
            except _UPSTREAM_ERRORS as e:
                logger.warning(f"router: stream from {cand.id} broke: {e!r}")
                return upstream_broke()
            if not line:
                # upstream closed without [DONE]: a crash, not a completion
                return upstream_broke()
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            data = line[len(b"data: "):]
            if data == b"[DONE]":
                # the terminal chunk was already relayed on a previous line
                try:
                    handler.wfile.write(b"data: [DONE]\n\n")
                    handler.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    return "client_gone"
                self._finish(state, cand.id, "ok" if state.finished else "error")
                return "done"
            try:
                ev = json.loads(data)
            except ValueError:
                continue
            if ev.get("object") == "error":
                # upstream's in-band internal error (its headers were already
                # sent too) — same disposition as a transport drop
                return upstream_broke()
            upstream_cid = ev.get("id")
            if upstream_cid:
                self._track(state, cand.id, str(upstream_cid))
            choice = (ev.get("choices") or [{}])[0]
            finish = choice.get("finish_reason")
            if finish == "engine_error":
                # the replica's supervisor gave up on this request: pre-token
                # it is ours to retry elsewhere, mid-stream it becomes the
                # router-level replica_error terminal
                return "failover" if state.tokens_relayed == 0 else "midstream_failed"
            ev["id"] = state.rid
            if finish:
                ev["replica"] = cand.id
            try:
                handler.wfile.write(f"data: {json.dumps(ev)}\n\n".encode())
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                logger.debug(f"router: client left stream {state.rid}; aborting upstream")
                self._abort_upstream(state, cand)
                return "client_gone"
            if finish:
                state.finished = True
            elif "token" in choice:
                state.tokens_relayed += 1

    def _abort_upstream(self, state: _RelayState, cand: ReplicaSnapshot):
        with self._live_lock:
            owner = self._live.get(state.rid)
        if owner is not None and owner[0] == cand.id:
            self.abort(state.rid)

    def _terminate_midstream(self, handler, state: _RelayState,
                             cand: Optional[ReplicaSnapshot], payload: dict):
        """In-band terminal for a stream whose replica died after tokens were
        relayed (PR 3's engine_error contract, one level up): final chunk with
        ``finish_reason="replica_error"`` + usage covering what the client
        actually received, then [DONE] — never a mid-stream connection reset."""
        replica_id = cand.id if cand is not None else "none"
        if cand is not None:
            self.pool.note_forward_failure(cand.id)
        prompt = payload.get("prompt")
        self._finish(state, replica_id, "replica_error")
        try:
            usage = {"completion_tokens": state.tokens_relayed}
            if isinstance(prompt, (list, tuple)):
                # for a string prompt the router cannot know the token count
                # (no tokenizer); omit rather than emit a null the client's
                # usage accounting would trip over
                usage["prompt_tokens"] = len(prompt)
                usage["total_tokens"] = len(prompt) + state.tokens_relayed
            final = {"id": state.rid, "object": "text_completion.chunk",
                     "replica": replica_id,
                     "choices": [{"index": 0, "finish_reason": "replica_error"}],
                     "usage": usage}
            handler.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
            handler.wfile.write(b"data: [DONE]\n\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass

    # ------------------------------------------------------------- lifecycle
    def start_in_thread(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start poller + HTTP without blocking; returns the bound port."""
        self.pool.start()
        self._httpd = self._make_httpd(host, port)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="router-http")
        t.start()
        bound = self._httpd.server_address[1]
        logger.info(f"router on {host}:{bound} fronting {len(self.pool)} replicas "
                    f"(policy={getattr(self.policy, 'name', '?')})")
        return bound

    def run(self, host: str = "0.0.0.0", port: int = 8010):
        self.pool.start()
        self._httpd = self._make_httpd(host, port)
        logger.info(f"router on {host}:{port} fronting {len(self.pool)} replicas")
        try:
            self._httpd.serve_forever()
        finally:
            self.shutdown()

    def shutdown(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
        self.pool.stop()
