"""In-process fleet launcher: N ``ServingServer`` replicas on ephemeral ports.

The whole front tier is exercisable under tier-1 CPU tests and the smoke
bench without any external process management: ``launch_replicas`` builds N
real serving replicas (each with its **own** ``MetricsRegistry`` — the pull
gauges bind to one engine, so replicas must never share a registry) and
``launch_fleet`` puts a started ``RouterServer`` in front of them.

Everything here takes an ``engine_factory`` callable instead of an engine so
the module stays import-light (no jax until a factory runs) and each replica's
supervisor can rebuild its engine independently.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

from ...utils.log import logger
from ..api import ServingServer
from ..metrics import MetricsRegistry
from .proxy import RouterServer

__all__ = ["ReplicaFleet", "launch_replicas", "launch_fleet"]


class ReplicaFleet:
    """Handle over N started in-process replicas (and optionally a router).

    With a router attached the fleet is *elastic*: :meth:`add_replica` grows
    it live (new ``ServingServer`` + pool registration + an immediate probe)
    and :meth:`drain_replica` shrinks it with zero stream loss (drain → wait
    for the router's live forwards to land → remove → shut the server down)
    — the in-process mirror of the router's ``POST /replicas`` /
    ``POST /replicas/drain`` / ``DELETE /replicas/{id}`` admin plane."""

    def __init__(self, servers: List[ServingServer], ports: List[int], host: str,
                 engine_factory: Optional[Callable[[], object]] = None,
                 replica_kw: Optional[dict] = None):
        self.servers = servers
        self.ports = ports
        self.host = host
        self.engine_factory = engine_factory
        self.replica_kw = dict(replica_kw or {})
        self.router: Optional[RouterServer] = None
        self.router_port: Optional[int] = None

    def endpoints(self) -> List[Tuple[str, int]]:
        return [(self.host, p) for p in self.ports]

    def registries(self) -> List[MetricsRegistry]:
        return [s.registry for s in self.servers]

    def replica_id(self, index: int) -> str:
        """The pool id of the index-th replica (the launcher registers
        replicas under their ``host:port``)."""
        return f"{self.host}:{self.ports[index]}"

    def add_replica(self) -> str:
        """Start one more in-process replica and join it to the router's pool
        (probed before the id is returned, so it routes on real health)."""
        if self.router is None:
            raise RuntimeError("add_replica needs a router (use launch_fleet)")
        if self.engine_factory is None:
            raise RuntimeError("fleet was built without an engine_factory")
        server = ServingServer(
            self.engine_factory(), registry=MetricsRegistry(),
            engine_factory=self.engine_factory, **self.replica_kw)
        port = server.start_in_thread(host=self.host)
        try:
            self.router.pool.add(self.host, port)
        except BaseException:
            server.shutdown(drain_timeout_s=1.0)
            raise
        self.servers.append(server)
        self.ports.append(port)
        rid = f"{self.host}:{port}"
        # targeted probe, same as the HTTP admin plane: no full-fleet sweep
        # (and no drain bookkeeping) on the caller thread
        self.router.pool.probe_one(rid)
        return rid

    def drain_replica(self, replica, deadline_s: float = 30.0,
                      wait_timeout_s: float = 60.0, poll_every_s: float = 0.05) -> bool:
        """Drain one replica (index or pool id) out of the fleet: no new
        requests, in-flight streams finish (bounded by ``deadline_s``, after
        which token-less survivors fail over), then the replica is removed
        from the pool and its server shut down. Returns True when the drain
        completed cleanly before removal."""
        if self.router is None:
            raise RuntimeError("drain_replica needs a router (use launch_fleet)")
        rid = self.replica_id(replica) if isinstance(replica, int) else str(replica)
        pool = self.router.pool
        pool.start_drain(rid, deadline_s=deadline_s)
        deadline = time.time() + wait_timeout_s
        drained = False
        # a started router's own poller drives the drain sweeps; only a pool
        # without a poller thread needs manual sweeps (concurrent poll_once
        # from two threads is tolerated but pointless)
        drive_manually = pool._thread is None
        while time.time() < deadline:
            if drive_manually:
                pool.poll_once()  # probe + drain-progress + deadline hook
            status = pool.drain_status(rid)
            if status is not None and status.get("drained"):
                drained = True
                break
            time.sleep(poll_every_s)
        # through the router's admin method (not bare pool.remove) so the
        # removal also drops the router-side accounting for the id
        code, doc = self.router.admin_remove_replica(rid, force=not drained)
        if code != 200:
            raise RuntimeError(f"removing {rid} failed: {doc}")
        idx = next((i for i, p in enumerate(self.ports)
                    if f"{self.host}:{p}" == rid), None)
        if idx is not None:
            server = self.servers.pop(idx)
            self.ports.pop(idx)
            try:
                server.shutdown(drain_timeout_s=5.0)
            except Exception as e:
                logger.warning(f"fleet: drained replica shutdown failed: {e!r}")
        return drained

    def shutdown(self, drain_timeout_s: Optional[float] = 10.0):
        """Router first (stop admitting), then the replicas (drain)."""
        if self.router is not None:
            self.router.shutdown()
            self.router = None
        for server in self.servers:
            try:
                server.shutdown(drain_timeout_s=drain_timeout_s)
            except Exception as e:
                logger.warning(f"fleet: replica shutdown failed: {e!r}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def launch_replicas(n: int, engine_factory: Callable[[], object], *,
                    tokenizer=None, scheduler_config=None, supervisor_policy=None,
                    host: str = "127.0.0.1") -> ReplicaFleet:
    """Start ``n`` in-process serving replicas on ephemeral ports.

    Each replica gets a fresh engine from ``engine_factory`` (which also
    serves as its supervisor's rebuild factory) and a private registry."""
    if n < 1:
        raise ValueError("n must be >= 1")
    replica_kw = dict(tokenizer=tokenizer, scheduler_config=scheduler_config,
                      supervisor_policy=supervisor_policy)
    servers: List[ServingServer] = []
    ports: List[int] = []
    try:
        for _ in range(n):
            server = ServingServer(
                engine_factory(), registry=MetricsRegistry(),
                engine_factory=engine_factory, **replica_kw)
            ports.append(server.start_in_thread(host=host))
            servers.append(server)
    except BaseException:
        for server in servers:
            server.shutdown(drain_timeout_s=1.0)
        raise
    return ReplicaFleet(servers, ports, host, engine_factory=engine_factory,
                        replica_kw=replica_kw)


def launch_fleet(n: int, engine_factory: Callable[[], object], *,
                 policy="least_loaded", router_registry: Optional[MetricsRegistry] = None,
                 poll_interval_s: float = 0.1, max_attempts: int = 3,
                 trace_sample_every: int = 1,
                 hedge_after_s: Optional[float] = None,
                 max_hedges_inflight: int = 4,
                 host: str = "127.0.0.1", **replica_kw) -> ReplicaFleet:
    """``launch_replicas`` + a started :class:`RouterServer` in front.

    Returns the fleet with ``.router`` / ``.router_port`` set; one initial
    synchronous poll sweep runs before the port is returned so the first
    request already routes on real health/load data."""
    from ...observability.tracer import SpanTracer

    fleet = launch_replicas(n, engine_factory, host=host, **replica_kw)
    try:
        # private tracer: in-process replicas share the global TRACER, and a
        # router recording into the same ring would double every stitched span
        router = RouterServer(fleet.endpoints(), policy=policy,
                              registry=router_registry or MetricsRegistry(),
                              poll_interval_s=poll_interval_s,
                              max_attempts=max_attempts,
                              trace_sample_every=trace_sample_every,
                              hedge_after_s=hedge_after_s,
                              max_hedges_inflight=max_hedges_inflight,
                              tracer=SpanTracer())
        router.pool.poll_once()
        fleet.router = router
        fleet.router_port = router.start_in_thread(host=host)
    except BaseException:
        fleet.shutdown(drain_timeout_s=1.0)
        raise
    return fleet
