"""In-process fleet launcher: N ``ServingServer`` replicas on ephemeral ports.

The whole front tier is exercisable under tier-1 CPU tests and the smoke
bench without any external process management: ``launch_replicas`` builds N
real serving replicas (each with its **own** ``MetricsRegistry`` — the pull
gauges bind to one engine, so replicas must never share a registry) and
``launch_fleet`` puts a started ``RouterServer`` in front of them.

Everything here takes an ``engine_factory`` callable instead of an engine so
the module stays import-light (no jax until a factory runs) and each replica's
supervisor can rebuild its engine independently.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ...utils.log import logger
from ..api import ServingServer
from ..metrics import MetricsRegistry
from .proxy import RouterServer

__all__ = ["ReplicaFleet", "launch_replicas", "launch_fleet"]


class ReplicaFleet:
    """Handle over N started in-process replicas (and optionally a router)."""

    def __init__(self, servers: List[ServingServer], ports: List[int], host: str):
        self.servers = servers
        self.ports = ports
        self.host = host
        self.router: Optional[RouterServer] = None
        self.router_port: Optional[int] = None

    def endpoints(self) -> List[Tuple[str, int]]:
        return [(self.host, p) for p in self.ports]

    def registries(self) -> List[MetricsRegistry]:
        return [s.registry for s in self.servers]

    def shutdown(self, drain_timeout_s: Optional[float] = 10.0):
        """Router first (stop admitting), then the replicas (drain)."""
        if self.router is not None:
            self.router.shutdown()
            self.router = None
        for server in self.servers:
            try:
                server.shutdown(drain_timeout_s=drain_timeout_s)
            except Exception as e:
                logger.warning(f"fleet: replica shutdown failed: {e!r}")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def launch_replicas(n: int, engine_factory: Callable[[], object], *,
                    tokenizer=None, scheduler_config=None, supervisor_policy=None,
                    host: str = "127.0.0.1") -> ReplicaFleet:
    """Start ``n`` in-process serving replicas on ephemeral ports.

    Each replica gets a fresh engine from ``engine_factory`` (which also
    serves as its supervisor's rebuild factory) and a private registry."""
    if n < 1:
        raise ValueError("n must be >= 1")
    servers: List[ServingServer] = []
    ports: List[int] = []
    try:
        for _ in range(n):
            server = ServingServer(
                engine_factory(), tokenizer=tokenizer,
                scheduler_config=scheduler_config,
                registry=MetricsRegistry(),
                engine_factory=engine_factory,
                supervisor_policy=supervisor_policy)
            ports.append(server.start_in_thread(host=host))
            servers.append(server)
    except BaseException:
        for server in servers:
            server.shutdown(drain_timeout_s=1.0)
        raise
    return ReplicaFleet(servers, ports, host)


def launch_fleet(n: int, engine_factory: Callable[[], object], *,
                 policy="least_loaded", router_registry: Optional[MetricsRegistry] = None,
                 poll_interval_s: float = 0.1, max_attempts: int = 3,
                 trace_sample_every: int = 1,
                 host: str = "127.0.0.1", **replica_kw) -> ReplicaFleet:
    """``launch_replicas`` + a started :class:`RouterServer` in front.

    Returns the fleet with ``.router`` / ``.router_port`` set; one initial
    synchronous poll sweep runs before the port is returned so the first
    request already routes on real health/load data."""
    from ...observability.tracer import SpanTracer

    fleet = launch_replicas(n, engine_factory, host=host, **replica_kw)
    try:
        # private tracer: in-process replicas share the global TRACER, and a
        # router recording into the same ring would double every stitched span
        router = RouterServer(fleet.endpoints(), policy=policy,
                              registry=router_registry or MetricsRegistry(),
                              poll_interval_s=poll_interval_s,
                              max_attempts=max_attempts,
                              trace_sample_every=trace_sample_every,
                              tracer=SpanTracer())
        router.pool.poll_once()
        fleet.router = router
        fleet.router_port = router.start_in_thread(host=host)
    except BaseException:
        fleet.shutdown(drain_timeout_s=1.0)
        raise
    return fleet
