"""Closed-loop fleet autoscaling: the policy loop over the elastic admin plane.

PR 10 made the fleet elastic (``POST /replicas`` / ``POST /replicas/drain`` /
``DELETE /replicas/{id}`` with zero-stream-loss drains) and PR 13 made it
observable (multi-window SLO burn rates on ``/fleet/slo``, per-replica health
and KV pressure on ``/replicas``). This module closes the loop: a control
thread that watches those signals and *drives* the admin plane, so a traffic
surge grows the fleet and a dead replica is replaced before a human notices.

The loop is deliberately an **external HTTP client** of the router — it runs
in-process for tests/bench (:class:`InProcessProvisioner`) or as a standalone
operator daemon (``tools/autoscaler.py`` + :class:`SubprocessProvisioner`)
against a production router, with identical decision logic.

**Decision ladder, one evaluation per tick** (every decision is a
flight-recorder event and a ``paddlenlp_router_autoscaler_*`` metric):

1. **Replace** — a DOWN, non-draining replica is force-removed (its streams
   are already failing over through the router's ordinary paths) and a
   replacement is owed. Availability repair ignores hysteresis and cooldowns.
2. **Scale up** — sustained overload (mean ``kv_utilization`` / mean engine
   queue depth over the live replicas, or the shortest-window SLO burn rate,
   past their thresholds for ``hysteresis_up`` consecutive ticks, outside the
   up-cooldown) adds ``<= max_step_up`` replicas, bounded by
   ``max_replicas``.
3. **Hold + brownout handoff** — overload at the max envelope cannot scale;
   the loop records ``scale.hold{max_envelope}`` and pushes a brownout floor
   to every live replica (``POST /admin/brownout``, the drain-propagation
   channel) so the fleet degrades selectively — shed best-effort, keep
   interactive TTFT — instead of timing out uniformly. Pushes repeat each
   tick to refresh the replica-side TTL; the floor lifts itself when the
   overload (and the pushes) stop.
4. **Scale down** — sustained calm for ``hysteresis_down`` ticks outside the
   down-cooldown drains the least-loaded replica(s) (zero stream loss — the
   admin plane's drain machinery). The drain is finalized on LATER ticks
   (removed once the pool reports it drained, force-removed past the
   deadline, then returned to the provisioner) so the control thread never
   blocks on an in-flight stream — a replica dying mid-drain is still
   replaced promptly.

**Chaos safety.** Every provision attempt runs through the
``router.provision`` fault point. A failed provision (or a provision whose
admin-plane join fails — the orphan replica is torn back down) leaves a
*deficit* the loop retries with exponential backoff on later ticks, so a
tombstoned (force-removed DOWN) replica is never silently left unreplaced
and a flapping provider cannot hot-loop the provider API.

**Concurrency model.** All decision state (streaks, cooldown stamps, the
provisioning deficit) is confined to the control thread — tests drive
:meth:`Autoscaler.evaluate_once` directly from their own single thread
instead. ``_stop`` is a ``threading.Event`` (self-synchronized).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
from typing import Dict, List, Optional, Tuple

from ...observability.flight_recorder import RECORDER
from ...utils.faults import FaultPoint
from ...utils.log import logger
from ..metrics import MetricsRegistry
from .metrics import AutoscalerMetrics
from .pool import DOWN
from .pool import push_brownout as push_brownout_to_replica

__all__ = ["Autoscaler", "AutoscalerPolicy", "FleetObservation",
           "ReplicaObservation", "ProvisionedReplica", "ReplicaProvisioner",
           "InProcessProvisioner", "SubprocessProvisioner", "RouterAdminClient"]

_F_PROVISION = FaultPoint("router.provision")


# --------------------------------------------------------------------- policy
@dataclasses.dataclass
class AutoscalerPolicy:
    """Envelope, thresholds and damping for the control loop.

    Scale-up triggers on ANY overload signal (mean KV utilization, mean
    engine queue depth, shortest-window SLO burn); scale-down requires ALL
    signals calm. ``hysteresis_*`` are consecutive-tick requirements,
    ``cooldown_*`` wall-clock spacing between actions in the same direction —
    together they keep an oscillating signal from flapping the fleet."""

    min_replicas: int = 1
    max_replicas: int = 4
    scale_up_kv_utilization: float = 0.85
    scale_up_queue_depth: float = 4.0
    scale_up_burn_rate: float = 10.0
    scale_down_kv_utilization: float = 0.30
    scale_down_queue_depth: float = 0.5
    hysteresis_up: int = 2
    hysteresis_down: int = 5
    cooldown_up_s: float = 10.0
    cooldown_down_s: float = 30.0
    max_step_up: int = 2
    max_step_down: int = 1
    drain_deadline_s: float = 30.0
    provision_backoff_base_s: float = 0.5
    provision_backoff_max_s: float = 30.0
    # brownout handoff while pinned at the max envelope (0 disables)
    brownout_push_level: int = 1
    brownout_push_ttl_s: float = 30.0

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{self.min_replicas}/{self.max_replicas}")
        if self.hysteresis_up < 1 or self.hysteresis_down < 1:
            raise ValueError("hysteresis_up/down must be >= 1")
        if self.max_step_up < 1 or self.max_step_down < 1:
            raise ValueError("max_step_up/down must be >= 1")


# --------------------------------------------------------------------- signals
@dataclasses.dataclass
class ReplicaObservation:
    """One replica row folded out of ``GET /replicas``."""

    id: str
    state: str = "healthy"
    draining: bool = False
    drained: bool = False  # drain complete — safe to remove
    kv_utilization: float = 0.0
    queue_depth: float = 0.0
    host: str = ""
    port: int = 0


@dataclasses.dataclass
class FleetObservation:
    """One control-loop input: the replica set + the fast-window burn rates
    (tests construct these directly; :meth:`Autoscaler.observe` scrapes
    them)."""

    replicas: List[ReplicaObservation] = dataclasses.field(default_factory=list)
    availability_burn: float = 0.0
    ttft_burn: float = 0.0


# ----------------------------------------------------------------- admin client
class RouterAdminClient:
    """Thin HTTP client over the router's admin + fleet planes (stdlib only,
    swappable in tests)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s

    def _request(self, method: str, path: str, payload: Optional[dict] = None,
                 host: Optional[str] = None, port: Optional[int] = None
                 ) -> Tuple[int, dict]:
        conn = http.client.HTTPConnection(host or self.host, port or self.port,
                                          timeout=self.timeout_s)
        try:
            body = json.dumps(payload).encode() if payload is not None else None
            conn.request(method, path, body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = resp.read()
        finally:
            conn.close()
        try:
            doc = json.loads(raw) if raw else {}
        except ValueError:
            doc = {}
        return resp.status, doc

    def list_replicas(self) -> dict:
        status, doc = self._request("GET", "/replicas")
        if status != 200:
            raise RuntimeError(f"GET /replicas: HTTP {status}")
        return doc

    def slo(self) -> dict:
        status, doc = self._request("GET", "/fleet/slo")
        if status != 200:
            raise RuntimeError(f"GET /fleet/slo: HTTP {status}")
        return doc

    def add_replica(self, host: str, port: int) -> dict:
        status, doc = self._request("POST", "/replicas",
                                    {"host": host, "port": port})
        if status != 200:
            raise RuntimeError(f"POST /replicas {host}:{port}: HTTP {status} {doc}")
        return doc

    def drain_replica(self, replica_id: str, deadline_s: float) -> dict:
        status, doc = self._request("POST", "/replicas/drain",
                                    {"id": replica_id, "deadline_s": deadline_s})
        if status != 200:
            raise RuntimeError(f"POST /replicas/drain {replica_id}: HTTP {status}")
        return doc

    def remove_replica(self, replica_id: str, force: bool = False) -> dict:
        from urllib.parse import quote

        path = f"/replicas/{quote(replica_id, safe='')}" + ("?force=1" if force else "")
        status, doc = self._request("DELETE", path)
        if status != 200:
            raise RuntimeError(f"DELETE {path}: HTTP {status} {doc}")
        return doc

    def push_brownout(self, host: str, port: int, level: int,
                      reason: str = "slo_fast_burn",
                      ttl_s: Optional[float] = None) -> bool:
        """Direct-to-replica brownout push (best effort, never raises)."""
        return push_brownout_to_replica(host, port, level, reason=reason,
                                        ttl_s=ttl_s, timeout_s=self.timeout_s)


# ----------------------------------------------------------------- provisioners
@dataclasses.dataclass
class ProvisionedReplica:
    host: str
    port: int


class ReplicaProvisioner:
    """Pluggable replica lifecycle provider. ``provision`` starts a replica
    server and returns its endpoint (the autoscaler joins it to the router);
    ``deprovision`` tears one down after the autoscaler removed it from the
    pool (unknown endpoints must be a no-op — the initial fleet was not
    provisioned here). ``close`` releases everything at shutdown."""

    def provision(self) -> ProvisionedReplica:
        raise NotImplementedError

    def deprovision(self, host: str, port: int):
        raise NotImplementedError

    def close(self):
        pass


class InProcessProvisioner(ReplicaProvisioner):
    """In-process replicas for tests and the CPU bench: each provision is a
    fresh ``ServingServer`` (own registry, own engine from
    ``engine_factory``) started on an ephemeral port in this process."""

    def __init__(self, engine_factory, host: str = "127.0.0.1",
                 replica_kw: Optional[dict] = None):
        self.engine_factory = engine_factory
        self.host = host
        self.replica_kw = dict(replica_kw or {})
        self.servers: Dict[Tuple[str, int], object] = {}

    def provision(self) -> ProvisionedReplica:
        from ..api import ServingServer

        server = ServingServer(
            self.engine_factory(), registry=MetricsRegistry(),
            engine_factory=self.engine_factory, **self.replica_kw)
        port = server.start_in_thread(host=self.host)
        self.servers[(self.host, port)] = server
        return ProvisionedReplica(self.host, port)

    def deprovision(self, host: str, port: int):
        server = self.servers.pop((host, port), None)
        if server is None:
            return
        try:
            server.shutdown(drain_timeout_s=5.0)
        except Exception as e:
            logger.warning(f"provisioner: shutdown of {host}:{port} failed: {e!r}")

    def close(self):
        for (host, port) in list(self.servers):
            self.deprovision(host, port)


class SubprocessProvisioner(ReplicaProvisioner):
    """Real-use provisioner: each replica is a subprocess launched from a
    command template (``{port}`` substituted with a fresh ephemeral port,
    ``{host}`` with the bind host), e.g.::

        python -m my_serving_entrypoint --host {host} --port {port}

    ``provision`` blocks until the replica's ``/health`` answers (bounded by
    ``ready_timeout_s``); ``deprovision`` terminates the subprocess."""

    def __init__(self, command: str, host: str = "127.0.0.1",
                 ready_timeout_s: float = 60.0):
        if "{port}" not in command:
            raise ValueError("command template must contain a {port} placeholder")
        self.command = command
        self.host = host
        self.ready_timeout_s = ready_timeout_s
        self.procs: Dict[Tuple[str, int], object] = {}

    @staticmethod
    def _free_port(host: str) -> int:
        import socket

        with socket.socket() as s:
            s.bind((host, 0))
            return s.getsockname()[1]

    def _wait_ready(self, host: str, port: int):
        deadline = time.time() + self.ready_timeout_s
        while time.time() < deadline:
            try:
                conn = http.client.HTTPConnection(host, port, timeout=2)
                try:
                    conn.request("GET", "/health")
                    conn.getresponse().read()
                finally:
                    conn.close()
                return
            except OSError:
                time.sleep(0.25)
        raise TimeoutError(
            f"replica on {host}:{port} not healthy within {self.ready_timeout_s}s")

    def provision(self) -> ProvisionedReplica:
        import shlex
        import subprocess

        port = self._free_port(self.host)
        cmd = [a.format(host=self.host, port=port)
               for a in shlex.split(self.command)]
        proc = subprocess.Popen(cmd)
        try:
            self._wait_ready(self.host, port)
        except BaseException:
            proc.terminate()
            raise
        self.procs[(self.host, port)] = proc
        return ProvisionedReplica(self.host, port)

    def deprovision(self, host: str, port: int):
        proc = self.procs.pop((host, port), None)
        if proc is None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except Exception:
            proc.kill()

    def close(self):
        for (host, port) in list(self.procs):
            self.deprovision(host, port)


# ----------------------------------------------------------------- control loop
class Autoscaler:
    """The SLO-driven control loop (see module docstring). ``router`` is the
    ``(host, port)`` of the router's HTTP plane (or a ready
    :class:`RouterAdminClient` — tests pass a stub)."""

    def __init__(self, router, provisioner: ReplicaProvisioner,
                 policy: Optional[AutoscalerPolicy] = None,
                 registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 2.0):
        if isinstance(router, (tuple, list)):
            self.admin = RouterAdminClient(router[0], int(router[1]))
        else:
            self.admin = router
        self.provisioner = provisioner
        self.policy = policy or AutoscalerPolicy()
        self.interval_s = interval_s
        self.metrics = AutoscalerMetrics(registry)
        self.metrics.target_envelope.set(self.policy.min_replicas, bound="min")
        self.metrics.target_envelope.set(self.policy.max_replicas, bound="max")
        # decision state — control-thread confined (tests drive evaluate_once
        # from their own single thread instead)
        self._over_streak = 0
        self._under_streak = 0
        self._last_up_t = -1e18
        self._last_down_t = -1e18
        self._deficit = 0  # replicas owed (replacements + failed provisions)
        # scale-down drains in flight: id -> {deadline_t, host, port}. Drains
        # are finalized on LATER ticks (remove once drained, force at the
        # deadline) so the control thread never blocks waiting on a stream —
        # a DOWN replica during a slow drain is still replaced promptly
        self._pending_drains: Dict[str, dict] = {}
        self._provision_backoff_s = 0.0
        self._provision_retry_t = -1e18
        self._last_hold_reason: Optional[str] = None
        # decision journal for bench/tests: (t, action, detail), bounded
        self.events: List[Tuple[float, str, dict]] = []
        self._events_cap = 512
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 30.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.evaluate_once()
            except Exception as e:  # one bad tick must not kill the loop
                logger.warning(f"autoscaler: evaluation failed: {e!r}")
            self._stop.wait(timeout=self.interval_s)

    # ------------------------------------------------------------- observation
    def observe(self) -> FleetObservation:
        """Scrape the router's ``/replicas`` + ``/fleet/slo`` planes into one
        observation. Polling ``/fleet/slo`` also *feeds* the router's SLO
        tracker — the control loop doubles as its scrape cadence."""
        doc = self.admin.list_replicas()
        replicas = []
        for row in doc.get("replicas", []):
            replicas.append(ReplicaObservation(
                id=str(row.get("id")),
                state=str(row.get("state", "healthy")),
                draining=bool(row.get("draining")),
                drained=bool((row.get("drain") or {}).get("drained")),
                kv_utilization=float(row.get("kv_utilization") or 0.0),
                queue_depth=float(row.get("queue_depth") or 0.0),
                host=str(row.get("host", "")),
                port=int(row.get("port") or 0)))
        availability_burn = ttft_burn = 0.0
        try:
            slo = self.admin.slo()
            windows = slo.get("windows") or {}
            if windows:
                shortest = windows[min(windows, key=lambda w: int(w.rstrip("s")))]
                availability_burn = float(shortest.get("availability_burn_rate", 0.0))
                ttft_burn = float(shortest.get("ttft_burn_rate", 0.0))
        except Exception as e:
            # partial signal beats no control loop: KV/queue pressure still
            # drives decisions while the SLO plane is unreachable
            logger.warning(f"autoscaler: /fleet/slo scrape failed: {e!r}")
        return FleetObservation(replicas=replicas,
                                availability_burn=availability_burn,
                                ttft_burn=ttft_burn)

    # ------------------------------------------------------------- decisions
    def evaluate_once(self, now: Optional[float] = None,
                      observation: Optional[FleetObservation] = None) -> dict:
        """One control-loop tick. Returns a summary of what was decided —
        the bench folds these into its JSON line, tests assert on them."""
        now = time.time() if now is None else now
        p = self.policy
        obs = self.observe() if observation is None else observation
        actions: List[Tuple[str, dict]] = []

        # 0 ------------------------------------------------ finalize pending drains
        self._advance_drains(obs, now, actions)

        live = [r for r in obs.replicas if not r.draining]
        down = [r for r in live if r.state == DOWN]
        healthy = [r for r in live if r.state != DOWN]

        # 1 ------------------------------------------------ replace DOWN replicas
        for dead in down:
            try:
                self.admin.remove_replica(dead.id, force=True)
            except Exception as e:
                logger.warning(f"autoscaler: removing DOWN {dead.id} failed: {e!r}")
                continue
            # the dead server (if this provisioner owns it) is returned now;
            # the REPLACEMENT is owed via the deficit, which retries with
            # backoff — a tombstoned replica is never silently forgotten
            try:
                self.provisioner.deprovision(dead.host, dead.port)
            except Exception as e:
                logger.warning(f"autoscaler: deprovision of {dead.id} failed: {e!r}")
            self._deficit += 1
            self._note("replace", {"replica": dead.id}, now, actions)
            RECORDER.record("scale.replace", replica=dead.id)
            self.metrics.decisions.inc(action="replace")
            logger.warning(f"autoscaler: replacing DOWN replica {dead.id}")

        # 2 ------------------------------------------------ min-envelope repair
        if len(healthy) + self._deficit < p.min_replicas:
            self._deficit = p.min_replicas - len(healthy)

        # 3 ------------------------------------------------ overload/underload signals
        n = len(healthy)
        kv = sum(r.kv_utilization for r in healthy) / n if n else 0.0
        queue = sum(r.queue_depth for r in healthy) / n if n else 0.0
        burn = max(obs.availability_burn, obs.ttft_burn)
        overloaded = (kv >= p.scale_up_kv_utilization
                      or queue >= p.scale_up_queue_depth
                      or burn >= p.scale_up_burn_rate)
        # underload reads only the LEADING signals (kv/queue pressure): the
        # burn rate is windowed memory of the incident — an already-calm
        # fleet would otherwise hold surge capacity until the short window
        # rolled past, long after hysteresis + cooldown said it was safe
        underloaded = (kv <= p.scale_down_kv_utilization
                       and queue <= p.scale_down_queue_depth)
        self._over_streak = self._over_streak + 1 if overloaded else 0
        self._under_streak = self._under_streak + 1 if underloaded else 0

        if overloaded and self._deficit == 0:
            if self._over_streak < p.hysteresis_up:
                self._hold("hysteresis", now, actions)
            elif now - self._last_up_t < p.cooldown_up_s:
                self._hold("cooldown", now, actions)
            elif n >= p.max_replicas:
                # scaling cannot help: hand off to the brownout ladder so the
                # fleet sheds best-effort work instead of timing out everyone
                self._hold("max_envelope", now, actions)
                self._push_brownout(healthy, now, actions)
            else:
                step = min(p.max_step_up, p.max_replicas - n)
                self._deficit += step
                self._last_up_t = now
                self._over_streak = 0
                self._last_hold_reason = None
                self._note("up", {"added": step, "target": n + step}, now, actions)
                RECORDER.record("scale.up", added=step, replicas=n + step)
                self.metrics.decisions.inc(action="up")
                logger.warning(
                    f"autoscaler: scaling up +{step} (kv={kv:.2f} queue={queue:.1f} "
                    f"burn={burn:.1f}) -> {n + step}")
        elif (underloaded and self._deficit == 0 and n > p.min_replicas):
            if self._under_streak < p.hysteresis_down:
                self._hold("hysteresis", now, actions)
            elif now - self._last_down_t < p.cooldown_down_s:
                self._hold("cooldown", now, actions)
            else:
                step = min(p.max_step_down, n - p.min_replicas)
                victims = sorted(
                    healthy, key=lambda r: (r.kv_utilization + r.queue_depth, r.id))
                removed = 0
                for victim in victims[:step]:
                    if self._start_drain_one(victim, now):
                        removed += 1
                if removed:
                    self._last_down_t = now
                    self._under_streak = 0
                    self._last_hold_reason = None
                    self._note("down", {"removed": removed, "target": n - removed},
                               now, actions)
                    RECORDER.record("scale.down", removed=removed,
                                    replicas=n - removed)
                    self.metrics.decisions.inc(action="down")
                    logger.warning(f"autoscaler: scaled down -{removed} -> {n - removed}")
        elif not overloaded and not underloaded:
            # inside the comfort band: clear the hold-episode dedup so the
            # next held episode records again
            self._last_hold_reason = None
        if n <= p.min_replicas and underloaded:
            self._hold("min_envelope", now, actions)

        # 4 ------------------------------------------------ settle the deficit
        joined = 0
        if self._deficit > 0:
            if now < self._provision_retry_t:
                self._hold("provision_backoff", now, actions)
            else:
                while self._deficit > 0 and n + joined < p.max_replicas:
                    if not self._provision_one(now, actions):
                        break
                    joined += 1

        self.metrics.replicas.set(n + joined)
        return {
            "t": now, "replicas": n + joined, "deficit": self._deficit,
            "kv_utilization": kv, "queue_depth": queue, "burn": burn,
            "overloaded": overloaded, "underloaded": underloaded,
            "actions": actions,
        }

    # ------------------------------------------------------------- helpers
    def _note(self, action: str, detail: dict, now: float,
              actions: Optional[List] = None):
        if actions is not None:
            actions.append((action, detail))
        self.events.append((now, action, detail))
        del self.events[:-self._events_cap]

    def _hold(self, reason: str, now: float, actions: List):
        """Record one suppressed-action episode (deduped on consecutive same
        reason so a long cooldown is one event, not one per tick)."""
        actions.append(("hold", {"reason": reason}))
        if self._last_hold_reason == reason:
            return
        self._last_hold_reason = reason
        self.events.append((now, "hold", {"reason": reason}))
        del self.events[:-self._events_cap]
        RECORDER.record("scale.hold", reason=reason)
        self.metrics.decisions.inc(action="hold")

    def _push_brownout(self, healthy: List[ReplicaObservation], now: float,
                       actions: List):
        """Max-envelope brownout handoff: push the floor to every live
        replica, refreshing its TTL each tick the condition persists."""
        level = self.policy.brownout_push_level
        if not level:
            return
        pushed = 0
        for r in healthy:
            if r.host and r.port and self.admin.push_brownout(
                    r.host, r.port, level, reason="slo_fast_burn",
                    ttl_s=self.policy.brownout_push_ttl_s):
                pushed += 1
        if pushed:
            self.metrics.brownout_pushes.inc(pushed)
            actions.append(("brownout_push", {"replicas": pushed, "level": level}))

    def _provision_one(self, now: float, actions: Optional[List] = None) -> bool:
        """Provision + join one replica. On any failure the deficit stays and
        the next attempt backs off exponentially; a replica that provisioned
        but failed to JOIN is torn back down (no orphans)."""
        try:
            _F_PROVISION.fire(deficit=self._deficit)
            rep = self.provisioner.provision()
        except Exception as e:
            self._provision_failed(now, f"provision: {e!r}")
            return False
        try:
            self.admin.add_replica(rep.host, rep.port)
        except Exception as e:
            try:
                self.provisioner.deprovision(rep.host, rep.port)
            except Exception:
                pass
            self._provision_failed(now, f"join {rep.host}:{rep.port}: {e!r}")
            return False
        self._deficit -= 1
        self._provision_backoff_s = 0.0
        self._note("provisioned", {"replica": f"{rep.host}:{rep.port}"}, now,
                   actions)
        logger.warning(f"autoscaler: provisioned replica {rep.host}:{rep.port} "
                       f"(deficit {self._deficit})")
        return True

    def _provision_failed(self, now: float, detail: str):
        self.metrics.provision_failures.inc()
        base = self.policy.provision_backoff_base_s
        self._provision_backoff_s = min(
            max(self._provision_backoff_s * 2, base),
            self.policy.provision_backoff_max_s)
        self._provision_retry_t = now + self._provision_backoff_s
        logger.warning(
            f"autoscaler: provision failed ({detail}); retrying in "
            f"{self._provision_backoff_s:.2f}s (deficit {self._deficit})")

    def _start_drain_one(self, victim: ReplicaObservation, now: float) -> bool:
        """Begin one scale-down drain (zero stream loss: the admin plane's
        drain machinery owns in-flight streams). Finalized by
        :meth:`_advance_drains` on later ticks — never blocks this one."""
        p = self.policy
        try:
            self.admin.drain_replica(victim.id, deadline_s=p.drain_deadline_s)
        except Exception as e:
            logger.warning(f"autoscaler: drain of {victim.id} failed: {e!r}")
            return False
        self._pending_drains[victim.id] = {
            # small grace past the router's own deadline: its drain enforcer
            # (pre-token eviction) gets to act before we force-remove
            "deadline_t": now + p.drain_deadline_s + 10.0,
            "host": victim.host, "port": victim.port,
        }
        return True

    def _advance_drains(self, obs: FleetObservation, now: float, actions: List):
        """Finalize pending scale-down drains: remove a victim once the pool
        reports it drained (or it vanished), force-remove at the deadline;
        a failed removal stays pending and retries next tick."""
        for rid, info in list(self._pending_drains.items()):
            row = next((r for r in obs.replicas if r.id == rid), None)
            drained = row is None or row.drained
            if not drained and now < info["deadline_t"]:
                continue
            try:
                self.admin.remove_replica(rid, force=not drained)
            except Exception as e:
                logger.warning(f"autoscaler: removal of {rid} failed: {e!r}")
                continue
            del self._pending_drains[rid]
            try:
                self.provisioner.deprovision(info["host"], info["port"])
            except Exception as e:
                logger.warning(f"autoscaler: deprovision of {rid} failed: {e!r}")
            self._note("drained", {"replica": rid, "forced": not drained},
                       now, actions)
