"""Multi-replica front tier: health-aware routing, cross-replica failover,
prefix-affinity placement.

The fourth pillar of the serving stack (after the continuous-batching
runtime, the observability layer, and the fault-tolerance supervisor): a
stdlib-only HTTP router that fronts N ``ServingServer`` replicas —

- :mod:`.pool` — replica registry + background health poller
  (HEALTHY → DEGRADED → DOWN → RECOVERING state machine off each replica's
  ``/health`` and ``/metrics`` planes);
- :mod:`.policy` — least-loaded candidate ordering and consistent-hash
  prefix affinity;
- :mod:`.proxy` — ``RouterServer``: SSE passthrough, 429/503 re-routing,
  pre-token failover, in-band ``replica_error`` mid-stream terminal;
- :mod:`.metrics` — the ``paddlenlp_router_*`` catalog;
- :mod:`.launcher` — in-process fleet helpers for tests and the CPU bench;
- :mod:`.autoscaler` — the closed-loop policy thread that watches
  ``/fleet/slo`` + ``/replicas`` and drives the admin plane (scale up/down,
  replace DOWN replicas, brownout handoff at the max envelope).
"""

from .autoscaler import (  # noqa: F401
    Autoscaler,
    AutoscalerPolicy,
    FleetObservation,
    InProcessProvisioner,
    ProvisionedReplica,
    ReplicaObservation,
    ReplicaProvisioner,
    RouterAdminClient,
    SubprocessProvisioner,
)
from .launcher import ReplicaFleet, launch_fleet, launch_replicas  # noqa: F401
from .metrics import (  # noqa: F401
    AutoscalerMetrics,
    RouterMetrics,
    federate_expositions,
    lint_federation,
)
from .policy import (  # noqa: F401
    HashRing,
    LeastLoadedPolicy,
    PrefixAffinityPolicy,
    load_score,
    resolve_policy,
)
from .pool import (  # noqa: F401
    DEGRADED,
    DOWN,
    DRAINED,
    DRAINING,
    HEALTHY,
    RECOVERING,
    REMOVED,
    DrainPendingError,
    ProbeResult,
    Replica,
    ReplicaPool,
    ReplicaSnapshot,
)
from .proxy import RouterServer  # noqa: F401

__all__ = [
    "RouterServer",
    "Autoscaler",
    "AutoscalerPolicy",
    "AutoscalerMetrics",
    "FleetObservation",
    "ReplicaObservation",
    "ReplicaProvisioner",
    "ProvisionedReplica",
    "InProcessProvisioner",
    "SubprocessProvisioner",
    "RouterAdminClient",
    "ReplicaPool",
    "Replica",
    "ReplicaSnapshot",
    "ProbeResult",
    "RouterMetrics",
    "federate_expositions",
    "lint_federation",
    "LeastLoadedPolicy",
    "PrefixAffinityPolicy",
    "HashRing",
    "load_score",
    "resolve_policy",
    "ReplicaFleet",
    "launch_replicas",
    "launch_fleet",
    "HEALTHY",
    "DEGRADED",
    "DOWN",
    "RECOVERING",
    "DRAINING",
    "DRAINED",
    "REMOVED",
    "DrainPendingError",
]
