"""Router metrics catalog + fleet metrics federation.

Same contract as :class:`~..engine_loop.ServingMetrics` for the replica plane:
names are stable API — the serving README catalog, ``tools/check_metrics.py``
(which instantiates this class so tier-1 lints the exposition) and
``tools/bench_serve.py --replicas N`` all consume them by string.

Federation (:func:`federate_expositions`): the router scrapes each replica's
``/metrics`` and merges the expositions into one, every sample re-labeled with
``{replica="<id>"}`` — "how is the fleet doing" becomes one scrape instead of
N. HELP/TYPE come from the first replica exposing each family;
:func:`lint_federation` catches the two ways a merge can lie (the same family
exposed with conflicting TYPEs across replicas, and a replica that already
carries a ``replica`` label, which the re-labeling would silently clobber).
"""

from __future__ import annotations

import math
from typing import Dict, List, Mapping, Optional, Tuple

from ...observability.prometheus import parse_prometheus_text
from ..metrics import REGISTRY, MetricsRegistry, _format_labels, _format_value

__all__ = ["RouterMetrics", "AutoscalerMetrics", "ROUTE_DECISION_BUCKETS",
           "federate_expositions", "federate_families", "lint_federation"]

# seconds; routing decisions are pure host work (snapshot + sort/hash), so the
# interesting range is tens of microseconds to a few milliseconds — the default
# latency buckets would dump every observation into the first bucket
ROUTE_DECISION_BUCKETS: Tuple[float, ...] = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05, 0.1,
)


class RouterMetrics:
    """Registers the router metric catalog in one registry.

    Push-mode only: the pool's health poller writes ``replica_healthy`` on
    every poll, and the proxy writes the request/failover counters at request
    terminal — there is no engine to bind pull gauges against."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = r = registry or REGISTRY
        self.requests = r.counter(
            "paddlenlp_router_requests_total",
            "Requests terminated by the router, by backing replica and outcome",
            labelnames=("replica", "outcome"))
        self.replica_healthy = r.gauge(
            "paddlenlp_router_replica_healthy",
            "1 when the replica's last health poll was HEALTHY, else 0",
            labelnames=("replica",))
        self.failovers = r.counter(
            "paddlenlp_router_failovers_total",
            "In-flight requests resubmitted to another replica after their "
            "replica failed before emitting a token")
        self.rerouted = r.counter(
            "paddlenlp_router_rerouted_total",
            "Forward attempts re-routed to the next candidate on a replica "
            "429/503 or connect failure (nothing relayed yet)")
        self.route_decision = r.histogram(
            "paddlenlp_router_route_decision_seconds",
            "Latency of one routing decision (pool snapshot + policy ordering)",
            buckets=ROUTE_DECISION_BUCKETS)
        self.health_polls = r.counter(
            "paddlenlp_router_health_polls_total",
            "Health-poller probes by replica and outcome (ok/degraded/error)",
            labelnames=("replica", "outcome"))
        self.fleet_scrape_errors = r.counter(
            "paddlenlp_router_fleet_scrape_errors_total",
            "Replica /metrics scrapes that failed during federation",
            labelnames=("replica",))
        self.hedges = r.counter(
            "paddlenlp_router_hedges_total",
            "Hedged stream attempts by outcome: primary_won/hedge_won (the "
            "shadow fired and lost/won the first-token race), capped (the "
            "in-flight-hedge cap suppressed it, counted at hedge-fire time), "
            "brownout (a leg's brownout level >= 2 suppressed the race, "
            "counted once per request at candidate selection), failed (both "
            "legs died)",
            labelnames=("outcome",))
        self.membership_changes = r.counter(
            "paddlenlp_router_membership_changes_total",
            "Admin-plane replica membership mutations by op (add/drain/remove)",
            labelnames=("op",))
        self.version_skew_terminations = r.counter(
            "paddlenlp_router_version_skew_total",
            "Token-bearing streams terminated in-band with "
            "finish_reason=version_skew because a weight rollout left no "
            "surviving replica on the stream's weights version")
        # same family name the replicas' ServingMetrics registers: the router
        # contributes the hedge_race phase (time from shadow launch to the
        # first usable event) so one histogram family carries the whole
        # attribution vocabulary across tiers
        self.latency_attribution = r.histogram(
            "paddlenlp_serving_latency_attribution_seconds",
            "Per-request e2e latency decomposed by phase (queue/"
            "admission_gate/prefill/chunk_stall/migration_wait/decode on "
            "replicas; hedge_race on the router) — phases sum to e2e",
            labelnames=("phase",))


class AutoscalerMetrics:
    """The ``paddlenlp_router_autoscaler_*`` catalog — one instance per
    :class:`~.autoscaler.Autoscaler` control loop. Push-mode: the loop stamps
    every decision; the replica gauges track the last observation."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = r = registry or REGISTRY
        self.decisions = r.counter(
            "paddlenlp_router_autoscaler_decisions_total",
            "Autoscaler control-loop decisions by action "
            "(up/down/replace/hold)",
            labelnames=("action",))
        self.replicas = r.gauge(
            "paddlenlp_router_autoscaler_replicas",
            "Live (non-draining) replicas the autoscaler observed on its "
            "last evaluation")
        self.target_envelope = r.gauge(
            "paddlenlp_router_autoscaler_envelope",
            "Configured min/max replica envelope bounds",
            labelnames=("bound",))
        self.provision_failures = r.counter(
            "paddlenlp_router_autoscaler_provision_failures_total",
            "Provision attempts that failed (each retries with backoff on a "
            "later control-loop tick)")
        self.brownout_pushes = r.counter(
            "paddlenlp_router_autoscaler_brownout_pushes_total",
            "Brownout floors pushed to replicas while holding at the max "
            "envelope under sustained overload")


# ----------------------------------------------------------------- federation
# rendering reuses the registry's own exposition formatters (_format_labels /
# _format_value from ..metrics) so the federated plane cannot drift from the
# per-process one on escaping or float rendering


def _sample_key(item):
    """Sort key for one family's samples: sample name, then labelset, then
    ascending numeric ``le`` (+Inf last) so histogram bucket lines come out in
    the cumulative order the exposition format expects."""
    (sample_name, labels), _v = item
    rest = sorted((k, v) for k, v in labels if k != "le")
    le = dict(labels).get("le")
    if le is None:
        le_f = -math.inf
    elif le == "+Inf":
        le_f = math.inf
    else:
        try:
            le_f = float(le)
        except ValueError:
            le_f = math.inf
    return sample_name, rest, le_f


def federate_expositions(expositions: Mapping[str, str]) -> str:
    """Merge per-replica Prometheus expositions into one, each sample
    re-labeled with ``replica="<id>"``.

    ``expositions`` maps replica id -> exposition text (unreachable replicas
    are simply absent — federation is partial by design, never an error).
    Raises ValueError on unparseable text; a caller that must stay partial
    under malformed input (the router) parses per replica itself and feeds
    :func:`federate_families`."""
    return federate_families(
        {rid: parse_prometheus_text(text) for rid, text in expositions.items()})


def federate_families(parsed: Mapping[str, Dict]) -> str:
    """:func:`federate_expositions` over already-parsed families
    (``{replica_id: parse_prometheus_text(...) output}``) — the router's path,
    which parses each scrape once and reuses the families for the SLO fold.
    Histogram ``le`` labels are kept last so bucket lines stay conventional;
    a pre-existing ``replica`` label is overwritten (and flagged by
    :func:`lint_federation`)."""
    names: List[str] = []
    for fams in parsed.values():
        for name in fams:
            if name not in names:
                names.append(name)
    lines: List[str] = []
    for name in sorted(names):
        help_text = type_text = None
        for fams in parsed.values():
            fam = fams.get(name)
            if fam is None:
                continue
            if help_text is None and fam.help:
                help_text = fam.help
            if type_text is None and fam.type:
                type_text = fam.type
        if help_text is not None:
            lines.append(f"# HELP {name} {help_text}")
        if type_text is not None:
            lines.append(f"# TYPE {name} {type_text}")
        for rid in sorted(parsed):
            fam = parsed[rid].get(name)
            if fam is None:
                continue
            for (sample_name, labels), value in sorted(
                    fam.samples.items(), key=_sample_key):
                pairs = [(k, v) for k, v in sorted(labels) if k not in ("replica", "le")]
                pairs.insert(0, ("replica", rid))
                le = dict(labels).get("le")
                if le is not None:
                    pairs.append(("le", le))
                lines.append(f"{sample_name}{_format_labels(pairs)} {_format_value(value)}")
    return "\n".join(lines) + "\n"


def lint_federation(expositions: Mapping[str, str]) -> List[str]:
    """Problems a federated merge would paper over (empty = clean):

    - **duplicate-family conflict**: the same family name exposed with
      different TYPEs across replicas (the merged exposition would attach one
      TYPE to samples of another shape);
    - **label collision**: a replica sample already carrying a ``replica``
      label, which re-labeling overwrites."""
    problems: List[str] = []
    types_seen: Dict[str, Tuple[str, str]] = {}  # family -> (replica, type)
    for rid in sorted(expositions):
        try:
            fams = parse_prometheus_text(expositions[rid])
        except ValueError as e:
            problems.append(f"{rid}: unparseable exposition: {e}")
            continue
        for name, fam in sorted(fams.items()):
            if fam.type:
                prev = types_seen.get(name)
                if prev is not None and prev[1] != fam.type:
                    problems.append(
                        f"{name}: TYPE conflict across replicas "
                        f"({prev[0]}={prev[1]!r} vs {rid}={fam.type!r})")
                else:
                    types_seen.setdefault(name, (rid, fam.type))
            for (_sample, labels) in fam.samples:
                if "replica" in dict(labels):
                    problems.append(
                        f"{name}: {rid} sample already carries a replica label "
                        f"(federation would overwrite it)")
                    break
    return problems
