"""Router metrics catalog: one registration point for every ``paddlenlp_router_*``
series the front tier exports.

Same contract as :class:`~..engine_loop.ServingMetrics` for the replica plane:
names are stable API — the serving README catalog, ``tools/check_metrics.py``
(which instantiates this class so tier-1 lints the exposition) and
``tools/bench_serve.py --replicas N`` all consume them by string.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..metrics import REGISTRY, MetricsRegistry

__all__ = ["RouterMetrics", "ROUTE_DECISION_BUCKETS"]

# seconds; routing decisions are pure host work (snapshot + sort/hash), so the
# interesting range is tens of microseconds to a few milliseconds — the default
# latency buckets would dump every observation into the first bucket
ROUTE_DECISION_BUCKETS: Tuple[float, ...] = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.05, 0.1,
)


class RouterMetrics:
    """Registers the router metric catalog in one registry.

    Push-mode only: the pool's health poller writes ``replica_healthy`` on
    every poll, and the proxy writes the request/failover counters at request
    terminal — there is no engine to bind pull gauges against."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = r = registry or REGISTRY
        self.requests = r.counter(
            "paddlenlp_router_requests_total",
            "Requests terminated by the router, by backing replica and outcome",
            labelnames=("replica", "outcome"))
        self.replica_healthy = r.gauge(
            "paddlenlp_router_replica_healthy",
            "1 when the replica's last health poll was HEALTHY, else 0",
            labelnames=("replica",))
        self.failovers = r.counter(
            "paddlenlp_router_failovers_total",
            "In-flight requests resubmitted to another replica after their "
            "replica failed before emitting a token")
        self.rerouted = r.counter(
            "paddlenlp_router_rerouted_total",
            "Forward attempts re-routed to the next candidate on a replica "
            "429/503 or connect failure (nothing relayed yet)")
        self.route_decision = r.histogram(
            "paddlenlp_router_route_decision_seconds",
            "Latency of one routing decision (pool snapshot + policy ordering)",
            buckets=ROUTE_DECISION_BUCKETS)
        self.health_polls = r.counter(
            "paddlenlp_router_health_polls_total",
            "Health-poller probes by replica and outcome (ok/degraded/error)",
            labelnames=("replica", "outcome"))
