"""Replica registry + background health poller for the router front tier.

Each replica is a ``ServingServer`` (or anything speaking its HTTP surface);
the pool learns replica health the same way an external prober would — by
polling ``GET /health`` (scheduler/engine stats; 503 while the replica's
engine-loop supervisor reports DEGRADED or the scheduler is draining) and
scraping ``GET /metrics`` for ``paddlenlp_serving_kv_utilization`` — so the
router needs no privileged in-process hooks and works unchanged against
out-of-process replicas.

**State machine** (per replica)::

    HEALTHY ──probe 503 (degraded/draining)──▶ DEGRADED
    HEALTHY/DEGRADED ──unreachable × down_after──▶ DOWN
    DOWN ──probe ok──▶ RECOVERING ──ok × recovery_polls──▶ HEALTHY
    RECOVERING ──probe fails──▶ back toward DOWN

A single unreachable probe demotes to DEGRADED (the replica may just be
GC-pausing); ``down_after`` consecutive failures mean DOWN — the policy layer
stops offering the replica entirely. Recovery is probational: a replica coming
back from DOWN serves traffic at RECOVERING priority until ``recovery_polls``
consecutive clean probes promote it, so a flapping replica cannot oscillate
straight back into preferred rotation.

The proxy feeds forwarding observations back through
:meth:`ReplicaPool.note_forward_failure` / :meth:`ReplicaPool.note_degraded`
so a mid-stream incident demotes the replica immediately instead of waiting a
poll interval.

**Live membership.** The replica set is no longer fixed at launch:
:meth:`ReplicaPool.add` registers a replica at runtime, :meth:`start_drain`
flips one to *draining* (the policy layer stops offering it; in-flight
streams finish), and :meth:`remove` takes a drained (or DOWN, or ``force``)
replica out, leaving a tombstone :meth:`drain_status` reports as
``removed``. Drain progress is driven from the poll sweep
(:meth:`_check_drains`): the owning router supplies ``drain_live`` (its own
open-forward count per replica — authoritative for router-fronted traffic)
and ``on_drain_deadline`` (called once when a drain outlives its deadline so
the router can fail the stuck token-less streams over). All three mutation
paths run through the ``router.membership`` fault point *before* touching
state, so an injected failure leaves the set exactly as it was.

**Concurrency model.** Three kinds of thread touch the pool: the poller
(``_run``/``poll_once``/``_check_drains``), HTTP proxy threads
(``snapshots``/``get``/``note_*``), and whoever mutates membership
(``add``/``start_drain``/``remove`` — admin-plane HTTP threads). The replica
list, id map and removal tombstones are guarded by ``_lock`` (``#
guarded-by:`` annotations, enforced by ``tools/analyze``); per-``Replica``
fields are written ONLY under that same pool lock (``_apply`` for health
fields, ``start_drain``/``_check_drains`` for the drain fields — including
``drain_expired_notified``, whose locked check-and-set is what makes the
deadline hook fire exactly once), and read by other threads only through
:meth:`Replica.snapshot`, which ``snapshots()`` calls under the lock. The
exceptions are ``Replica.polls``/``_offset_samples``: normally
poller-confined, but ``poll_once`` may also be driven by admin/launcher
threads — both fields tolerate the rare concurrent sweep (a lost ``polls``
increment only jitters the kv-scrape cadence; deque appends are atomic).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ...observability.tracer import TRACER
from ...utils.faults import FaultPoint
from ...utils.log import logger
from .metrics import RouterMetrics

__all__ = ["HEALTHY", "DEGRADED", "DOWN", "RECOVERING", "DRAINING", "DRAINED",
           "REMOVED", "Replica", "ReplicaSnapshot", "ProbeResult", "ReplicaPool",
           "DrainPendingError", "push_brownout"]

HEALTHY = "healthy"
DEGRADED = "degraded"
DOWN = "down"
RECOVERING = "recovering"
# drain lifecycle strings (drain_status / admin plane; `draining` is a flag
# ORTHOGONAL to the health state — a draining replica still health-polls)
DRAINING = "draining"
DRAINED = "drained"
REMOVED = "removed"

_F_HEALTH_POLL = FaultPoint("router.health_poll")
_F_MEMBERSHIP = FaultPoint("router.membership")


class DrainPendingError(RuntimeError):
    """remove() refused: the replica has not finished draining (HTTP 409)."""

KV_UTILIZATION_METRIC = "paddlenlp_serving_kv_utilization"


def push_brownout(host: str, port: int, level: int,
                  reason: str = "slo_fast_burn",
                  ttl_s: Optional[float] = None,
                  timeout_s: float = 10.0) -> bool:
    """POST a brownout floor to one replica's ``/admin/brownout`` (best
    effort: False on any transport/HTTP failure, never raises). The ONE
    client for this route — the router's SLO fast-burn hook and the
    autoscaler's max-envelope handoff both go through here."""
    payload = {"level": int(level), "reason": reason}
    if ttl_s is not None:
        payload["ttl_s"] = float(ttl_s)
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        try:
            conn.request("POST", "/admin/brownout",
                         body=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
        finally:
            conn.close()
        return resp.status == 200
    except (OSError, http.client.HTTPException, ValueError) as e:
        logger.debug(f"brownout push to {host}:{port} failed: {e!r}")
        return False


@dataclasses.dataclass
class ProbeResult:
    """Outcome of one health probe. ``reachable`` separates a live replica
    shedding load (503 degraded/draining — still owns its queue) from one
    that cannot be talked to at all (connect/timeout — may be gone)."""

    reachable: bool
    status: Optional[str] = None  # the /health "status" field
    inflight: int = 0
    queue_depth: int = 0
    kv_utilization: Optional[float] = None
    retry_after_s: Optional[float] = None
    brownout_level: int = 0  # the replica's overload-brownout ladder level
    # the base-weight version the replica reports on /health (rollout gate +
    # version-skew failover guard); None when the probe could not read one
    weights_version: Optional[str] = None
    error: Optional[str] = None
    # clock-sync piggyback: the replica's tracer-timeline "now" plus the
    # probe's RTT — one offset estimate per probe (NTP-style midpoint)
    clock_offset_s: Optional[float] = None
    rtt_s: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class ReplicaSnapshot:
    """Immutable point-in-time view the routing policy consumes."""

    id: str
    host: str
    port: int
    state: str
    inflight: int
    queue_depth: int
    kv_utilization: float
    retry_after_s: Optional[float]
    consecutive_failures: int
    last_poll_t: Optional[float]
    clock_offset_s: Optional[float] = None  # replica tracer time - router tracer time
    draining: bool = False  # membership: no NEW requests; in-flight finish
    drained: bool = False  # drain complete — safe to remove
    # the replica's overload-brownout level (0 normal .. 3 clamp): >= 2 means
    # the replica asked the fleet to stop racing hedge shadows against it
    brownout_level: int = 0
    # last /health-reported base-weight version (None until first probe):
    # the policy's skew guard and the rollout's rejoin gate both read this
    weights_version: Optional[str] = None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class Replica:
    """Mutable pool-side record for one replica (poller-thread writes, HTTP
    threads read only through :meth:`snapshot` under the pool lock)."""

    def __init__(self, replica_id: str, host: str, port: int):
        self.id = replica_id
        self.host = host
        self.port = port
        # optimistic start: a replica is offered traffic until the first probe
        # says otherwise — the common launch order is "replicas up, then
        # router", and starting DOWN would 503 every request for one interval
        self.state = HEALTHY
        self.inflight = 0
        self.queue_depth = 0
        self.kv_utilization = 0.0
        self.retry_after_s: Optional[float] = None
        self.brownout_level = 0
        self.weights_version: Optional[str] = None
        self.consecutive_failures = 0
        self.recovery_streak = 0
        self.last_poll_t: Optional[float] = None
        self.last_error: Optional[str] = None
        self.polls = 0  # probe count (drives the kv-scrape cadence)
        # drain lifecycle (written under the pool lock; see module docstring)
        self.draining = False
        self.drained = False
        self.drain_deadline_t: Optional[float] = None
        self.drain_expired_notified = False  # poller-thread confined
        # clock skew vs the router, for cross-tier trace stitching: each probe
        # yields (rtt, offset); the lowest-RTT sample in the window wins (the
        # midpoint assumption — request and response legs symmetric — is most
        # credible when the network round trip was fastest)
        self._offset_samples: deque = deque(maxlen=8)
        self.clock_offset_s: Optional[float] = None

    def note_offset(self, rtt_s: float, offset_s: float):
        self._offset_samples.append((rtt_s, offset_s))
        self.clock_offset_s = min(self._offset_samples)[1]

    def snapshot(self) -> ReplicaSnapshot:
        return ReplicaSnapshot(
            id=self.id, host=self.host, port=self.port, state=self.state,
            inflight=self.inflight, queue_depth=self.queue_depth,
            kv_utilization=self.kv_utilization, retry_after_s=self.retry_after_s,
            consecutive_failures=self.consecutive_failures, last_poll_t=self.last_poll_t,
            clock_offset_s=self.clock_offset_s, draining=self.draining,
            drained=self.drained, brownout_level=self.brownout_level,
            weights_version=self.weights_version)


class ReplicaPool:
    """Owns the replica set and the background poller thread."""

    def __init__(self, metrics: Optional[RouterMetrics] = None,
                 poll_interval_s: float = 1.0, probe_timeout_s: float = 2.0,
                 down_after: int = 3, recovery_polls: int = 2,
                 kv_scrape_every: int = 5, tracer=None):
        if down_after < 1:
            raise ValueError("down_after must be >= 1")
        if recovery_polls < 1:
            raise ValueError("recovery_polls must be >= 1")
        if kv_scrape_every < 1:
            raise ValueError("kv_scrape_every must be >= 1")
        self.metrics = metrics
        # clock-offset probes must read the SAME timeline stitching shifts
        # onto: the owning router's tracer (launch_fleet gives it a private
        # one whose epoch anchor differs from the global TRACER's)
        self.tracer = tracer if tracer is not None else TRACER
        self.poll_interval_s = poll_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.down_after = down_after
        self.recovery_polls = recovery_polls
        self.kv_scrape_every = kv_scrape_every
        self._lock = threading.Lock()
        self._replicas: List[Replica] = []  # guarded-by: _lock
        self._by_id: Dict[str, Replica] = {}  # guarded-by: _lock
        self._removed: Dict[str, Dict] = {}  # guarded-by: _lock
        # bounded: an autoscaler cycling replicas on ephemeral ports mints a
        # fresh id per scale-down — without a cap the tombstones (and every
        # GET /replicas response) would grow for the router's whole lifetime
        self._removed_cap = 256
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # membership hooks the owning router wires up: drain_live(replica_id)
        # -> the router's own open-forward count (authoritative for drain
        # completion — probe inflight is the fallback for a poolside-only
        # deployment); on_drain_deadline(replica_id) fires ONCE when a drain
        # outlives its deadline (the router fails stuck streams over)
        self.drain_live: Optional[Callable[[str], int]] = None
        self.on_drain_deadline: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------- membership
    def add(self, host: str, port: int, replica_id: Optional[str] = None) -> Replica:
        rid = replica_id or f"{host}:{port}"
        _F_MEMBERSHIP.fire(op="add", replica=rid)
        with self._lock:
            if rid in self._by_id:
                raise ValueError(f"replica {rid!r} already registered")
            replica = Replica(rid, host, port)
            self._replicas.append(replica)
            self._by_id[rid] = replica
            # re-adding a previously-removed id revives it: drop the tombstone
            self._removed.pop(rid, None)
        if self.metrics is not None:
            self.metrics.replica_healthy.set(1.0, replica=rid)
        self.tracer.instant("membership", cat="router", op="add", replica=rid)
        return replica

    def start_drain(self, replica_id: str, deadline_s: float = 30.0) -> Dict:
        """Flip a replica to draining: the policy layer stops offering it,
        in-flight streams finish, and once the router reports zero live
        forwards the poll sweep marks it ``drained`` (removable). Past
        ``deadline_s`` the ``on_drain_deadline`` hook fires once so the owner
        can fail stuck token-less streams over. Idempotent: re-draining an
        already-draining replica only tightens/extends the deadline."""
        _F_MEMBERSHIP.fire(op="drain", replica=replica_id)
        with self._lock:
            replica = self._by_id.get(replica_id)
            if replica is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            replica.draining = True
            replica.drained = False
            replica.drain_deadline_t = time.time() + max(float(deadline_s), 0.0)
            replica.drain_expired_notified = False
        logger.warning(f"router: replica {replica_id} draining "
                       f"(deadline {deadline_s:.1f}s)")
        self.tracer.instant("membership", cat="router", op="drain",
                            replica=replica_id, deadline_s=deadline_s)
        return self.drain_status(replica_id)

    def cancel_drain(self, replica_id: str) -> Dict:
        """Undo :meth:`start_drain` — the rejoin half of a rolling weight
        rollout (drain, swap, un-drain) for a replica that is NOT leaving the
        fleet. Clears the whole drain lifecycle so the policy layer offers it
        again; idempotent on a replica that was never draining."""
        _F_MEMBERSHIP.fire(op="undrain", replica=replica_id)
        with self._lock:
            replica = self._by_id.get(replica_id)
            if replica is None:
                raise KeyError(f"unknown replica {replica_id!r}")
            replica.draining = False
            replica.drained = False
            replica.drain_deadline_t = None
            replica.drain_expired_notified = False
        logger.warning(f"router: replica {replica_id} drain cancelled (rejoining)")
        self.tracer.instant("membership", cat="router", op="undrain",
                            replica=replica_id)
        return self.drain_status(replica_id)

    def remove(self, replica_id: str, force: bool = False) -> Dict:
        """Take a replica out of the pool. Refused (:class:`DrainPendingError`)
        unless it finished draining, is DOWN, or ``force`` — live streams on a
        force-removed replica keep relaying (the router holds its own upstream
        connections) but lose failover-by-exclusion bookkeeping. Leaves a
        tombstone ``drain_status`` reports as ``removed``; idempotent on an
        already-removed id."""
        _F_MEMBERSHIP.fire(op="remove", replica=replica_id)
        with self._lock:
            replica = self._by_id.get(replica_id)
            if replica is None:
                if replica_id in self._removed:
                    return dict(self._removed[replica_id])
                raise KeyError(f"unknown replica {replica_id!r}")
            if not (force or replica.drained or replica.state == DOWN):
                raise DrainPendingError(
                    f"replica {replica_id!r} is not drained "
                    f"(draining={replica.draining}, state={replica.state}); "
                    "drain it first or pass force")
            self._replicas.remove(replica)
            del self._by_id[replica_id]
            tomb = {"id": replica_id, "state": REMOVED, "removed_t": time.time(),
                    "was_drained": replica.drained, "forced": bool(force)}
            self._removed[replica_id] = tomb
            while len(self._removed) > self._removed_cap:  # oldest-first trim
                self._removed.pop(next(iter(self._removed)))
        if self.metrics is not None:
            # drop, don't zero: a pinned replica_healthy{removed-id}=0 series
            # would alert as "unhealthy replica" forever and leak one series
            # per scale-down under autoscaler churn
            self.metrics.replica_healthy.remove_series(replica=replica_id)
        logger.warning(f"router: replica {replica_id} removed from the pool"
                       + (" (forced)" if force else ""))
        self.tracer.instant("membership", cat="router", op="remove",
                            replica=replica_id, forced=force)
        return dict(tomb)

    def removed(self) -> List[Dict]:
        """Removal tombstones (admin-plane listing)."""
        with self._lock:
            return [dict(t) for t in self._removed.values()]

    def is_draining(self, replica_id: str) -> bool:
        with self._lock:
            replica = self._by_id.get(replica_id)
            return replica is not None and replica.draining

    def drain_status(self, replica_id: str) -> Optional[Dict]:
        """Drain lifecycle view of one replica: ``draining`` → ``drained`` →
        ``removed`` (tombstone), or the plain health state when no drain is in
        progress. None for ids the pool has never seen."""
        with self._lock:
            if replica_id in self._removed:
                return dict(self._removed[replica_id])
            replica = self._by_id.get(replica_id)
            if replica is None:
                return None
            if replica.draining:
                state = DRAINED if replica.drained else DRAINING
            else:
                state = replica.state
            return {
                "id": replica_id, "state": state, "draining": replica.draining,
                "drained": replica.drained,
                "deadline_in_s": None if replica.drain_deadline_t is None
                else replica.drain_deadline_t - time.time(),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._replicas)

    def get(self, replica_id: str) -> Optional[Replica]:
        with self._lock:
            return self._by_id.get(replica_id)

    def snapshots(self) -> List[ReplicaSnapshot]:
        with self._lock:
            return [r.snapshot() for r in self._replicas]

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, name="router-health-poller",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, join_timeout_s: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout_s)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as e:  # the poller must outlive any single probe
                logger.warning(f"router: health-poll sweep failed: {e!r}")
            self._stop.wait(timeout=self.poll_interval_s)

    # ------------------------------------------------------------- polling
    def poll_once(self):
        """One synchronous sweep over every replica (tests call this directly
        for deterministic state-machine coverage)."""
        with self._lock:
            replicas = list(self._replicas)
        for replica in replicas:
            try:
                result = self._probe(replica)
            except Exception as e:
                # connect refused, timeout, injected router.health_poll fault,
                # junk body — all the same to the state machine: unreachable
                result = ProbeResult(reachable=False, error=repr(e))
            self._apply(replica, result)
        self._check_drains()

    def probe_one(self, replica_id: str):
        """Probe a single replica synchronously (the admin plane's
        join-before-serve check) — unlike :meth:`poll_once` this does not
        sweep drains, so an HTTP thread can call it without racing the
        poller's drain bookkeeping."""
        replica = self.get(replica_id)
        if replica is None:
            return
        try:
            result = self._probe(replica)
        except Exception as e:
            result = ProbeResult(reachable=False, error=repr(e))
        self._apply(replica, result)

    def _check_drains(self):
        """Advance every in-progress drain: mark it ``drained`` when the owner
        reports zero live forwards (probe inflight as the fallback), and fire
        the deadline hook once when it has outlived its deadline. Runs on the
        poller thread (or inside a manual ``poll_once``)."""
        with self._lock:
            draining = [r for r in self._replicas if r.draining and not r.drained]
        now = time.time()
        for replica in draining:
            live = None
            if self.drain_live is not None:
                try:
                    live = int(self.drain_live(replica.id))
                except Exception as e:
                    logger.warning(f"router: drain_live({replica.id}) failed: {e!r}")
            if live is None:
                # poolside fallback: the replica's own /health inflight — an
                # unreachable replica reads 0 (its streams are breaking anyway
                # and will fail over through the normal forward path)
                live = replica.inflight if replica.state != DOWN else 0
            if live == 0:
                with self._lock:
                    replica.drained = True
                logger.warning(f"router: replica {replica.id} drained "
                               "(no live streams); safe to remove")
                self.tracer.instant("membership", cat="router", op="drained",
                                    replica=replica.id)
            elif (replica.drain_deadline_t is not None
                  and now >= replica.drain_deadline_t):
                # check-and-set under the pool lock: poll_once may be driven
                # by the poller AND by admin/launcher threads, and the
                # deadline hook must fire exactly once per drain
                with self._lock:
                    if replica.drain_expired_notified or not replica.draining:
                        continue
                    replica.drain_expired_notified = True
                logger.warning(
                    f"router: drain of {replica.id} outlived its deadline with "
                    f"{live} live stream(s); failing stuck streams over")
                self.tracer.instant("membership", cat="router", op="drain_expired",
                                    replica=replica.id, live=live)
                if self.on_drain_deadline is not None:
                    try:
                        self.on_drain_deadline(replica.id)
                    except Exception as e:
                        logger.warning(
                            f"router: drain-deadline hook for {replica.id} failed: {e!r}")

    def _probe(self, replica: Replica) -> ProbeResult:
        """GET /health (+ /metrics kv_utilization) of one replica. Raises on
        transport failure; the caller folds that into ProbeResult."""
        _F_HEALTH_POLL.fire(replica=replica.id)
        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=self.probe_timeout_s)
        t0 = self.tracer.now()
        try:
            conn.request("GET", "/health")
            resp = conn.getresponse()
            retry_after = resp.getheader("Retry-After")
            body = json.loads(resp.read() or b"{}")
        finally:
            conn.close()
        t1 = self.tracer.now()
        sched = body.get("scheduler") or {}
        engine = body.get("engine") or {}
        brownout = body.get("brownout")
        result = ProbeResult(
            reachable=True,
            status=body.get("status"),
            inflight=int(sched.get("inflight", 0)),
            queue_depth=int(engine.get("queue_depth", 0)),
            retry_after_s=float(retry_after) if retry_after else None,
            brownout_level=int(brownout) if isinstance(brownout, (int, float)) else 0,
            weights_version=(str(body["weights_version"])
                             if body.get("weights_version") is not None else None),
        )
        # clock-offset estimate for trace stitching: the replica stamped its
        # tracer-timeline "now" somewhere inside [t0, t1]; assume the midpoint
        remote_now = body.get("now")
        if isinstance(remote_now, (int, float)):
            result.rtt_s = t1 - t0
            result.clock_offset_s = float(remote_now) - (t0 + t1) / 2.0
        # kv_utilization rides on the replica's Prometheus plane (pull gauge
        # sampled at scrape). Scraping + parsing the full exposition per poll
        # would dominate a fast poll interval, so it runs every Nth probe —
        # KV pressure moves on decode timescales, not poll timescales. A
        # failed scrape keeps the last observation rather than failing the
        # whole probe.
        if replica.polls % self.kv_scrape_every == 0:
            try:
                result.kv_utilization = self._scrape_kv_utilization(replica)
            except Exception as e:
                logger.debug(f"router: kv scrape of {replica.id} failed: {e!r}")
        replica.polls += 1
        return result

    def _scrape_kv_utilization(self, replica: Replica) -> Optional[float]:
        conn = http.client.HTTPConnection(replica.host, replica.port,
                                          timeout=self.probe_timeout_s)
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
        finally:
            conn.close()
        if resp.status != 200:
            return None
        from ...observability.prometheus import parse_prometheus_text

        fam = parse_prometheus_text(text).get(KV_UTILIZATION_METRIC)
        if fam is None:
            return None
        v = fam.value()
        return None if v is None or v != v else float(v)  # NaN-safe

    # ------------------------------------------------------------- transitions
    def _apply(self, replica: Replica, result: ProbeResult, probed: bool = True):
        """Fold one observation into the replica's state machine. ``probed``
        distinguishes a real prober visit (stamps ``last_poll_t``, counts in
        ``health_polls_total``) from proxy forward feedback (state transition
        only — phantom probe bookkeeping would lie to operators)."""
        with self._lock:
            prev = replica.state
            if probed:
                replica.last_poll_t = time.time()
            replica.last_error = result.error
            if result.reachable and result.status == "ok":
                replica.consecutive_failures = 0
                replica.retry_after_s = None
                if prev in (DOWN, RECOVERING):
                    replica.recovery_streak += 1
                    replica.state = (HEALTHY if replica.recovery_streak >= self.recovery_polls
                                     else RECOVERING)
                else:
                    replica.state = HEALTHY
                outcome = "ok"
            elif result.reachable:
                # alive but shedding (degraded/draining): not a reachability
                # failure — it still owns its in-flight work
                replica.consecutive_failures = 0
                replica.recovery_streak = 0
                replica.state = DEGRADED
                replica.retry_after_s = result.retry_after_s
                outcome = "degraded"
            else:
                replica.consecutive_failures += 1
                replica.recovery_streak = 0
                replica.state = (DOWN if replica.consecutive_failures >= self.down_after
                                 else DEGRADED)
                # an unreachable replica's last Retry-After hint is stale — a
                # dead replica must not inflate retry_after_hint() forever
                replica.retry_after_s = None
                outcome = "error"
            if result.reachable:
                replica.inflight = result.inflight
                replica.queue_depth = result.queue_depth
                replica.brownout_level = result.brownout_level
                # proxy-feedback observations carry no version; keep the last
                # probed one rather than forgetting it
                if result.weights_version is not None:
                    replica.weights_version = result.weights_version
                if result.kv_utilization is not None:
                    replica.kv_utilization = result.kv_utilization
                if result.clock_offset_s is not None and result.rtt_s is not None:
                    replica.note_offset(result.rtt_s, result.clock_offset_s)
            new = replica.state
        if self.metrics is not None:
            self.metrics.replica_healthy.set(1.0 if new == HEALTHY else 0.0,
                                             replica=replica.id)
            if probed:
                self.metrics.health_polls.inc(replica=replica.id, outcome=outcome)
        if new != prev:
            logger.warning(f"router: replica {replica.id} {prev} -> {new}"
                           + (f" ({result.error})" if result.error else ""))
            self.tracer.instant("replica_state", cat="router", replica=replica.id,
                                prev=prev, state=new, error=result.error)

    # ------------------------------------------------------------- proxy feedback
    def note_forward_failure(self, replica_id: str):
        """A forward attempt hit a transport failure or a replica-side request
        failure — demote now instead of waiting for the next poll."""
        replica = self.get(replica_id)
        if replica is not None:
            self._apply(replica, ProbeResult(reachable=False, error="forward failure"),
                        probed=False)

    def note_degraded(self, replica_id: str, retry_after_s: Optional[float] = None):
        """A forward attempt got the replica's 503 circuit breaker."""
        replica = self.get(replica_id)
        if replica is not None:
            self._apply(replica, ProbeResult(reachable=True, status="degraded",
                                             inflight=replica.inflight,
                                             queue_depth=replica.queue_depth,
                                             retry_after_s=retry_after_s,
                                             brownout_level=replica.brownout_level),
                        probed=False)

    def clock_offset(self, replica_id: str) -> float:
        """Best current clock-offset estimate (replica tracer time minus router
        tracer time) for a replica; 0.0 before any estimate exists."""
        replica = self.get(replica_id)
        if replica is None or replica.clock_offset_s is None:
            return 0.0
        return replica.clock_offset_s

    def retry_after_hint(self) -> float:
        """Largest replica-reported Retry-After (>=1s floor) — what the router
        tells clients when every candidate is unavailable."""
        hints = [s.retry_after_s for s in self.snapshots() if s.retry_after_s]
        return max([1.0] + [float(h) for h in hints])
