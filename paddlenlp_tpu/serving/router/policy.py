"""Routing policies: least-loaded shedding and consistent-hash prefix affinity.

A policy turns the pool's replica snapshots into an **ordered candidate
list** — the proxy walks it front-to-back, so position 0 is the routing
decision and the tail is the failover order. Policies are pure functions of
their inputs (no hidden state beyond the memoized hash ring), which is what
makes the prefix-affinity determinism testable: the same prompt prefix over
the same replica set always yields the same candidate order.

**Effective load score.** ``inflight + queue_depth + kv_utilization``: the
replica's admission-window occupancy, its engine-side waiting queue, and the
KV-block pressure (0..1 — a tiebreaker between replicas with equal request
counts, and the early-warning signal before preemption thrash).

**Prefix affinity.** Requests sharing a prompt prefix hash to the same point
on a consistent-hash ring, so a shared-prefix burst (few-shot template, long
system prompt) lands on one replica where the planned prefix cache can serve
it warm. The ring walk also defines the failover order: when the pinned
replica is DOWN/excluded, every client of that prefix agrees on the *same*
next replica — the prefix stays co-located even through an incident. Ring
membership changes move only ~1/N of prefixes (the point of consistent
hashing over modulo placement).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import FrozenSet, Iterable, List, Optional, Sequence, Tuple, Union

from .pool import DEGRADED, DOWN, HEALTHY, RECOVERING, ReplicaSnapshot

__all__ = ["load_score", "LeastLoadedPolicy", "PrefixAffinityPolicy", "HashRing",
           "resolve_policy"]

Prompt = Union[str, Sequence[int], None]

#: candidate preference by state: HEALTHY first, probational RECOVERING next,
#: DEGRADED only when nothing better exists (its 503 breaker will bounce the
#: attempt anyway, but a breaker can lift between poll and forward). DOWN is
#: never offered.
_STATE_RANK = {HEALTHY: 0, RECOVERING: 1, DEGRADED: 2}


def load_score(snap: ReplicaSnapshot) -> float:
    """Effective load: admission inflight + engine queue depth + KV utilization."""
    return snap.inflight + snap.queue_depth + snap.kv_utilization


def _eligible(snapshots: Iterable[ReplicaSnapshot],
              exclude: FrozenSet[str]) -> List[ReplicaSnapshot]:
    # DOWN is unreachable; a draining replica is healthy but leaving — it
    # finishes its in-flight streams and must never be offered NEW requests
    return [s for s in snapshots
            if s.state != DOWN and not s.draining and s.id not in exclude]


class LeastLoadedPolicy:
    """Order candidates by (state preference, effective load score, id).

    The id tiebreaker keeps the order total and deterministic so tests and
    failover behave identically across runs."""

    name = "least_loaded"

    def select(self, snapshots: Sequence[ReplicaSnapshot], prompt: Prompt = None,
               exclude: FrozenSet[str] = frozenset(),
               adapter_id: Optional[str] = None,
               conversation: Optional[str] = None) -> List[ReplicaSnapshot]:
        return sorted(_eligible(snapshots, exclude),
                      key=lambda s: (_STATE_RANK.get(s.state, 3), load_score(s), s.id))


class HashRing:
    """Consistent-hash ring over replica ids with virtual nodes.

    ``vnodes`` points per replica smooth the arc lengths so one replica cannot
    own a disproportionate share of the prefix space; md5 is used for its
    distribution quality, not security."""

    def __init__(self, ids: Sequence[str], vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.ids = tuple(ids)
        points: List[Tuple[int, str]] = []
        for rid in self.ids:
            for v in range(vnodes):
                points.append((self._hash(f"{rid}#{v}"), rid))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")

    def ordered(self, key: str) -> List[str]:
        """Distinct replica ids in ring order starting at ``key``'s successor:
        position 0 is the pinned replica, the rest is the agreed failover walk."""
        if not self._points:
            return []
        start = bisect.bisect_left(self._keys, self._hash(key)) % len(self._points)
        seen, out = set(), []
        for i in range(len(self._points)):
            rid = self._points[(start + i) % len(self._points)][1]
            if rid not in seen:
                seen.add(rid)
                out.append(rid)
            if len(seen) == len(self.ids):
                break
        return out


class PrefixAffinityPolicy:
    """Pin requests sharing a prompt prefix to one replica via the hash ring.

    ``prefix_tokens`` bounds the affinity key: the first N token ids (or, for
    raw string prompts, the first ``4 * N`` characters — roughly the same text
    span) so that requests differing only in their tail still co-locate. The
    ring is rebuilt only when the replica id set changes.

    **Weighted spill.** A popular prefix can turn its pinned replica into a
    hot spot — and an autoscaler that just grew the fleet would watch the new
    replicas idle while the pin melts. ``spill_load_score`` bounds how hot a
    pin may run: when the pinned replica's :func:`load_score` exceeds it, the
    request spills to the next ring candidate whose score is still under the
    threshold (the *agreed* failover order, so every client of the prefix
    spills to the SAME replica — the prefix stays co-located on two replicas
    instead of scattering). When every candidate is equally hot the pin
    stands: bouncing between uniformly-loaded replicas would only shed the
    cache benefit. ``None`` disables spilling.

    **Adapter affinity.** A request carrying an ``adapter_id`` hashes on
    ``a:<adapter_id>`` instead of its prompt prefix: every request for one
    LoRA adapter lands on the same replica, whose registry pool then serves
    the adapter warm (one hot-load instead of N, and the replica's prefix
    cache — keyed ``(adapter_id, tokens)`` — stays coherent per adapter).
    The same weighted spill bounds a hot adapter pin, and the ring walk is
    the agreed failover/spill order, so a melting pin co-locates the adapter
    on exactly one more replica.

    **Conversation affinity.** A ``/v1/chat/completions`` request carrying a
    ``conversation`` key hashes on ``c:<conversation>`` — the strongest
    affinity signal, outranking adapter and prompt-prefix keys. Every turn of
    a conversation lands on the replica whose hierarchical prefix cache holds
    the previous turns' prompt AND completion KV (device or host tier), so
    turn N+1 re-prefills only its new user message even across HBM cache
    pressure. The ring walk and weighted spill apply unchanged."""

    name = "prefix_affinity"

    def __init__(self, prefix_tokens: int = 16, vnodes: int = 64,
                 spill_load_score: Optional[float] = 8.0):
        if prefix_tokens < 1:
            raise ValueError("prefix_tokens must be >= 1")
        if spill_load_score is not None and spill_load_score <= 0:
            raise ValueError("spill_load_score must be > 0 (None disables)")
        self.prefix_tokens = prefix_tokens
        self.vnodes = vnodes
        self.spill_load_score = spill_load_score
        self._ring: Optional[HashRing] = None
        self._ring_ids: Optional[Tuple[str, ...]] = None
        self._fallback = LeastLoadedPolicy()

    def prefix_key(self, prompt: Prompt, adapter_id: Optional[str] = None,
                   conversation: Optional[str] = None) -> Optional[str]:
        if conversation:
            return "c:" + conversation
        if adapter_id:
            return "a:" + adapter_id
        if prompt is None:
            return None
        if isinstance(prompt, str):
            return "s:" + prompt[: 4 * self.prefix_tokens]
        try:
            return "t:" + ",".join(str(int(t)) for t in list(prompt)[: self.prefix_tokens])
        except (TypeError, ValueError):
            return None

    def _ring_for(self, snapshots: Sequence[ReplicaSnapshot]) -> HashRing:
        ids = tuple(sorted(s.id for s in snapshots))
        if self._ring is None or self._ring_ids != ids:
            self._ring = HashRing(ids, vnodes=self.vnodes)
            self._ring_ids = ids
        return self._ring

    def select(self, snapshots: Sequence[ReplicaSnapshot], prompt: Prompt = None,
               exclude: FrozenSet[str] = frozenset(),
               adapter_id: Optional[str] = None,
               conversation: Optional[str] = None) -> List[ReplicaSnapshot]:
        key = self.prefix_key(prompt, adapter_id, conversation)
        if key is None:
            return self._fallback.select(snapshots, prompt, exclude)
        # ring membership is computed over ALL replicas (not just eligible
        # ones): a replica's arc must not migrate while it is merely DOWN, or
        # its prefixes would re-pin twice — once leaving, once coming back
        ring_order = {rid: i for i, rid in enumerate(self._ring_for(snapshots).ordered(key))}
        eligible = _eligible(snapshots, exclude)
        # the ring walk is the affinity chain; state rank still outranks it so
        # a DEGRADED pinned replica yields to the next healthy ring member
        ordered = sorted(eligible,
                         key=lambda s: (_STATE_RANK.get(s.state, 3),
                                        ring_order.get(s.id, len(ring_order)), s.id))
        # weighted spill: a too-hot pin yields to the FIRST ring successor
        # still under the threshold (same state rank — a spill must not trade
        # cache warmth for a degraded replica); the successor moves to the
        # front and the rest of the walk keeps its order, so the failover
        # chain stays agreed across clients
        spill = self.spill_load_score
        if spill is not None and len(ordered) > 1 and load_score(ordered[0]) > spill:
            pinned_rank = _STATE_RANK.get(ordered[0].state, 3)
            for i in range(1, len(ordered)):
                cand = ordered[i]
                if _STATE_RANK.get(cand.state, 3) != pinned_rank:
                    break  # never spill onto a worse-state replica
                if load_score(cand) <= spill:
                    ordered.insert(0, ordered.pop(i))
                    break
        return ordered


def resolve_policy(policy) -> object:
    """``"least_loaded"`` / ``"prefix_affinity"`` / a policy instance → instance."""
    if policy is None:
        return LeastLoadedPolicy()
    if isinstance(policy, str):
        if policy == "least_loaded":
            return LeastLoadedPolicy()
        if policy == "prefix_affinity":
            return PrefixAffinityPolicy()
        raise ValueError(f"unknown routing policy {policy!r}; "
                         "use 'least_loaded' or 'prefix_affinity'")
    if not hasattr(policy, "select"):
        raise TypeError(f"policy {policy!r} has no select()")
    return policy
