"""Admission control + backpressure in front of the engine loop.

The engine's own waiting queue is unbounded (a batch ``generate()`` call wants
that); a server does not — heavy traffic must shed load *before* prompts pile
up in host memory. The scheduler enforces:

- a bounded in-flight window (``max_inflight`` = running + waiting): past it,
  submissions raise :class:`SaturatedError` (HTTP 429, retryable);
- per-request deadlines (``default_timeout_s`` unless the caller overrides) so
  one stuck client cannot hold a slot forever;
- graceful drain: ``drain()`` flips to rejecting new work with
  :class:`ShuttingDownError` (HTTP 503) while in-flight requests finish;
- circuit breaker: while the engine loop is DEGRADED (supervisor rebuilding
  the engine after a step failure), submissions raise :class:`DegradedError`
  (HTTP 503 with ``Retry-After``) instead of queueing behind a dead engine.

**Concurrency model.** ``submit()`` is called from many HTTP worker threads
at once, and ``_release`` fires on whichever thread resolves the handle (the
engine loop, usually). The admission window (``_inflight``) and the drain
flag (``_draining``) are therefore guarded by ``_lock`` — annotated with
``# guarded-by:`` and enforced by ``tools/analyze`` (lock-discipline
checker). The ``rejected_*`` counters are single-writer-ish int bumps read
only by ``stats()``; a momentarily stale read is acceptable and they stay
unguarded on purpose. ``_idle`` is a ``threading.Event`` (self-synchronized).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ..observability.flight_recorder import RECORDER
from ..observability.tracer import TRACER
from ..utils.faults import FaultPoint
from ..utils.log import logger
from .brownout import PRIORITIES, BrownoutController, BrownoutPolicy
from .engine_loop import EngineLoop, RequestHandle
from .tenancy.quotas import DEFAULT_TENANT, TenantQuotas

__all__ = ["Scheduler", "SchedulerConfig", "SaturatedError", "ShuttingDownError",
           "DegradedError", "ShedError", "DeadlineUnmetError", "TenantQuotaError"]

_F_SUBMIT = FaultPoint("serving.submit")
_F_SHED = FaultPoint("sched.shed")


class SaturatedError(Exception):
    """In-flight window full — shed load (HTTP 429 + ``Retry-After`` from the
    live queue-wait estimate)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class TenantQuotaError(SaturatedError):
    """One tenant's ``max_inflight`` admission quota is full — shed only that
    tenant's traffic (HTTP 429 + ``Retry-After``) while the shared window
    stays open to everyone else. Subclasses :class:`SaturatedError` so every
    429 path handles it without knowing about tenancy."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 tenant: str = DEFAULT_TENANT):
        super().__init__(message, retry_after_s=retry_after_s)
        self.tenant = tenant


class ShuttingDownError(Exception):
    """Scheduler draining/stopped — not accepting work (HTTP 503)."""


class ShedError(Exception):
    """Brownout priority shed: the replica is overloaded and this request's
    priority class is below the current ladder level (HTTP 503 +
    ``Retry-After``)."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 priority: str = "best_effort"):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.priority = priority


class DeadlineUnmetError(Exception):
    """Deadline-aware admission rejected on arrival: the live queue-wait
    estimate already exceeds the request's ``deadline_ms`` budget, so
    admitting it would only burn a slot on a guaranteed timeout (HTTP 503 +
    ``Retry-After``)."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 estimate_s: float = 0.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.estimate_s = estimate_s


class DegradedError(Exception):
    """Engine loop is DEGRADED (rebuilding) — retry later (HTTP 503 +
    ``Retry-After: retry_after_s``)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class SchedulerConfig:
    def __init__(self, max_inflight: int = 64, default_timeout_s: Optional[float] = 120.0,
                 max_prompt_tokens: Optional[int] = None):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = max_inflight
        self.default_timeout_s = default_timeout_s
        self.max_prompt_tokens = max_prompt_tokens


class Scheduler:
    """Bounded admission window around an :class:`EngineLoop`."""

    def __init__(self, loop: EngineLoop, config: Optional[SchedulerConfig] = None,
                 brownout: Optional[BrownoutController] = None,
                 brownout_policy: Optional[BrownoutPolicy] = None,
                 tenant_quotas: Optional[TenantQuotas] = None):
        self.loop = loop
        self.config = config or SchedulerConfig()
        self.tenant_quotas = tenant_quotas
        self._lock = threading.Lock()
        self._inflight = 0  # guarded-by: _lock
        self._tenant_inflight: dict = {}  # guarded-by: _lock
        self._draining = False  # guarded-by: _lock
        self._idle = threading.Event()
        self._idle.set()
        self.rejected_saturated = 0
        self.rejected_draining = 0
        self.rejected_degraded = 0
        self.rejected_shed = 0
        self.rejected_deadline = 0
        self.rejected_tenant_quota = 0
        # overload-brownout ladder: evaluated on every submission against the
        # local saturation signal (window occupancy vs the live queue-wait
        # estimate); the router/autoscaler can push a level floor on top
        self.brownout = brownout if brownout is not None else BrownoutController(
            policy=brownout_policy, pressure_fn=self._pressure)
        if self.brownout.pressure_fn is None:
            self.brownout.pressure_fn = self._pressure

    def _reject_if_unavailable(self, trace):  # holds-lock: _lock
        """Caller holds ``_lock``. Raise when this scheduler cannot accept
        work at all — draining/stopped (``ShuttingDownError``) or engine
        DEGRADED (``DegradedError`` with a recovery hint: shed load NOW
        instead of piling work on a dead engine)."""
        if self._draining or not self.loop.running:
            self.rejected_draining += 1
            RECORDER.record("sched.reject", trace=trace, reason="draining")
            TRACER.instant("admission_rejected", cat="scheduler", reason="draining")
            raise ShuttingDownError("server is draining; retry against another replica")
        if self.loop.degraded:
            self.rejected_degraded += 1
            retry_after = self.loop.retry_after_hint()
            RECORDER.record("sched.reject", trace=trace, reason="degraded",
                            retry_after_s=retry_after)
            TRACER.instant("admission_rejected", cat="scheduler", reason="degraded",
                           retry_after_s=retry_after)
            raise DegradedError(
                "engine is recovering from a failure; retry shortly",
                retry_after_s=retry_after)

    def _pressure(self) -> float:
        """Local saturation signal for the brownout ladder: the worse of
        admission-window occupancy and the queue-wait estimate relative to the
        policy's saturation threshold (>= 1.0 means overloaded)."""
        occupancy = self.inflight / max(self.config.max_inflight, 1)
        wait = self.loop.queue_wait_estimate()
        return max(occupancy,
                   wait / max(self.brownout.policy.saturation_wait_s, 1e-9))

    # ------------------------------------------------------------- admission
    def submit(self, prompt_ids, sampling=None, timeout_s: Optional[float] = None,
               max_retries: Optional[int] = None,
               trace: Optional[str] = None,
               priority: str = "interactive",
               deadline_s: Optional[float] = None,
               tenant: str = DEFAULT_TENANT,
               adapter_id: Optional[str] = None) -> RequestHandle:
        """Admit one request or raise (SaturatedError / ShuttingDownError /
        DegradedError / ShedError / DeadlineUnmetError / TenantQuotaError).
        ``max_retries`` is the per-request engine-rebuild requeue budget
        (None = supervisor policy default); ``trace`` adopts an inbound
        cross-tier trace id (None = the loop mints ``req-N``). ``priority``
        selects the brownout shed class and the engine's admission order;
        ``deadline_s`` is the request's total latency budget — rejected on
        arrival when the live queue-wait estimate already exceeds it, and
        enforced as the engine deadline otherwise. ``tenant`` keys the
        per-tenant ``max_inflight`` quota (a full quota sheds only that
        tenant) and the tenant label on every shed/finish metric;
        ``adapter_id`` selects the LoRA adapter the engine decodes with."""
        cfg = self.config
        if priority not in PRIORITIES:
            raise ValueError(f"priority must be one of {PRIORITIES}, got {priority!r}")
        if cfg.max_prompt_tokens is not None and len(prompt_ids) > cfg.max_prompt_tokens:
            raise ValueError(
                f"prompt of {len(prompt_ids)} tokens exceeds max_prompt_tokens={cfg.max_prompt_tokens}")
        # availability checks come FIRST: a draining/degraded replica must
        # report draining/degraded (the signal the router's failure
        # classification keys on), not a brownout shed — and drain-induced
        # occupancy must never walk the brownout ladder
        with self._lock:
            self._reject_if_unavailable(trace)
        # overload controls run before the admission window: they shed work
        # the window would only queue toward a guaranteed-bad outcome
        level = self.brownout.evaluate()
        if self.brownout.should_shed(priority):
            self.rejected_shed += 1
            _F_SHED.fire(priority=priority)
            self.loop.metrics.shed.inc(reason="shed", priority=priority,
                                       tenant=tenant)
            retry_after = self.loop.queue_wait_estimate()
            RECORDER.record("sched.reject", trace=trace, reason="shed",
                            level=level)
            TRACER.instant("admission_rejected", cat="scheduler", reason="shed",
                           level=level)
            raise ShedError(
                f"replica browned out (level {level}); {priority} traffic is "
                "being shed — retry later or elsewhere",
                retry_after_s=retry_after, priority=priority)
        if deadline_s is not None:
            estimate = self.loop.queue_wait_estimate()
            if estimate > deadline_s:
                self.rejected_deadline += 1
                self.loop.metrics.shed.inc(reason="deadline", priority=priority,
                                           tenant=tenant)
                RECORDER.record("sched.reject", trace=trace, reason="deadline",
                                estimate_s=round(estimate, 4))
                TRACER.instant("admission_rejected", cat="scheduler",
                               reason="deadline", estimate_s=estimate)
                raise DeadlineUnmetError(
                    f"queue-wait estimate {estimate:.3f}s already exceeds the "
                    f"{deadline_s:.3f}s deadline; rejecting on arrival",
                    retry_after_s=estimate, estimate_s=estimate)
        cap = self.brownout.max_tokens_cap()
        if cap is not None and sampling is not None \
                and getattr(sampling, "max_new_tokens", 0) > cap:
            # level-3 clamp: shorter completions for everyone beats timeouts
            # for everyone — documented in the brownout ladder
            sampling = dataclasses.replace(sampling, max_new_tokens=cap)
        with self._lock:
            # re-checked: a drain/degrade may have started while the overload
            # controls ran outside the lock
            self._reject_if_unavailable(trace)
            if self._inflight >= cfg.max_inflight:
                self.rejected_saturated += 1
                # Retry-After tracks the live backlog, not a constant: a
                # deep queue quotes a longer backoff than a momentary blip
                retry_after = self.loop.queue_wait_estimate()
                RECORDER.record("sched.reject", trace=trace, reason="saturated",
                                inflight=self._inflight)
                TRACER.instant("admission_rejected", cat="scheduler", reason="saturated",
                               inflight=self._inflight)
                raise SaturatedError(
                    f"in-flight window full ({self._inflight}/{cfg.max_inflight}); retry later",
                    retry_after_s=retry_after)
            tcap = None if self.tenant_quotas is None \
                else self.tenant_quotas.max_inflight(tenant)
            if tcap is not None and self._tenant_inflight.get(tenant, 0) >= tcap:
                # per-tenant isolation: one tenant at its quota sheds only its
                # OWN traffic — the shared window stays open to everyone else
                self.rejected_tenant_quota += 1
                self.loop.metrics.shed.inc(reason="tenant_quota",
                                           priority=priority, tenant=tenant)
                retry_after = self.loop.queue_wait_estimate()
                RECORDER.record("sched.reject", trace=trace, reason="tenant_quota",
                                tenant=tenant, inflight=self._tenant_inflight.get(tenant, 0))
                TRACER.instant("admission_rejected", cat="scheduler",
                               reason="tenant_quota", tenant=tenant)
                raise TenantQuotaError(
                    f"tenant {tenant!r} at its max_inflight quota "
                    f"({self._tenant_inflight.get(tenant, 0)}/{tcap}); retry later",
                    retry_after_s=retry_after, tenant=tenant)
            self._inflight += 1
            self._tenant_inflight[tenant] = self._tenant_inflight.get(tenant, 0) + 1
            self._idle.clear()
        deadline = timeout_s if timeout_s is not None else cfg.default_timeout_s
        if deadline_s is not None:
            # the deadline is a TOTAL latency budget: it also bounds the
            # engine-side abort deadline so an admitted-then-stuck request
            # frees its slot at the deadline, not at the generic timeout
            deadline = deadline_s if deadline is None else min(deadline, deadline_s)
        try:
            _F_SUBMIT.fire(prompt_len=len(prompt_ids))
            # recorded retrospectively so Span.trace carries the request's id
            # (assigned by submit) and trace-filtered timelines include admission
            t0 = time.perf_counter()
            handle = self.loop.submit(prompt_ids, sampling, deadline_s=deadline,
                                      max_retries=max_retries, trace=trace,
                                      priority=priority, tenant=tenant,
                                      adapter_id=adapter_id)
            TRACER.add_span("admission", TRACER.epoch_time(t0),
                            time.perf_counter() - t0, cat="scheduler",
                            trace=handle.trace, prompt_len=len(prompt_ids))
        except BaseException:
            self._release(tenant)
            raise
        # release the window slot the moment the request resolves (any reason)
        handle.add_done_callback(lambda _h: self._release(tenant))
        return handle

    def cancel(self, handle: RequestHandle):
        self.loop.cancel(handle)

    def _release(self, tenant: str = DEFAULT_TENANT):
        with self._lock:
            self._inflight -= 1
            n = self._tenant_inflight.get(tenant, 0) - 1
            if n > 0:
                self._tenant_inflight[tenant] = n
            else:
                self._tenant_inflight.pop(tenant, None)
            if self._inflight <= 0:
                self._idle.set()

    # ------------------------------------------------------------- stats/drain
    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def tenant_inflight(self) -> dict:
        """Snapshot of in-flight counts by tenant (quota bookkeeping view)."""
        with self._lock:
            return dict(self._tenant_inflight)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def stats(self) -> dict:
        return {
            "inflight": self.inflight,
            "max_inflight": self.config.max_inflight,
            "draining": self.draining,
            "engine_state": self.loop.state,
            # slot-level partial recoveries (poisoned requests quarantined
            # without a full rebuild) — surfaced on /health so operators can
            # see a replica absorbing poison before it escalates
            "slot_quarantines": getattr(self.loop, "slot_quarantines", 0),
            "rejected_saturated": self.rejected_saturated,
            "rejected_draining": self.rejected_draining,
            "rejected_degraded": self.rejected_degraded,
            "rejected_shed": self.rejected_shed,
            "rejected_deadline": self.rejected_deadline,
            "rejected_tenant_quota": self.rejected_tenant_quota,
            # per-tenant occupancy of the shared window (tenants currently at
            # zero drop out) + the configured quotas, for /health visibility
            "tenants": {
                "inflight": self.tenant_inflight(),
                "quotas": self.tenant_quotas.describe()
                if self.tenant_quotas is not None else None,
            },
            # the overload ladder, surfaced on /health so the router's pool
            # snapshots (and operators) see a replica shedding before it 503s
            "brownout": self.brownout.stats(),
            "queue_wait_estimate_s": round(self.loop.queue_wait_estimate(), 4),
        }

    def start_drain(self):
        """Flip to rejecting new work WITHOUT waiting for in-flight requests
        — replica-side drain propagation: the router (or an operator) tells
        this server it is leaving the fleet, new direct traffic 503s
        immediately while accepted streams keep finishing."""
        with self._lock:
            self._draining = True
        TRACER.instant("membership", cat="scheduler", op="drain_direct")

    def stop_drain(self):
        """Undo :meth:`start_drain`: resume admitting new work. The rejoin
        half of a rolling weight rollout — the router drains a replica, swaps
        its weights, then un-drains it so it takes traffic again without a
        process restart."""
        with self._lock:
            self._draining = False
        TRACER.instant("membership", cat="scheduler", op="undrain_direct")

    def drain(self, timeout_s: Optional[float] = 30.0) -> bool:
        """Stop admitting; wait for in-flight work. Returns True if empty."""
        self.start_drain()
        ok = self._idle.wait(timeout=timeout_s)
        if not ok:
            logger.warning(f"scheduler drain timed out with {self.inflight} in flight")
        return ok

    def shutdown(self, timeout_s: Optional[float] = 30.0):
        """Drain then stop the engine loop (leftovers abort)."""
        self.drain(timeout_s)
        self.loop.stop(drain=False)
