"""Shared HTTP handler plumbing for the serving planes.

``serving/api.py`` (single replica) and ``serving/router/proxy.py`` (front
tier) speak the same JSON-over-HTTP dialect: raw/JSON/error senders with
explicit Content-Length, and a body reader enforcing a size cap + JSON-object
validation. One base class keeps the 413/400 semantics from drifting between
the planes.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler
from typing import Optional

from ..utils.log import logger

__all__ = ["JsonRequestHandler"]


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Base handler: JSON senders + capped body reader.

    Subclasses set ``log_prefix`` and ``max_body_bytes`` (class attributes, so
    the closure-defined handlers in api.py/proxy.py can override per server).
    """

    protocol_version = "HTTP/1.1"
    log_prefix = "http"
    max_body_bytes = 8 << 20

    def log_message(self, fmt, *args):
        logger.debug(f"{self.log_prefix}: " + fmt % args)

    # ------------------------------------------------------------- senders
    def _send_raw(self, code: int, body: bytes, ctype: str,
                  headers: Optional[dict] = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload: dict, headers: Optional[dict] = None):
        self._send_raw(code, json.dumps(payload).encode(), "application/json",
                       headers=headers)

    def _send_error_json(self, code: int, message: str, etype: str,
                         headers: Optional[dict] = None):
        self._send_json(code, {"error": {"message": message, "type": etype,
                                         "code": code}}, headers=headers)

    # ------------------------------------------------------------- body
    def _read_body(self) -> Optional[dict]:
        """Parse the request body as a JSON object, or send the error and
        return None. Oversized bodies are rejected before reading."""
        n = int(self.headers.get("Content-Length", 0))
        if n < 0:
            # rfile.read(-1) would block until the client closes, pinning the
            # handler thread — a trivially exploitable slow-loris
            self.close_connection = True
            self._send_error_json(400, f"invalid Content-Length {n}", "invalid_request")
            return None
        if n > self.max_body_bytes:
            # rejected before reading: the unread body makes this connection
            # unusable for keep-alive
            self.close_connection = True
            self._send_error_json(
                413, f"body of {n} bytes exceeds limit {self.max_body_bytes}",
                "payload_too_large")
            return None
        raw = self.rfile.read(n) if n else b"{}"
        try:
            payload = json.loads(raw or b"{}")
        except ValueError as e:
            self._send_error_json(400, f"invalid JSON body: {e}", "invalid_request")
            return None
        if not isinstance(payload, dict):
            self._send_error_json(400, "body must be a JSON object", "invalid_request")
            return None
        return payload
