"""Chat-completions surface: conversation → token-id rendering.

The ``/v1/chat/completions`` endpoint (api.py) is a thin shape adapter over
the existing completion pipeline — what makes multi-turn chat *cheap* is the
hierarchical prefix cache underneath it, and that only works if rendering is
**prefix-stable**: turn N+1's rendered prompt must begin with turn N's
rendered prompt followed byte-for-byte by turn N's completion ids. The
:class:`ChatTemplate` here guarantees that by construction:

- every message renders as ``[role_marker] + content_ids + [sep]``;
- the render ends with a bare ``[assistant_marker]`` (the generation prompt);
- the model's completion then streams exactly where the next turn's history
  will replay it: turn N's ``... [assistant] <completion> [sep] ...`` starts
  with turn N's prompt (``... [assistant]``) + its sampled ids.

Because the engine registers a finished request's prompt AND generated
blocks in the prefix cache (and the host tier keeps them across HBM
pressure), turn N+1 re-prefills only its new user message — the
``cached_tokens`` usage field covers turn N's prompt and completion.

Assistant-message ``content`` SHOULD be the token ids the server streamed
(the ``token_ids`` field of the previous response): re-encoding decoded text
is not guaranteed to reproduce the sampled ids, which silently downgrades
the cache hit to the longest re-tokenized match. Both list-of-ints and
string content are accepted; strings go through the server's tokenizer.

The default marker ids are small reserved ids (1..4) — tokenizer-less
deployments (token-id payloads, the test/bench configuration) must keep
real content clear of them, and tokenizer deployments should construct the
template from their special-token ids instead.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Sequence

__all__ = ["ChatTemplate", "ROLES"]

#: accepted ``role`` values, in the only order a well-formed conversation
#: can interleave them (system first if present, then user/assistant turns)
ROLES = ("system", "user", "assistant")


@dataclasses.dataclass(frozen=True)
class ChatTemplate:
    """Prefix-stable chat rendering (see module docstring for the invariant
    the prefix cache depends on). Marker defaults are reserved low ids so the
    tokenizer-less test configuration (vocab 96) can use them directly."""

    system_token_id: int = 1
    user_token_id: int = 2
    assistant_token_id: int = 3
    sep_token_id: int = 4

    def role_token(self, role: str) -> int:
        if role == "system":
            return self.system_token_id
        if role == "user":
            return self.user_token_id
        if role == "assistant":
            return self.assistant_token_id
        raise ValueError(f"message role must be one of {'/'.join(ROLES)}, got {role!r}")

    def render(self, messages: Sequence[dict],
               encode: Callable[[str], List[int]]) -> List[int]:
        """Render a conversation to prompt token ids ending in the assistant
        generation marker. ``encode`` maps string content to ids (the
        server's tokenizer path); list content passes through as ids."""
        if not isinstance(messages, (list, tuple)) or not messages:
            raise ValueError("messages must be a non-empty list of "
                             "{'role', 'content'} objects")
        ids: List[int] = []
        for i, msg in enumerate(messages):
            if not isinstance(msg, dict):
                raise ValueError(f"messages[{i}] must be an object, got {type(msg).__name__}")
            role = str(msg.get("role", ""))
            marker = self.role_token(role)
            if role == "system" and i != 0:
                raise ValueError("a system message is only valid as messages[0]")
            content = msg.get("content")
            if isinstance(content, str):
                content_ids = [int(t) for t in encode(content)]
            elif isinstance(content, (list, tuple)):
                content_ids = [int(t) for t in content]
            else:
                raise ValueError(
                    f"messages[{i}].content must be a string or a token-id "
                    f"list, got {type(content).__name__}")
            if not content_ids:
                raise ValueError(f"messages[{i}].content is empty")
            ids.append(marker)
            ids.extend(content_ids)
            ids.append(self.sep_token_id)
        if messages[-1].get("role") == "assistant":
            raise ValueError("the last message must not be from the assistant "
                             "(nothing to generate)")
        ids.append(self.assistant_token_id)  # the generation prompt
        return ids
