"""Billing-grade usage metering: one structured record per finished request.

The :class:`UsageMeter` is the request-side half of serving-cost accounting.
The goodput ledger (PR 15) is the device-side truth — every fed position
decomposed into useful/padding/spec_rejected/rework under an exact
conservation invariant — but it has no notion of *who* a token belongs to.
The meter closes that gap: when the engine loop resolves a request (normal
finish, abort, engine_error quarantine, capacity reject, shutdown), it books
exactly one usage record keyed by the request's **trace id**, so a retry or
requeue-after-rebuild that resolves the same logical request twice (or the
same request booked by two replicas across a mid-stream failover) dedups to
one bill — in-process via the seen-id set, offline via
``tools/usage_report.py``'s record-id merge.

Record fields and their reconciliation contract:

- ``prompt_tokens`` / ``cached_tokens`` / ``completion_tokens``: the billable
  client view — prompt length, prefix-cache credit (booked ONCE, at first
  admission), and every token the client actually received (the handle's
  streamed list, which survives rebuild unfolding);
- ``useful_tokens``: the engine-attributed useful fed positions for this
  request, mirroring the per-tenant goodput fold token for token — summing
  it over sealed records equals the ledger's ``useful`` total exactly when
  every booked request finished on one engine (zero slack), and undershoots
  by at most the dead engine's completed work per retried request under
  chaos (the documented slack);
- ``kv_block_seconds``: the integral of held KV blocks over wall time
  (per-step checkpoints + finalized at free), ``adapter_slot_seconds``: wall
  time holding a real adapter-pool slot — the two residency costs a
  tokens-only price table misses;
- ``spec_drafted`` / ``spec_accepted``: speculative work billed per request;
- identity + shape: tenant, adapter_id, priority, finish_reason, retries,
  arrival/finish timestamps, e2e seconds, and the PR-13 latency-attribution
  phase breakdown.

Durability is optional: with a :class:`~...observability.usage.UsageLedger`
attached every record also lands in the append-only JSONL segment store;
without one the meter still maintains the rolling aggregate that
``GET /debug/usage``, the router's ``/fleet/usage`` fold, and postmortem
bundles read. Set ``PDNLP_TPU_USAGE_DIR`` to arm durability from the
environment (the postmortem-dir pattern).
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import OrderedDict
from typing import Dict, Optional

from ...observability.usage import (RECORD_SCHEMA_VERSION, UsageLedger,
                                    empty_aggregate, fold_record)

__all__ = ["ENV_DIR", "UsageMeter"]

#: environment opt-in for the durable ledger (mirrors PDNLP_TPU_POSTMORTEM_DIR)
ENV_DIR = "PDNLP_TPU_USAGE_DIR"


class UsageMeter:
    """Per-replica usage bookkeeping: build, dedup, aggregate, persist.

    Thread-safety: records are booked on the engine-loop thread;
    :meth:`snapshot` runs on HTTP threads — one lock covers both (booking is
    per-finished-request, snapshots per-scrape: cold paths)."""

    def __init__(self, ledger: Optional[UsageLedger] = None, metrics=None,
                 max_seen_ids: int = 65536):
        self.ledger = ledger
        self.metrics = metrics
        self.max_seen_ids = int(max_seen_ids)
        self._lock = threading.Lock()
        self._seen: "OrderedDict[str, None]" = OrderedDict()
        self._agg = empty_aggregate()
        self._duplicates = 0
        self._seq = itertools.count()

    @classmethod
    def from_env(cls, metrics=None) -> "UsageMeter":
        """Meter with a durable ledger iff ``PDNLP_TPU_USAGE_DIR`` is set."""
        directory = os.environ.get(ENV_DIR, "").strip()
        ledger = UsageLedger(directory) if directory else None
        return cls(ledger=ledger, metrics=metrics)

    # ----------------------------------------------------------------- booking
    def record_finished(self, req, handle=None,
                        attribution: Optional[Dict] = None) -> Optional[Dict]:
        """Book usage for one resolved request. Returns the record, or None
        when this record id was already booked (idempotent re-resolution).
        Never raises into the engine loop: a ledger-write failure costs
        durability of one record, not the serving thread."""
        record = self._build(req, handle, attribution)
        with self._lock:
            rid = record["record_id"]
            if rid in self._seen:
                self._duplicates += 1
                return None
            self._seen[rid] = None
            while len(self._seen) > self.max_seen_ids:
                self._seen.popitem(last=False)
            fold_record(self._agg, record)
        if self.metrics is not None:
            self._count(record)
        if self.ledger is not None:
            try:
                self.ledger.append(record)
            except Exception:  # noqa: BLE001 — durability is best-effort here
                pass
        return record

    def _build(self, req, handle, attribution) -> Dict:
        trace = getattr(handle, "trace", None) or getattr(req, "trace", None)
        # engine req_ids restart per engine instance — without a trace they
        # are NOT unique over time, so mint a local id instead of deduping
        # two different requests into one bill
        record_id = trace or f"local-{next(self._seq)}"
        prompt_ids = getattr(req, "prompt_ids", None)
        n_prompt = 0 if prompt_ids is None else len(prompt_ids)
        if handle is not None:
            prompt_tokens = int(handle.prompt_len)
            # the handle's streamed list is every token the client received,
            # across preemption folds and engine rebuilds — the billing truth
            completion = len(handle._streamed)
        else:
            base = int(getattr(req, "base_prompt_len", 0) or n_prompt)
            prompt_tokens = base
            # a preemption folds generated tokens into prompt_ids: they were
            # delivered, so they bill as completion, not prompt
            completion = len(getattr(req, "output_ids", []) or []) \
                + max(n_prompt - base, 0)
        arrival_t = getattr(req, "arrival_t", None)
        finish_t = getattr(req, "finish_t", None)
        record = {
            "schema": RECORD_SCHEMA_VERSION,
            "record_id": record_id,
            "req_id": getattr(req, "req_id", -1),
            "tenant": getattr(req, "tenant", None) or "default",
            "adapter_id": getattr(req, "adapter_id", None)
            or getattr(handle, "adapter_id", None),
            "priority": getattr(req, "priority", "interactive"),
            "finish_reason": getattr(req, "finish_reason", None)
            or ("abort" if getattr(req, "aborted", False) else "unknown"),
            "retries": getattr(handle, "retries", 0) if handle is not None else 0,
            "prompt_tokens": prompt_tokens,
            "cached_tokens": int(getattr(req, "cached_tokens", 0) or 0),
            "completion_tokens": int(completion),
            "useful_tokens": int(getattr(req, "useful_tokens", 0) or 0),
            "spec_drafted": int(getattr(req, "spec_drafted", 0) or 0),
            "spec_accepted": int(getattr(req, "spec_accepted", 0) or 0),
            "kv_block_seconds": round(float(
                getattr(req, "kv_block_seconds", 0.0) or 0.0), 6),
            "adapter_slot_seconds": round(float(
                getattr(req, "adapter_slot_seconds", 0.0) or 0.0), 6),
            "arrival_t": arrival_t,
            "finish_t": finish_t,
            "e2e_s": round(finish_t - arrival_t, 6)
            if arrival_t is not None and finish_t is not None else None,
            "attribution": attribution,
        }
        if self.ledger is not None:
            record["replica"] = self.ledger.replica
        return record

    def _count(self, record: Dict):
        labels = dict(tenant=record["tenant"],
                      adapter=record["adapter_id"] or "base")
        for kind, field in (("prompt", "prompt_tokens"),
                            ("cached", "cached_tokens"),
                            ("completion", "completion_tokens")):
            if record[field]:
                self.metrics.usage_tokens.inc(record[field], kind=kind, **labels)
        self.metrics.usage_records.inc(tenant=record["tenant"])

    # ----------------------------------------------------------------- views
    def snapshot(self) -> Dict:
        """The ``GET /debug/usage`` document: rolling aggregate + ledger
        durability stats. Matches (by construction) what folding this
        replica's sealed+open segments would produce."""
        with self._lock:
            doc = {
                "tier": "serving",
                "schema": RECORD_SCHEMA_VERSION,
                "records": self._agg["records"],
                "totals": dict(self._agg["totals"]),
                "tenants": {t: dict(b) for t, b in self._agg["tenants"].items()},
                "adapters": {a: dict(b) for a, b in self._agg["adapters"].items()},
                "duplicates_suppressed": self._duplicates,
            }
        doc["ledger"] = self.ledger.stats() if self.ledger is not None else None
        return doc

    def close(self):
        """Seal the durable ledger (shutdown): sealed segments are what the
        offline aggregator merges."""
        if self.ledger is not None:
            self.ledger.close()
